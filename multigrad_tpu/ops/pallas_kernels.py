"""Hand-written Pallas TPU kernels for the hot sumstat ops.

The framework's FLOP budget is dominated by two per-particle kernels
(SURVEY §3.1: the user sumstats function inside the fused SPMD
loss-and-grad program):

* the erf-CDF binned count (the SMF estimator,
  ``/root/reference/tests/smf_example/smf_grad_descent.py:32-48``) —
  implemented here as a single-pass Pallas kernel with an **analytic
  custom VJP**, so neither forward nor backward ever materialises the
  ``(edges, N)`` cdf matrix in HBM: each particle tile is streamed
  HBM → VMEM once and reduced on-chip.  XLA's fusion of the
  ``jnp``-level formulation (:mod:`multigrad_tpu.ops.binned`) is
  already good; the Pallas version additionally
  (1) halves transcendental work in the backward pass by reusing the
  shared ``exp(-z²)`` term for all three gradients (values, edges,
  sigma) instead of differentiating through ``erf``, and
  (2) pins the accumulator layout so counts never round-trip to HBM
  between tiles.

* the pairwise-distance bin count (the wp(rp)/ξ(r) estimator,
  :mod:`multigrad_tpu.ops.pairwise`) — Pallas version in
  :func:`pair_counts_pallas`: the ``(tile, tile)`` separation block
  lives only in VMEM while *all* radial bins are histogrammed from it,
  instead of re-masking the block per bin.  Coordinates are fed in
  both row ``(N, 1)`` and column ``(1, N)`` layouts so the pair-block
  broadcast is a native sublane×lane outer product — no relayouts.

Both kernels run in interpret mode off-TPU (tests exercise them on
CPU; ``interpret=None`` auto-detects), and both are wrapped in
``jax.custom_vjp`` so they compose with the framework's two-stage
chain rule exactly like their XLA counterparts.

Kernel-design references: ``/opt/skills/guides/pallas_guide.md``
(grid/accumulator patterns, tiling constraints, custom-VJP pattern).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..parallel._shard_map_compat import vma_of as _vma_of

_SQRT2 = 1.4142135623730951
_INV_SQRT_PI = 0.5641895835477563

# Padding sentinel for the particle axis.  A particle at 1e18 has
# cdf == 1 at every finite edge (all bin diffs 0) and z² overflows to
# inf so exp(-z²) == 0 — forward and backward contributions are
# exactly zero.  (Same reasoning as ops.binned._PAD_CLIP.)
_PAD_VALUE = 1e18

_LANES = 128
_SUBLANES = 8
_MIN_TILE = _LANES * _SUBLANES  # particle tiles are (8, block//8)


def _out_struct(shape, *operands):
    """ShapeDtypeStruct whose varying-manual-axes (vma) type is the
    union of the operands' — required for pallas_call under
    ``shard_map`` (jax ≥0.7 tracks vma; a kernel's outputs vary over
    whatever mesh axes its inputs do)."""
    vma = frozenset()
    for x in operands:
        vma |= _vma_of(x)
    try:
        return jax.ShapeDtypeStruct(shape, jnp.float32, vma=vma)
    except TypeError:  # older jax: no vma kwarg
        return jax.ShapeDtypeStruct(shape, jnp.float32)


def _unify_vma(*arrays):
    """Lift every operand to the union of their varying-manual-axes.

    Under ``shard_map`` some kernel inputs are replicated (bin edges,
    sigma) and some device-varying (the shard's particles); mixing
    them inside a kernel is a vma type error, so replicated operands
    are pcast to varying over the missing axes first (a no-op outside
    shard_map)."""
    from ..parallel._shard_map_compat import pvary

    union = frozenset()
    for a in arrays:
        union |= _vma_of(a)
    if not union:
        return arrays
    out = []
    for a in arrays:
        missing = tuple(sorted(union - _vma_of(a)))
        out.append(pvary(a, missing) if missing else a)
    return tuple(out)


def _match_vma(ct, primal):
    """Cast a cotangent to its primal's varying-manual-axes type.

    A custom_vjp is opaque to shard_map's transpose machinery, so the
    backward must do what the automatic transpose would: sum shard
    contributions (psum) for cotangents of *replicated* primals (the
    reference's explicit allreduce of partial gradients,
    ``multigrad.py:531-532``), and mark zeros for varying primals as
    varying."""
    from ..parallel._shard_map_compat import pvary

    want, have = _vma_of(primal), _vma_of(ct)
    extra = tuple(sorted(have - want))
    if extra:
        ct = jax.lax.psum(ct, extra)
    missing = tuple(sorted(want - _vma_of(ct)))
    if missing:
        ct = pvary(ct, missing)
    return ct


def _lane_onehot_sum(scalars, dtype=jnp.float32):
    """(1, 128) row with ``scalars[k]`` in lane k, rest zero.

    Mosaic has no scatter; a small unrolled Σ_k s_k·[lane == k] builds
    the accumulator update as pure vector ops instead.
    """
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, _LANES), 1)
    out = jnp.zeros((1, _LANES), dtype)
    for k, s in enumerate(scalars):
        out = out + jnp.where(lane == k, s, 0.0).astype(dtype)
    return out


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def _auto_interpret(interpret):
    """Resolve the user-facing ``interpret`` flag.

    Off-TPU (or on explicit request) kernels run in TPU interpret
    mode.  ``InterpretParams`` (not plain ``True``) is used where it
    exists (jax >= 0.7) because the HLO interpreter's internal block
    indexing is incompatible with ``shard_map``'s vma type checking;
    pre-vma jax has neither the class nor the type checking, so plain
    ``True`` is the correct interpret flag there."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if interpret is True and hasattr(pltpu, "InterpretParams"):
        return pltpu.InterpretParams()
    return interpret


def _use_jnp_emulation(interpret, *operands):
    """True when the kernel should be emulated with plain jnp ops.

    Interpret-mode kernels simulate the TPU core tile-by-tile and are
    orders of magnitude slower than compiled jnp, so when interpret
    resolution is *automatic* (``interpret=None``) a CPU mesh — which
    exists only to simulate TPU topologies in CI (SURVEY §4) — runs
    mathematically identical jnp instead.  An **explicit**
    ``interpret=True`` overrides the emulation and runs the genuine
    ``pallas_call`` interpret kernel even under a mesh axis
    (tests/test_pallas_shardmap.py uses this to exercise the real
    kernel + vma machinery under ``shard_map``).  Compiled Mosaic is
    used on real chips either way."""
    if interpret is not None or not _auto_interpret(interpret):
        return False
    return any(_vma_of(x) for x in operands)


# XLA's float32 erf rational approximation (the polynomial XLA itself
# lowers lax.erf to for f32) — Mosaic has no erf primitive, so we
# inline the same clamp + P(x²)/Q(x²) form and match the XLA path's
# numerics.  Max error vs exact erf ~1 ulp f32 on [-4, 4], saturated
# (±1 within f32) outside.
_ERF_ALPHA = (-2.72614225801306e-10, 2.77068142495902e-08,
              -2.10102402082508e-06, -5.69250639462346e-05,
              -7.34990630326855e-04, -2.95459980854025e-03,
              -1.60960333262415e-02)
_ERF_BETA = (-1.45660718464996e-05, -2.13374055278905e-04,
             -1.68282697438203e-03, -7.37332916720468e-03,
             -1.42647390514189e-02)


def _erf_f32(x):
    x = jnp.clip(x, -4.0, 4.0)
    x2 = x * x
    alpha = jnp.float32(_ERF_ALPHA[0])
    for c in _ERF_ALPHA[1:]:
        alpha = alpha * x2 + jnp.float32(c)
    beta = jnp.float32(_ERF_BETA[0])
    for c in _ERF_BETA[1:]:
        beta = beta * x2 + jnp.float32(c)
    return x * alpha / beta


# ---------------------------------------------------------------------------
# Binned erf-CDF counts (the SMF hot op)
# ---------------------------------------------------------------------------


def _make_erf_fwd_kernel(n_edges, vec_sigma=False):
    """Forward tile kernel: accumulate per-bin smoothed counts.

    The particle tile is an (8, L) VMEM block; the (small, static)
    edge loop is unrolled, so every op is a well-tiled 2D vector op.
    cdf differences are taken per particle before the tile reduction
    (diff-then-sum — see ops/binned.py precision note).

    With ``vec_sigma`` the smoothing width varies per particle
    (mass-dependent scatter): ``inv`` arrives as an (8, L) VMEM tile
    riding alongside the values instead of an SMEM scalar — the z
    computation is elementwise either way, so the kernel body is
    identical up to the broadcast.
    """

    def kernel(edges_ref, inv_ref, vals_ref, out_ref):
        @pl.when(pl.program_id(0) == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        inv = inv_ref[:] if vec_sigma else inv_ref[0, 0]  # 1 / (√2 σ)
        vals = vals_ref[:]                           # (8, L)
        edges = edges_ref[:]                         # (EP, 1)
        # Streaming diff: only two cdf blocks live at a time, so VMEM
        # use is O(L), independent of the bin count.
        prev = 0.5 * (1.0 + _erf_f32((edges[0, 0] - vals) * inv))
        per_bin = []
        for e in range(1, n_edges):
            cur = 0.5 * (1.0 + _erf_f32((edges[e, 0] - vals) * inv))
            per_bin.append(jnp.sum(cur - prev))
            prev = cur
        out_ref[:] += _lane_onehot_sum(per_bin, vals.dtype)

    return kernel


def _make_erf_bwd_kernel(n_edges, vec_sigma=False):
    """Backward tile: all three gradients from one shared exp(-z²).

    With ``J = Σ_b g_b · counts_b = Σ_{e,i} h_e · cdf(z_{e,i})``
    (``h_e = g_{e-1} - g_e``), and ``P = exp(-z²)``:

      dJ/dv_i = -(inv/√π) Σ_e h_e P_{e,i}
      dJ/dσ   = -(1/(σ√π)) Σ_{e,i} h_e P z         (scalar)
      dJ/de_e =  (inv/√π) h_e Σ_i P_{e,i}          (row sums)

    The kernel emits the raw reductions; constant factors are applied
    host-side.  acc row 0 = per-edge P sums, acc[1, 0] = Σ h·P·z.

    Per-particle sigma (``vec_sigma``) changes only which reductions
    survive to outputs:

      dJ/dv_i = -(inv_i/√π) Σ_e h_e P_{e,i}        (same, inv per i)
      dJ/dσ_i = -(1/(σ_i√π)) Σ_e h_e P_{e,i} z_{e,i}  (per-particle —
                an (8, L) tile like dv, not a scalar)
      dJ/de_e =  (1/√π) h_e Σ_i inv_i P_{e,i}      (inv-weighted rows)
    """

    def kernel(edges_ref, inv_ref, h_ref, vals_ref, dv_ref, psum_ref,
               hpz_ref):
        @pl.when(pl.program_id(0) == 0)
        def _():
            psum_ref[:] = jnp.zeros_like(psum_ref)
            if not vec_sigma:
                hpz_ref[:] = jnp.zeros_like(hpz_ref)

        inv = inv_ref[:] if vec_sigma else inv_ref[0, 0]
        vals = vals_ref[:]                           # (8, L)
        edges = edges_ref[:]
        h = h_ref[:]                                 # (1, EP)

        dv = jnp.zeros_like(vals)
        p_sums = []
        hpz = (jnp.zeros_like(vals) if vec_sigma
               else jnp.zeros((), vals.dtype))
        for e in range(n_edges):
            z = (edges[e, 0] - vals) * inv
            p = jnp.exp(-(z * z))
            dv = dv + h[0, e] * p
            if vec_sigma:
                # dedges needs the inv-weighted row sums; dsigma is a
                # per-particle tile accumulated across edges.
                p_sums.append(jnp.sum(inv * p))
                hpz = hpz + h[0, e] * (p * z)
            else:
                p_sums.append(jnp.sum(p))
                hpz = hpz + h[0, e] * jnp.sum(p * z)

        dv_ref[:] = dv                               # scaled on host
        psum_ref[:] += _lane_onehot_sum(p_sums, vals.dtype)
        if vec_sigma:
            hpz_ref[:] = hpz
        else:
            hpz_ref[:] += _lane_onehot_sum([hpz], vals.dtype)

    return kernel


def _erf_prep(values, bin_edges, sigma, block_size):
    """Pad particles (neutral sentinel) and reshape to (8, L) tiles.

    ``inv`` comes back as a (1, 1) scalar for scalar sigma, or padded
    + tiled exactly like ``vals`` for per-particle sigma (pad value 1:
    padded particles sit at the ±1e18 sentinel where exp(-z²) is an
    exact 0 for any finite inv, so the pad sigma is inert — it only
    has to be finite and nonzero to keep z well-defined).
    """
    # Clip caller-supplied ±inf (e.g. the framework's inf padding) to
    # the finite sentinel: at ±1e18 the forward cdf still saturates
    # exactly, while the backward z stays finite so p·z terms are 0
    # instead of 0·inf = NaN (same reasoning as binned._PAD_CLIP).
    values = jnp.clip(jnp.asarray(values, jnp.float32),
                      -_PAD_VALUE, _PAD_VALUE)
    edges = jnp.asarray(bin_edges, jnp.float32)
    n, n_edges = values.shape[0], edges.shape[0]
    n_pad = _round_up(max(n, 1), block_size)
    lanes = block_size // _SUBLANES
    vals = jnp.pad(values, (0, n_pad - n), constant_values=_PAD_VALUE)
    vals = vals.reshape(n_pad // lanes, lanes)
    ep = _round_up(n_edges, _SUBLANES)
    edges_p = jnp.pad(edges, (0, ep - n_edges), mode="edge")
    inv = 1.0 / (_SQRT2 * jnp.asarray(sigma, jnp.float32))
    if jnp.ndim(sigma) > 0:
        inv = jnp.pad(inv, (0, n_pad - n), constant_values=1.0)
        inv = inv.reshape(n_pad // lanes, lanes)
    else:
        inv = inv.reshape(1, 1)
    return vals, edges_p.reshape(ep, 1), inv, n_pad, ep


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _erf_counts_core(block_size, interpret, values, bin_edges, sigma):
    counts, _ = _erf_counts_fwd(block_size, interpret, values,
                                bin_edges, sigma)
    return counts


def _erf_counts_fwd(block_size, interpret, values, bin_edges, sigma):
    n_edges = bin_edges.shape[0]
    vec = jnp.ndim(sigma) > 0
    vals, edges_p, inv, n_pad, ep = _erf_prep(values, bin_edges, sigma,
                                              block_size)
    edges_p, inv, vals = _unify_vma(edges_p, inv, vals)
    if _use_jnp_emulation(interpret, values, sigma):
        flat = vals.reshape(1, n_pad)
        inv_b = inv.reshape(1, n_pad) if vec else inv[0, 0]
        cdf = 0.5 * (1.0 + _erf_f32(
            (edges_p[:n_edges] - flat) * inv_b))        # (E, n_pad)
        counts = jnp.sum(jnp.diff(cdf, axis=0), axis=1)
        return counts, (values, bin_edges, sigma)
    lanes = block_size // _SUBLANES
    tile_spec = pl.BlockSpec((_SUBLANES, lanes), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    inv_spec = tile_spec if vec else pl.BlockSpec(
        (1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)
    out = pl.pallas_call(
        _make_erf_fwd_kernel(n_edges, vec),
        grid=(n_pad // block_size,),
        in_specs=[
            pl.BlockSpec((ep, 1), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            inv_spec,
            tile_spec,
        ],
        out_specs=pl.BlockSpec((1, _LANES), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_out_struct((1, _LANES), vals, inv),
        interpret=_auto_interpret(interpret),
        cost_estimate=pl.CostEstimate(
            flops=6 * n_edges * n_pad, bytes_accessed=4 * n_pad,
            transcendentals=n_edges * n_pad),
    )(edges_p, inv, vals)
    counts = out[0, : n_edges - 1]
    return counts, (values, bin_edges, sigma)


def _erf_counts_bwd(block_size, interpret, residuals, g):
    values, bin_edges, sigma = residuals
    n = values.shape[0]
    n_edges = bin_edges.shape[0]
    vec = jnp.ndim(sigma) > 0
    vals, edges_p, inv, n_pad, ep = _erf_prep(values, bin_edges, sigma,
                                              block_size)
    g = jnp.asarray(g, jnp.float32)
    # h_e = g_{e-1} - g_e  (g_{-1} = g_B = 0), padded to the edge tile.
    h = jnp.pad(g, (1, 0)) - jnp.pad(g, (0, 1))
    h = jnp.pad(h, (0, ep - n_edges)).reshape(1, ep)
    edges_p, inv, h, vals = _unify_vma(edges_p, inv, h, vals)

    sqrt_pi = jnp.sqrt(jnp.float32(jnp.pi))
    if _use_jnp_emulation(interpret, values, sigma):
        flat = vals.reshape(1, n_pad)
        inv_b = inv.reshape(1, n_pad) if vec else inv[0, 0]
        z = (edges_p[:n_edges] - flat) * inv_b          # (E, n_pad)
        p = jnp.exp(-(z * z))
        dv_raw = (h[:, :n_edges] @ p).reshape(
            n_pad // (block_size // _SUBLANES), -1)
        psum = jnp.pad(jnp.sum((inv_b * p) if vec else p, axis=1)[None],
                       ((0, 0), (0, _LANES - n_edges)))
        if vec:
            ds_raw = (h[:, :n_edges] @ (p * z)).reshape(dv_raw.shape)
        else:
            hpz = jnp.sum(h[0, :n_edges] * jnp.sum(p * z, axis=1))
            ds_raw = jnp.pad(hpz.reshape(1, 1),
                             ((0, 0), (0, _LANES - 1)))
    else:
        dv_raw, psum, ds_raw = _erf_bwd_pallas_call(
            block_size, interpret, n_edges, n_pad, ep, edges_p, inv,
            h, vals, vec)

    if vec:
        inv_flat = inv.reshape(n_pad)[:n]
        sigma_f = jnp.asarray(sigma, jnp.float32)
        dvalues = (-(inv_flat * _INV_SQRT_PI)
                   * dv_raw.reshape(n_pad)[:n]).astype(values.dtype)
        # psum rows already carry the per-particle inv weights.
        dedges = _INV_SQRT_PI * h[0, :n_edges] * psum[0, :n_edges]
        dsigma = -(ds_raw.reshape(n_pad)[:n] / (sigma_f * sqrt_pi))
        dsigma = dsigma.astype(jnp.result_type(sigma))
    else:
        sigma_f = jnp.asarray(sigma, jnp.float32)
        inv_s = inv[0, 0]
        dvalues = (-(inv_s * _INV_SQRT_PI)
                   * dv_raw.reshape(n_pad)[:n]).astype(values.dtype)
        dedges = (inv_s * _INV_SQRT_PI) * h[0, :n_edges] \
            * psum[0, :n_edges]
        dsigma = -(ds_raw[0, 0] / (sigma_f * sqrt_pi))
        dsigma = jnp.asarray(dsigma, jnp.float32).reshape(
            jnp.shape(sigma))
    return (_match_vma(dvalues, values),
            _match_vma(dedges.astype(jnp.result_type(bin_edges)),
                       bin_edges),
            _match_vma(dsigma, sigma))


def _erf_bwd_pallas_call(block_size, interpret, n_edges, n_pad, ep,
                         edges_p, inv, h, vals, vec=False):
    lanes = block_size // _SUBLANES
    tile_spec = pl.BlockSpec((_SUBLANES, lanes), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    inv_spec = tile_spec if vec else pl.BlockSpec(
        (1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)
    # Third output: per-particle dsigma tile (vec) or the Σ h·P·z
    # scalar in lane 0 (scalar sigma).
    ds_spec = tile_spec if vec else pl.BlockSpec(
        (1, _LANES), lambda i: (0, 0), memory_space=pltpu.VMEM)
    ds_shape = (n_pad // lanes, lanes) if vec else (1, _LANES)
    return pl.pallas_call(
        _make_erf_bwd_kernel(n_edges, vec),
        grid=(n_pad // block_size,),
        in_specs=[
            pl.BlockSpec((ep, 1), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            inv_spec,
            pl.BlockSpec((1, ep), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            tile_spec,
        ],
        out_specs=(
            tile_spec,
            pl.BlockSpec((1, _LANES), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            ds_spec,
        ),
        out_shape=(
            _out_struct((n_pad // lanes, lanes), vals, inv, h),
            _out_struct((1, _LANES), vals, inv, h),
            _out_struct(ds_shape, vals, inv, h),
        ),
        interpret=_auto_interpret(interpret),
        cost_estimate=pl.CostEstimate(
            flops=8 * n_edges * n_pad, bytes_accessed=8 * n_pad,
            transcendentals=n_edges * n_pad),
    )(edges_p, inv, h, vals)


_erf_counts_core.defvjp(_erf_counts_fwd, _erf_counts_bwd)


def binned_erf_counts_pallas(values, bin_edges, sigma,
                             block_size: int = 32768,
                             interpret: bool | None = None):
    """Pallas TPU smoothed histogram — drop-in for
    :func:`multigrad_tpu.ops.binned.binned_erf_counts`.

    Each particle contributes ``cdf(edge_hi) - cdf(edge_lo)`` per bin
    (reference semantics, ``smf_grad_descent.py:38-48``).  Fully
    differentiable wrt ``values``, ``bin_edges`` and ``sigma`` via the
    analytic VJP above.

    Parameters
    ----------
    values : (N,) array
    bin_edges : (B+1,) array, ``B + 1 <= 128``
    sigma : scalar or (N,) array
        Gaussian smoothing width — a scalar, or one width per particle
        (mass-dependent scatter).  The per-particle path streams the
        widths as a second (8, L) VMEM tile alongside the values; the
        cost over scalar sigma is one extra HBM read of N floats per
        pass.
    block_size : int
        Particle-tile size (multiple of 1024); VMEM working set is
        ``O(block_size)`` per live cdf block.
    interpret : bool, optional
        Force Pallas interpret mode; default auto (True off-TPU).
    """
    if jnp.ndim(sigma) > 1 or (
            jnp.ndim(sigma) == 1
            and jnp.shape(sigma) != jnp.shape(values)):
        raise ValueError(
            f"sigma must be a scalar or match values' shape "
            f"{jnp.shape(values)}, got {jnp.shape(sigma)}")
    if jnp.shape(bin_edges)[0] > _LANES:
        raise ValueError(f"at most {_LANES} bin edges supported")
    if block_size % _MIN_TILE:
        raise ValueError(f"block_size must be a multiple of {_MIN_TILE}")
    return _erf_counts_core(block_size, interpret, values,
                            jnp.asarray(bin_edges), sigma)


# ---------------------------------------------------------------------------
# Fused (windowed scatter-into-bins) erf-CDF counts
# ---------------------------------------------------------------------------


def _make_fused_fwd_kernel(window, vec_sigma=False):
    """Forward windowed-mass tile kernel.

    The particle tile is an (8, L) VMEM block; its ``window`` gathered
    edge rows arrive as a (W, 8, L) block (one row per window slot,
    prepared by an XLA gather — Mosaic has no per-element gather, and
    the window offsets are data-dependent).  The kernel streams the
    edge rows exactly like the dense kernel streams static edges: two
    live cdf blocks, per-particle diff, masses written back per slot.
    The scatter-add into bins happens host-side
    (:func:`multigrad_tpu.ops.binned.scatter_bin_masses` — a row-wise
    ``segment_sum`` XLA lowers well); the transcendental-heavy windowed
    cdf work and its analytic VJP live here.
    """

    def kernel(inv_ref, vals_ref, ewin_ref, out_ref):
        inv = inv_ref[:] if vec_sigma else inv_ref[0, 0]  # 1 / (√2 σ)
        vals = vals_ref[:]                           # (8, L)
        prev = 0.5 * (1.0 + _erf_f32((ewin_ref[0] - vals) * inv))
        for w in range(1, window):
            cur = 0.5 * (1.0 + _erf_f32((ewin_ref[w] - vals) * inv))
            out_ref[w - 1] = cur - prev
            prev = cur

    return kernel


def _make_fused_bwd_kernel(window, vec_sigma=False):
    """Backward windowed tile: all three gradients from one exp(-z²).

    Same algebra as the dense backward kernel restricted to the
    window (``h_e = g_{e-1} - g_e`` with the boundary terms zero), but
    the edge cotangent is *per particle-slot* (``dewin``) — the
    scatter of those back onto the shared edge vector is the
    transpose of the host-side gather, handled by XLA.
    """

    def kernel(inv_ref, vals_ref, ewin_ref, g_ref, dv_ref, dew_ref,
               ds_ref):
        if not vec_sigma:
            @pl.when(pl.program_id(0) == 0)
            def _():
                ds_ref[:] = jnp.zeros_like(ds_ref)

        inv = inv_ref[:] if vec_sigma else inv_ref[0, 0]
        vals = vals_ref[:]                           # (8, L)
        dv = jnp.zeros_like(vals)
        hz = jnp.zeros_like(vals) if vec_sigma \
            else jnp.zeros((), vals.dtype)
        for e in range(window):
            z = (ewin_ref[e] - vals) * inv
            p = jnp.exp(-(z * z))
            if e == 0:
                h = -g_ref[0]
            elif e == window - 1:
                h = g_ref[window - 2]
            else:
                h = g_ref[e - 1] - g_ref[e]
            hp = h * p
            dv = dv + hp
            dew_ref[e] = (inv * _INV_SQRT_PI) * hp
            hz = hz + (hp * z if vec_sigma else jnp.sum(hp * z))
        dv_ref[:] = -(inv * _INV_SQRT_PI) * dv
        if vec_sigma:
            # -(1/(σ√π)) = -inv·√2/√π
            ds_ref[:] = -(inv * _SQRT2 * _INV_SQRT_PI) * hz
        else:
            ds_ref[:] += _lane_onehot_sum([hz], vals.dtype)

    return kernel


def _fused_prep(values, ewin, sigma, window, block_size):
    """Pad + tile (vals, inv, ewin) for the fused kernels.

    vals/inv tile exactly like :func:`_erf_prep`; the per-particle
    edge windows transpose to (W, rows, lanes) so each grid step sees
    a (W, 8, L) block.  Pad edge value 0.0 is inert: padded particles
    sit at the ±1e18 sentinel where exp(-z²) is an exact 0.
    """
    values = jnp.clip(jnp.asarray(values, jnp.float32),
                      -_PAD_VALUE, _PAD_VALUE)
    n = values.shape[0]
    n_pad = _round_up(max(n, 1), block_size)
    lanes = block_size // _SUBLANES
    vals = jnp.pad(values, (0, n_pad - n), constant_values=_PAD_VALUE)
    vals = vals.reshape(n_pad // lanes, lanes)
    ew = jnp.asarray(ewin, jnp.float32)
    ew = jnp.pad(ew, ((0, n_pad - n), (0, 0)))
    ew = ew.T.reshape(window, n_pad // lanes, lanes)
    inv = 1.0 / (_SQRT2 * jnp.asarray(sigma, jnp.float32))
    if jnp.ndim(sigma) > 0:
        inv = jnp.pad(inv, (0, n_pad - n), constant_values=1.0)
        inv = inv.reshape(n_pad // lanes, lanes)
    else:
        inv = inv.reshape(1, 1)
    return vals, ew, inv, n_pad, lanes


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _fused_masses_core(block_size, interpret, window, values, ewin,
                       sigma):
    masses, _ = _fused_masses_fwd(block_size, interpret, window,
                                  values, ewin, sigma)
    return masses


def _fused_masses_fwd(block_size, interpret, window, values, ewin,
                      sigma):
    vec = jnp.ndim(sigma) > 0
    residuals = (values, ewin, sigma)
    if _use_jnp_emulation(interpret, values, sigma):
        v = jnp.clip(jnp.asarray(values, jnp.float32),
                     -_PAD_VALUE, _PAD_VALUE)
        inv = 1.0 / (_SQRT2 * jnp.asarray(sigma, jnp.float32))
        inv = inv[:, None] if vec else inv
        cdf = 0.5 * (1.0 + _erf_f32((ewin - v[:, None]) * inv))
        return jnp.diff(cdf, axis=1), residuals
    n = values.shape[0]
    vals, ew, inv, n_pad, lanes = _fused_prep(values, ewin, sigma,
                                              window, block_size)
    ew, inv, vals = _unify_vma(ew, inv, vals)
    tile_spec = pl.BlockSpec((_SUBLANES, lanes), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    inv_spec = tile_spec if vec else pl.BlockSpec(
        (1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)
    out = pl.pallas_call(
        _make_fused_fwd_kernel(window, vec),
        grid=(n_pad // block_size,),
        in_specs=[
            inv_spec,
            tile_spec,
            pl.BlockSpec((window, _SUBLANES, lanes),
                         lambda i: (0, i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((window - 1, _SUBLANES, lanes),
                               lambda i: (0, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_out_struct((window - 1, n_pad // lanes, lanes),
                              vals, inv, ew),
        interpret=_auto_interpret(interpret),
        cost_estimate=pl.CostEstimate(
            flops=6 * window * n_pad,
            bytes_accessed=4 * (window + 1) * n_pad,
            transcendentals=window * n_pad),
    )(inv, vals, ew)
    masses = out.reshape(window - 1, n_pad).T[:n]
    return masses, residuals


def _fused_masses_bwd(block_size, interpret, window, residuals, g):
    values, ewin, sigma = residuals
    vec = jnp.ndim(sigma) > 0
    n = values.shape[0]
    g = jnp.asarray(g, jnp.float32)
    sigma_f = jnp.asarray(sigma, jnp.float32)
    if _use_jnp_emulation(interpret, values, sigma):
        v = jnp.clip(jnp.asarray(values, jnp.float32),
                     -_PAD_VALUE, _PAD_VALUE)
        inv = 1.0 / (_SQRT2 * sigma_f)                   # scalar | (N,)
        inv_b = inv[:, None] if vec else inv
        z = (ewin - v[:, None]) * inv_b                  # (N, W)
        p = jnp.exp(-(z * z))
        h = jnp.pad(g, ((0, 0), (1, 0))) \
            - jnp.pad(g, ((0, 0), (0, 1)))               # (N, W)
        hp = h * p
        dvalues = -(inv * _INV_SQRT_PI) * jnp.sum(hp, axis=1)
        dewin = (inv_b * _INV_SQRT_PI) * hp
        hz = jnp.sum(hp * z, axis=1)                     # (N,)
        sqrt_pi = jnp.sqrt(jnp.float32(jnp.pi))
        dsigma = -(hz / (sigma_f * sqrt_pi)) if vec \
            else -(jnp.sum(hz) / (sigma_f * sqrt_pi))
    else:
        vals, ew, inv, n_pad, lanes = _fused_prep(
            values, ewin, sigma, window, block_size)
        g_pad = jnp.pad(g, ((0, n_pad - n), (0, 0)))
        g_t = g_pad.T.reshape(window - 1, n_pad // lanes, lanes)
        ew, inv, vals, g_t = _unify_vma(ew, inv, vals, g_t)
        tile_spec = pl.BlockSpec((_SUBLANES, lanes), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)
        inv_spec = tile_spec if vec else pl.BlockSpec(
            (1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)
        ds_spec = tile_spec if vec else pl.BlockSpec(
            (1, _LANES), lambda i: (0, 0), memory_space=pltpu.VMEM)
        ds_shape = (n_pad // lanes, lanes) if vec else (1, _LANES)
        dv, dew, ds = pl.pallas_call(
            _make_fused_bwd_kernel(window, vec),
            grid=(n_pad // block_size,),
            in_specs=[
                inv_spec,
                tile_spec,
                pl.BlockSpec((window, _SUBLANES, lanes),
                             lambda i: (0, i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((window - 1, _SUBLANES, lanes),
                             lambda i: (0, i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=(
                tile_spec,
                pl.BlockSpec((window, _SUBLANES, lanes),
                             lambda i: (0, i, 0),
                             memory_space=pltpu.VMEM),
                ds_spec,
            ),
            out_shape=(
                _out_struct((n_pad // lanes, lanes), vals, inv, ew,
                            g_t),
                _out_struct((window, n_pad // lanes, lanes), vals,
                            inv, ew, g_t),
                _out_struct(ds_shape, vals, inv, ew, g_t),
            ),
            interpret=_auto_interpret(interpret),
            cost_estimate=pl.CostEstimate(
                flops=10 * window * n_pad,
                bytes_accessed=4 * (3 * window + 2) * n_pad,
                transcendentals=window * n_pad),
        )(inv, vals, ew, g_t)
        dvalues = dv.reshape(n_pad)[:n]
        dewin = dew.reshape(window, n_pad).T[:n]
        if vec:
            # -(1/(σ√π)) scaling applied in-kernel (per-particle inv).
            dsigma = ds.reshape(n_pad)[:n]
        else:
            inv_s = inv[0, 0]
            dsigma = -(ds[0, 0] * inv_s * _SQRT2 * _INV_SQRT_PI)
    dvalues = dvalues.astype(jnp.result_type(values))
    dsigma = jnp.asarray(dsigma, jnp.float32).reshape(jnp.shape(sigma))
    dsigma = dsigma.astype(jnp.result_type(sigma))
    return (_match_vma(dvalues, values),
            _match_vma(dewin.astype(jnp.result_type(ewin)), ewin),
            _match_vma(dsigma, sigma))


_fused_masses_core.defvjp(_fused_masses_fwd, _fused_masses_bwd)


def binned_erf_counts_fused_pallas(values, bin_edges, sigma,
                                   window: int,
                                   block_size: int = 32768,
                                   interpret: bool | None = None):
    """Fused (windowed scatter-into-bins) Pallas smoothed histogram.

    Pallas twin of the XLA ``bin_mode="fused"`` path
    (:func:`multigrad_tpu.ops.binned.binned_erf_counts`): each
    particle's cdf is evaluated at only ``window`` consecutive edges
    around its value (f32-exact outside — see
    :data:`multigrad_tpu.ops.binned.SAT_Z`), with the windowed-mass
    computation and its analytic VJP in a Pallas kernel (no
    ``(N, W)`` cdf residuals — the backward recomputes exp(-z²) on
    the fly) and the scatter-add of masses into bins as a row-wise
    ``segment_sum`` on the XLA side, where it lowers well.  No edge-
    count cap: unlike the dense kernel's (1, 128) lane accumulator,
    any number of bins is supported (``window <= 128`` instead).

    Fully differentiable wrt ``values``, ``bin_edges`` and ``sigma``
    (the edge cotangent rides the gather transpose).
    """
    from .binned import scatter_bin_masses, window_starts

    if jnp.ndim(sigma) > 1 or (
            jnp.ndim(sigma) == 1
            and jnp.shape(sigma) != jnp.shape(values)):
        raise ValueError(
            f"sigma must be a scalar or match values' shape "
            f"{jnp.shape(values)}, got {jnp.shape(sigma)}")
    if block_size % _MIN_TILE:
        raise ValueError(f"block_size must be a multiple of {_MIN_TILE}")
    edges = jnp.asarray(bin_edges)
    n_edges = edges.shape[0]
    window = int(min(window, n_edges))
    if not 2 <= window <= _LANES:
        raise ValueError(f"window must be in [2, {_LANES}], "
                         f"got {window}")
    values_c = jnp.clip(jnp.asarray(values, jnp.float32),
                        -_PAD_VALUE, _PAD_VALUE)
    start = window_starts(values_c, edges, sigma, window)
    offs = start[:, None] + jnp.arange(window, dtype=jnp.int32)[None, :]
    ewin = edges[offs]                                   # (N, W)
    masses = _fused_masses_core(block_size, interpret, window,
                                values, ewin, sigma)
    return scatter_bin_masses(masses, start, n_edges)


# ---------------------------------------------------------------------------
# Pairwise-distance bin counts (the wp(rp)/xi hot op)
# ---------------------------------------------------------------------------


def _pair_sep_block(rows, cols, use_box, projected, box, pimax):
    """(T, T) squared separations + π-cut mask for one pair block.

    ``rows``/``cols`` are per-coordinate (T, 1) / (1, T) blocks, so
    each ``rows[c] - cols[c]`` is a native outer-product broadcast.
    """
    diffs = []
    for c in range(3):
        d = rows[c] - cols[c]
        if use_box:
            d = d - box * jnp.round(d / box)
        diffs.append(d)
    if projected:
        sep_sq = diffs[0] * diffs[0] + diffs[1] * diffs[1]
        pi_ok = jnp.abs(diffs[2]) < pimax
    else:
        sep_sq = (diffs[0] * diffs[0] + diffs[1] * diffs[1]
                  + diffs[2] * diffs[2])
        pi_ok = None
    return sep_sq, pi_ok


def _make_pair_fwd_kernel(n_bins, use_box, projected):
    """Forward pair-block kernel: all bins from one VMEM sep² block.

    For each radial bin the masked weight product is reduced as
    ``w1 · (M @ w2)`` (matvec on the MXU); the ``(T, T)`` separation
    block is computed once and reused for every bin, instead of the
    XLA path's bin-by-bin refusion.
    """

    def kernel(edges_sq_ref, meta_ref, x1_ref, y1_ref, z1_ref, w1_ref,
               x2_ref, y2_ref, z2_ref, w2_ref, out_ref):
        @pl.when((pl.program_id(0) == 0) & (pl.program_id(1) == 0))
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        sep_sq, pi_ok = _pair_sep_block(
            (x1_ref[:], y1_ref[:], z1_ref[:]),
            (x2_ref[:], y2_ref[:], z2_ref[:]),
            use_box, projected, meta_ref[0], meta_ref[1])
        esq = edges_sq_ref[:]                        # (EP, 1)
        w1 = w1_ref[:]                               # (1, T) rows=i
        w2 = w2_ref[:]                               # (1, T) cols=j

        partial_counts = []
        for b in range(n_bins):                      # static unroll
            mask = (sep_sq >= esq[b, 0]) & (sep_sq < esq[b + 1, 0])
            if projected:
                mask = mask & pi_ok
            # mw2[0, i] = Σ_j mask_ij w2_j ; count = Σ_i w1_i mw2_i —
            # both as (1,T)-layout dot_generals (no transposes).
            mw2 = jax.lax.dot_general(
                w2, mask.astype(jnp.float32), (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            cnt = jax.lax.dot_general(
                w1, mw2, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            partial_counts.append(cnt[0, 0])
        out_ref[:] += _lane_onehot_sum(partial_counts)

    return kernel


def _make_pair_bwd_kernel(n_bins, use_box, projected):
    """Backward pair block: the *row-side* weight gradient
    ``dJ/dw1_i = Σ_j G_ij w2_j``, where ``G_ij = Σ_b g_b [pair ij in
    bin b]`` is the cotangent-weighted combined mask — built
    bin-by-bin in VMEM, applied as one matvec.

    Only the row gradient is emitted: its output block follows the
    row grid index, so accumulation over the column axis happens on
    consecutive grid steps (a revisited output block would be stale —
    Pallas outputs are write-only).  The column-side gradient is the
    same kernel with the two particle sets swapped (the pair masks
    are symmetric), dispatched as a second call by :func:`_pair_bwd`.
    """

    def kernel(edges_sq_ref, meta_ref, x1_ref, y1_ref, z1_ref, w1_ref,
               x2_ref, y2_ref, z2_ref, w2_ref, g_ref, dw1_ref):
        del w1_ref  # row weights don't enter their own gradient
        sep_sq, pi_ok = _pair_sep_block(
            (x1_ref[:], y1_ref[:], z1_ref[:]),
            (x2_ref[:], y2_ref[:], z2_ref[:]),
            use_box, projected, meta_ref[0], meta_ref[1])
        esq = edges_sq_ref[:]
        gvec = g_ref[:]                              # (1, LANES)

        gmat = jnp.zeros(sep_sq.shape, jnp.float32)
        for b in range(n_bins):                      # static unroll
            mask = (sep_sq >= esq[b, 0]) & (sep_sq < esq[b + 1, 0])
            if projected:
                mask = mask & pi_ok
            gmat = gmat + gvec[0, b] * mask.astype(jnp.float32)

        @pl.when(pl.program_id(1) == 0)
        def _():
            dw1_ref[:] = jnp.zeros_like(dw1_ref)

        # dw1[0, i] = Σ_j G_ij w2_j, produced in (1, T) row layout.
        dw1_ref[:] += jax.lax.dot_general(
            w2_ref[:], gmat, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)

    return kernel


def _pair_prep(tile, pos, w):
    """Split coordinates into row (N, 1) and column (1, N) layouts."""
    pos = jnp.asarray(pos, jnp.float32)
    w = jnp.asarray(w, jnp.float32)
    n = pos.shape[0]
    n_pad = _round_up(n, tile)
    pos = jnp.pad(pos, ((0, n_pad - n), (0, 0)))
    w = jnp.pad(w, (0, n_pad - n)).reshape(1, n_pad)
    rows = tuple(pos[:, c].reshape(n_pad, 1) for c in range(3))
    cols = tuple(pos[:, c].reshape(1, n_pad) for c in range(3))
    return rows, cols, w, n_pad


def _pair_inputs(tile, pos1, w1, pos2, w2, bin_edges, box, pimax):
    edges = jnp.asarray(bin_edges, jnp.float32)
    ep = _round_up(edges.shape[0], _SUBLANES)
    edges_sq = jnp.pad(edges * edges, (0, ep - edges.shape[0]),
                       mode="edge").reshape(ep, 1)
    meta = jnp.stack([jnp.asarray(box, jnp.float32),
                      jnp.asarray(pimax, jnp.float32)])
    side1 = _pair_prep(tile, pos1, w1)     # (rows, cols, w, n_pad)
    side2 = _pair_prep(tile, pos2, w2)
    return edges_sq, meta, side1, side2, ep


def _pair_in_specs(tile, ep):
    row_spec = pl.BlockSpec((tile, 1), lambda i, j: (i, 0),
                            memory_space=pltpu.VMEM)
    col_spec = pl.BlockSpec((1, tile), lambda i, j: (0, j),
                            memory_space=pltpu.VMEM)
    return [
        pl.BlockSpec((ep, 1), lambda i, j: (0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((2,), lambda i, j: (0,),
                     memory_space=pltpu.SMEM),
        row_spec, row_spec, row_spec,
        pl.BlockSpec((1, tile), lambda i, j: (0, i),
                     memory_space=pltpu.VMEM),
        col_spec, col_spec, col_spec,
        pl.BlockSpec((1, tile), lambda i, j: (0, j),
                     memory_space=pltpu.VMEM),
    ]


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def _pair_counts_core(tile, interpret, use_box, projected, autocorr,
                      pos1, w1, pos2, w2, bin_edges, box, pimax):
    counts, _ = _pair_fwd(tile, interpret, use_box, projected,
                          autocorr, pos1, w1, pos2, w2, bin_edges,
                          box, pimax)
    return counts


def _pair_masks_jnp(pos1, pos2, bin_edges, use_box, projected, box,
                    pimax):
    """Per-bin pair masks as dense jnp — the emulation's shared
    building block (same math as the kernel's mask loop)."""
    p1 = jnp.asarray(pos1, jnp.float32)
    p2 = jnp.asarray(pos2, jnp.float32)
    d = p1[:, None, :] - p2[None, :, :]
    if use_box:
        d = d - box * jnp.round(d / box)
    if projected:
        sep_sq = d[..., 0] ** 2 + d[..., 1] ** 2
        pi_ok = jnp.abs(d[..., 2]) < pimax
    else:
        sep_sq = jnp.sum(d * d, axis=-1)
        pi_ok = True
    esq = jnp.asarray(bin_edges, jnp.float32) ** 2
    return [((sep_sq >= esq[b]) & (sep_sq < esq[b + 1]) & pi_ok
             ).astype(jnp.float32)
            for b in range(bin_edges.shape[0] - 1)]


def _pair_fwd(tile, interpret, use_box, projected, autocorr,
              pos1, w1, pos2, w2, bin_edges, box, pimax):
    n_bins = bin_edges.shape[0] - 1
    if _use_jnp_emulation(interpret, w1, w2, pos1, pos2):
        # CPU shard_map simulation: delegate the forward to the XLA
        # reference implementation so the emulation can never drift
        # from the conventions the kernel mirrors.
        from .pairwise import _block_counts
        edges = jnp.asarray(bin_edges, jnp.float32)
        counts = _block_counts(
            jnp.asarray(pos1, jnp.float32), jnp.asarray(w1, jnp.float32),
            jnp.asarray(pos2, jnp.float32), jnp.asarray(w2, jnp.float32),
            edges * edges, box if use_box else None,
            pimax if projected else None)
        return counts, (pos1, w1, pos2, w2, bin_edges, box, pimax)
    edges_sq, meta, side1, side2, ep = _pair_inputs(
        tile, pos1, w1, pos2, w2, bin_edges, box, pimax)
    rows1, _, w1p, n1 = side1
    _, cols2, w2p, n2 = side2
    (edges_sq, meta, w1p, w2p, *rc) = _unify_vma(
        edges_sq, meta, w1p, w2p, *rows1, *cols2)
    rows1, cols2 = tuple(rc[:3]), tuple(rc[3:])

    out = pl.pallas_call(
        _make_pair_fwd_kernel(n_bins, use_box, projected),
        grid=(n1 // tile, n2 // tile),
        in_specs=_pair_in_specs(tile, ep),
        out_specs=pl.BlockSpec((1, _LANES), lambda i, j: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_out_struct((1, _LANES), w1p, w2p, *rows1, *cols2),
        interpret=_auto_interpret(interpret),
        cost_estimate=pl.CostEstimate(
            flops=2 * n1 * n2 * (3 + n_bins),
            bytes_accessed=16 * (n1 + n2), transcendentals=0),
    )(edges_sq, meta, *rows1, w1p, *cols2, w2p)
    counts = out[0, :n_bins]
    return counts, (pos1, w1, pos2, w2, bin_edges, box, pimax)


def _pair_bwd_rowgrad(kernel, tile, interpret, ep, n_bins, edges_sq,
                      meta, rows_a, wa, na, cols_b, wb, nb, g_pad):
    """dJ/dw for the row side of one (rows_a × cols_b) sweep."""
    (edges_sq, meta, wa, wb, g_pad, *rc) = _unify_vma(
        edges_sq, meta, wa, wb, g_pad, *rows_a, *cols_b)
    rows_a, cols_b = tuple(rc[:3]), tuple(rc[3:])
    return pl.pallas_call(
        kernel,
        grid=(na // tile, nb // tile),
        in_specs=_pair_in_specs(tile, ep) + [
            pl.BlockSpec((1, _LANES), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, tile), lambda i, j: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=_out_struct((1, na), wa, wb, g_pad, *rows_a, *cols_b),
        interpret=_auto_interpret(interpret),
        cost_estimate=pl.CostEstimate(
            flops=2 * na * nb * (3 + n_bins),
            bytes_accessed=16 * (na + nb), transcendentals=0),
    )(edges_sq, meta, *rows_a, wa, *cols_b, wb, g_pad)


def _pair_bwd(tile, interpret, use_box, projected, autocorr,
              residuals, g):
    pos1, w1, pos2, w2, bin_edges, box, pimax = residuals
    n_bins = bin_edges.shape[0] - 1
    def zero(p):
        return _match_vma(jnp.zeros(jnp.shape(p), jnp.float32), p)
    if _use_jnp_emulation(interpret, w1, w2, pos1, pos2):
        masks = _pair_masks_jnp(pos1, pos2, bin_edges, use_box,
                                projected, box, pimax)
        gmat = sum(jnp.asarray(g, jnp.float32)[b] * masks[b]
                   for b in range(n_bins))
        w1f = jnp.asarray(w1, jnp.float32)
        w2f = jnp.asarray(w2, jnp.float32)
        return (zero(pos1), (gmat @ w2f).astype(jnp.result_type(w1)),
                zero(pos2), (w1f @ gmat).astype(jnp.result_type(w2)),
                zero(bin_edges), zero(box), zero(pimax))
    edges_sq, meta, side1, side2, ep = _pair_inputs(
        tile, pos1, w1, pos2, w2, bin_edges, box, pimax)
    rows1, cols1, w1p, n1 = side1
    rows2, cols2, w2p, n2 = side2
    g_pad = jnp.pad(jnp.asarray(g, jnp.float32),
                    (0, _LANES - n_bins)).reshape(1, _LANES)

    kernel = _make_pair_bwd_kernel(n_bins, use_box, projected)
    # Row-side gradient of each sweep; the pair masks are symmetric,
    # so dw2 is the same kernel with the particle sets swapped.
    dw1 = _pair_bwd_rowgrad(kernel, tile, interpret, ep, n_bins,
                            edges_sq, meta, rows1, w1p, n1, cols2,
                            w2p, n2, g_pad)
    if autocorr:
        # Autocorrelation (the wp/xi single-shard hot path): G is
        # symmetric and the two sides coincide, so the second O(N²)
        # sweep would recompute dw1 exactly.  (Decided statically at
        # the pair_counts_pallas entry — object identity does not
        # survive the custom_vjp residual round-trip under jit.)
        dw2 = dw1
    else:
        dw2 = _pair_bwd_rowgrad(kernel, tile, interpret, ep, n_bins,
                                edges_sq, meta, rows2, w2p, n2, cols1,
                                w1p, n1, g_pad)

    dw1_out = dw1[0, :jnp.shape(w1)[0]].astype(jnp.result_type(w1))
    dw2_out = dw2[0, :jnp.shape(w2)[0]].astype(jnp.result_type(w2))
    return (zero(pos1), _match_vma(dw1_out, w1),
            zero(pos2), _match_vma(dw2_out, w2),
            zero(bin_edges), zero(box), zero(pimax))


_pair_counts_core.defvjp(_pair_fwd, _pair_bwd)


def pair_counts_pallas(pos1, w1, pos2, w2, bin_edges,
                       box_size=None, pimax=None,
                       tile: int = 512,
                       interpret: bool | None = None):
    """Weighted ordered-pair counts between two particle blocks.

    Pallas analogue of ``ops.pairwise._block_counts`` (same
    conventions: ordered pairs ``counts[b] = Σ_ij w1_i w2_j
    [edge_b ≤ sep < edge_{b+1}]``, direct per-bin masks, optional
    periodic minimum image and projected ``|π| < pimax`` cut).
    Differentiable wrt the *weights* only (positions are data; their
    cotangent is zero), via an analytic VJP — no (tile, tile) block
    ever reaches HBM in either pass.

    Inputs are zero-padded to ``tile`` (weight 0 → exactly neutral
    for every count).
    """
    bin_edges = jnp.asarray(bin_edges, jnp.float32)
    if bin_edges.shape[0] - 1 > _LANES:
        raise ValueError(f"at most {_LANES} bins supported")
    if tile % _LANES:
        raise ValueError(f"tile must be a multiple of {_LANES}")
    return _pair_counts_core(
        tile, interpret,
        box_size is not None, pimax is not None,
        pos2 is pos1 and w2 is w1,
        pos1, w1, pos2, w2, bin_edges,
        jnp.asarray(0.0 if box_size is None else box_size, jnp.float32),
        jnp.asarray(0.0 if pimax is None else pimax, jnp.float32))
