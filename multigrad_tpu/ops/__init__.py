from .binned import (binned_density, binned_density_jit, binned_erf_counts,
                     norm_cdf)
from .pairwise import (analytic_rr_counts, ring_weighted_pair_counts,
                       wp_from_counts, xi_from_counts)

__all__ = ["binned_density", "binned_density_jit", "binned_erf_counts",
           "norm_cdf", "analytic_rr_counts", "ring_weighted_pair_counts",
           "wp_from_counts", "xi_from_counts"]
