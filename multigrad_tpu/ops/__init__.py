from .binned import (binned_density, binned_density_jit, binned_erf_counts,
                     fused_bin_window, norm_cdf)
from .pairwise import (analytic_rr_counts, ring_weighted_pair_counts,
                       wp_from_counts, xi_from_counts)

__all__ = ["binned_density", "binned_density_jit", "binned_erf_counts",
           "fused_bin_window", "norm_cdf", "analytic_rr_counts",
           "ring_weighted_pair_counts", "wp_from_counts",
           "xi_from_counts", "binned_erf_counts_pallas",
           "binned_erf_counts_fused_pallas", "pair_counts_pallas"]

_PALLAS_EXPORTS = {"binned_erf_counts_pallas",
                   "binned_erf_counts_fused_pallas", "pair_counts_pallas"}


def __getattr__(name):
    # Lazy: jax.experimental.pallas (+ Mosaic) only loads when the
    # opt-in pallas backend is actually used, mirroring the deferred
    # imports inside binned/pairwise.
    if name in _PALLAS_EXPORTS:
        from . import pallas_kernels
        return getattr(pallas_kernels, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
