from .binned import (binned_density, binned_density_jit, binned_erf_counts,
                     norm_cdf)

__all__ = ["binned_density", "binned_density_jit", "binned_erf_counts",
           "norm_cdf"]
