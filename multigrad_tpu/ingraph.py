"""Fully in-graph distributed gradient descent (compat surface).

Parity module for the reference's experimental ``multigrad.mpi4jax``
package (``/root/reference/multigrad/mpi4jax/multigrad.py``), which
prototyped moving the collectives *inside* the jitted graph via
mpi4jax custom calls.  In this framework everything is in-graph by
construction, so these functions are thin compositions of the core —
kept because the reference exposes the surface (C9 in SURVEY §2.1):

* :func:`distribute_data` — contiguous chunk per shard
  (``mpi4jax/multigrad.py:17-23``); here: shard + return the global
  sharded array.
* :func:`reduce_sum` — in-graph allreduce (``:27-29``); here a psum
  façade over the comm axis.
* :func:`simple_grad_descent` — ``lax.scan`` gradient descent
  returning a pandas DataFrame (``:33-61``).  The reference's
  update-on-root-then-bcast (``:48-52``) is replaced by replicated
  SPMD updates (same values, no transfer).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
from jax import numpy as jnp

from .parallel.collectives import reduce_sum as _reduce_sum
from .parallel.collectives import scatter_nd
from .parallel.mesh import MeshComm


def distribute_data(data, comm: Optional[MeshComm] = None, pad_value=0.0):
    """Shard `data` along its leading axis over `comm`'s devices.

    The reference sliced out this rank's contiguous chunk
    (``mpi4jax/multigrad.py:17-23``, chunk = ceil(n/n_ranks)); under
    one controller the whole array is placed shard-per-device instead
    (padding with `pad_value` when ragged — the reference's TODO at
    ``:14-15`` about out-of-memory data is addressed by
    :func:`multigrad_tpu.parallel.scatter_from_local`).
    """
    if comm is None:
        return jnp.asarray(data)
    return scatter_nd(data, axis=0, comm=comm, pad_value=pad_value)


def reduce_sum(partial_value, comm: Optional[MeshComm] = None):
    """In-graph allreduce-sum (parity: ``mpi4jax/multigrad.py:27-29``)."""
    return _reduce_sum(partial_value, comm=comm)


def simple_grad_descent(data_dict, loss_and_grad_func: Callable, guess,
                        learning_rate: float = 0.01, nsteps: int = 100,
                        comm: Optional[MeshComm] = None):
    """Distributed fixed-LR gradient descent as one ``lax.scan``.

    Parity with ``mpi4jax/multigrad.py:33-61`` including the pandas
    DataFrame return.  ``loss_and_grad_func(data_dict, params)``
    computes this *shard's* ``(loss, grad)`` from its local view of
    ``data_dict`` (leaves sharded over `comm` arrive shard-by-shard,
    like the reference's per-rank chunks); both are allreduce-summed
    in-graph — the reference summed only the gradient and left each
    rank its local loss (``:43-44``), whereas here the recorded loss
    is the total, which is replicated and well-defined globally.
    """
    import pandas as pd

    from jax.sharding import PartitionSpec
    from .core.model import _leaf_spec, _merge_aux, _split_aux
    from .parallel._shard_map_compat import shard_map
    from .utils.util import cached_program

    guess = jnp.asarray(guess, dtype=jnp.result_type(float))
    dynamic, static, treedef = _split_aux(data_dict)
    specs = tuple(_leaf_spec(leaf, comm) for leaf in dynamic) \
        if comm is not None else ()
    learning_rate = float(learning_rate)

    def build():
        def make_loop(dd):
            def loopfunc(params, _x):
                loss, grad = loss_and_grad_func(dd, params)
                grad = _reduce_sum(grad, comm=comm)
                loss = _reduce_sum(loss, comm=comm)
                y = (loss, params)
                return params - learning_rate * grad, y
            return loopfunc

        def local(guess, dynamic_leaves):
            dd = _merge_aux(dynamic_leaves, static, treedef)
            _, iterations = jax.lax.scan(make_loop(dd), guess,
                                         None, length=nsteps)
            return iterations

        if comm is None:
            return jax.jit(local)
        return jax.jit(shard_map(
            local, mesh=comm.mesh,
            in_specs=(PartitionSpec(), list(specs)),
            out_specs=PartitionSpec()))

    try:
        cache_key = ("ingraph_gd", nsteps, learning_rate, comm, treedef,
                     tuple(static), specs)
        hash(cache_key)
    except TypeError:  # unhashable static aux: build fresh (no cache)
        run = build()
    else:
        run = cached_program(loss_and_grad_func, cache_key, build)

    loss, params = run(guess, dynamic)
    return pd.DataFrame(dict(
        loss=list(jnp.asarray(loss)),
        params=list(jnp.asarray(params))))
