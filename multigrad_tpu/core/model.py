"""Distributed one-point-function model core, TPU-native.

Re-design of the reference's ``OnePointModel``
(``/root/reference/multigrad/multigrad.py:186-544``).  The algebra is
identical — the two-stage VJP chain rule with communication volume
O(|sumstats| + |params|) independent of data size
(``multigrad.py:508-538``):

    y_r, vjp_r = jax.vjp(partial_sumstats, params)   # local per shard
    y          = psum(y_r)                           # comm: |y| floats
    dL/dy      = grad(loss_from_sumstats)(y)         # replicated
    dL/dp      = psum(vjp_r(dL/dy))                  # comm: |p| floats

— but the *execution model* is completely different.  The reference
interleaves host-side mpi4py collectives between jitted kernels, which
is why every method there is stamped "NOTE: Never jit this method".
Here the whole chain — both collectives included — is **one XLA
program**: the user's sumstats kernel, the psums, the loss gradient and
the VJP all live inside a single ``jit(shard_map(...))``, so XLA can
fuse, overlap the two all-reduces with compute, and keep everything
resident on-device.  This is the shape the reference's own in-graph
``mpi4jax`` experiment gestures at (``mpi4jax/multigrad.py:27-58``).

Sharding contract
-----------------
``aux_data`` is an arbitrary pytree.  Leaves that are ``jax.Array``s
sharded over ``comm``'s mesh axis (produce them with
:func:`multigrad_tpu.parallel.scatter_nd` or
:func:`~multigrad_tpu.parallel.scatter_from_local`) enter the SPMD
block shard-by-shard — inside ``calc_partial_sumstats_from_params``
the model sees only the local shard, exactly like an MPI rank saw only
its own chunk.  All other leaves are replicated.  Non-numeric leaves
(strings, callables, …) stay static in the closure.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from ..parallel._shard_map_compat import (PRE_VMA, pvary, pvary_like,
                                          shard_map)
# Collectives go through the instrumented wrappers (telemetry comm
# accounting happens at trace time; a plain lax.psum would be
# invisible to it).
from ..parallel.collectives import psum as _psum
from ..parallel.mesh import MeshComm
from ..telemetry.comm import record_collective as _record_collective
from ..optim import adam as _adam
from ..optim import bfgs as _bfgs
from ..optim.adam import init_randkey
from ..utils import util as _util


#: Named ``jax.checkpoint`` policies for the streamed scan path's
#: per-chunk remat.  ``None``/``"nothing"`` = save nothing (recompute
#: the whole chunk body in the backward pass — the historical
#: behavior, minimal memory); ``"dots"`` = save matmul/dot results
#: only (``jax.checkpoint_policies.checkpoint_dots`` — the cheap-to-
#: recompute erf/elementwise work is still rematerialized, but
#: MXU-shaped intermediates are kept, the discipline of the
#: weight-update-sharding and pjit-on-TPUv4 papers); ``"everything"``
#: = remat disabled (all residuals saved — fastest backward, highest
#: memory).
REMAT_POLICY_NAMES = ("nothing", "dots", "dots_with_no_batch_dims",
                      "everything")

#: Relative-variance floor of the gradient-noise-scale diagnostic:
#: its denominator |mean shard gradient|² is exactly zero at a
#: critical point, and the tap must stay finite there (the ratio
#: saturates instead of emitting inf into the record stream).
GNS_EPS = 1e-20


def resolve_remat_policy(policy):
    """Resolve a remat-policy knob to a ``jax.checkpoint`` policy.

    Accepts ``None`` (save nothing), one of
    :data:`REMAT_POLICY_NAMES`, or any ``jax.checkpoint`` policy
    callable (returned as-is).
    """
    if policy is None:
        return None
    if callable(policy):
        return policy
    cp = jax.checkpoint_policies
    try:
        return {
            "nothing": None,
            "dots": cp.checkpoint_dots,
            "dots_with_no_batch_dims":
                cp.checkpoint_dots_with_no_batch_dims,
            "everything": cp.everything_saveable,
        }[policy]
    except (KeyError, TypeError):
        raise ValueError(
            f"unknown remat_policy {policy!r}; expected None, one of "
            f"{REMAT_POLICY_NAMES}, or a jax.checkpoint policy "
            "callable") from None


def _is_dynamic_leaf(leaf) -> bool:
    """Array-like and float leaves become traced jit arguments.

    Python ints/bools stay static: aux ints are typically sizes or
    flags consumed by Python control flow (e.g. a chunk size), which
    must not be traced.  Arrays (any dtype) are always dynamic.
    """
    if isinstance(leaf, (jax.Array, np.ndarray)):
        return True
    return isinstance(leaf, float) or isinstance(leaf, (np.floating,
                                                        np.complexfloating))


def _split_aux(aux_data):
    """Split aux pytree into (dynamic_leaves, static_leaves, treedef)."""
    leaves, treedef = jax.tree_util.tree_flatten(aux_data)
    dynamic = [leaf if _is_dynamic_leaf(leaf) else None for leaf in leaves]
    static = [None if _is_dynamic_leaf(leaf) else leaf for leaf in leaves]
    return dynamic, static, treedef


def _merge_aux(dynamic, static, treedef):
    leaves = [d if s is None else s for d, s in zip(dynamic, static)]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _leaf_spec(leaf, comm: MeshComm) -> PartitionSpec:
    """Sharding spec of an aux leaf relative to `comm` (see module doc)."""
    if leaf is None:
        return PartitionSpec()
    sh = getattr(leaf, "sharding", None)
    if (isinstance(sh, NamedSharding)
            and set(comm.axes) & set(
                jax.tree_util.tree_leaves(tuple(sh.spec)))):
        return sh.spec
    return PartitionSpec()


@dataclass
class OnePointModel:
    """Differentiable data-parallel model over additive summary statistics.

    API-parity port of ``multigrad.OnePointModel``
    (``/root/reference/multigrad/multigrad.py:186-544``).  Subclass it
    (as a dataclass) and implement the same two methods as the
    reference:

    * ``calc_partial_sumstats_from_params(params[, randkey]) -> y_r``
      — sumstats of this shard's data; totals are the sum over shards.
    * ``calc_loss_from_sumstats(y[, sumstats_aux][, randkey]) -> loss``

    Parameters
    ----------
    aux_data : Any
        Pytree available to the user methods via ``self.aux_data``.
        See the module docstring for the sharding contract.
    comm : MeshComm, optional
        The device set + mesh axis to distribute over. ``None`` (the
        default) runs single-device, mirroring the reference's
        mpi4py-less fallback (``multigrad.py:23-27``).
    loss_func_has_aux, sumstats_func_has_aux : bool
        Same aux-plumbing flags as the reference
        (``multigrad.py:200-210``).
    """

    aux_data: Any = None
    comm: Optional[MeshComm] = None
    loss_func_has_aux: bool = False
    sumstats_func_has_aux: bool = False

    # ------------------------------------------------------------------ #
    # Abstract user methods (parity: multigrad.py:212-223)
    # ------------------------------------------------------------------ #
    def calc_partial_sumstats_from_params(self, params, randkey=None):
        """Custom method to map parameters to partial summary statistics."""
        raise NotImplementedError(
            "Subclass must implement `calc_partial_sumstats_from_params`")

    def calc_loss_from_sumstats(self, sumstats, sumstats_aux=None,
                                randkey=None):
        """Custom method to map total summary statistics to loss."""
        raise NotImplementedError(
            "Subclass must implement `calc_loss_from_sumstats`")

    # ------------------------------------------------------------------ #
    def __post_init__(self):
        # Gradient of the loss wrt total sumstats (multigrad.py:390-396).
        self._grad_loss_from_sumstats = jax.grad(
            self.calc_loss_from_sumstats, has_aux=self.loss_func_has_aux)
        self._program_cache = {}

    # The reference hashes models to use them as jit statics
    # (multigrad.py:540-544, with a buggy __eq__). We never pass models
    # through jit boundaries — programs are cached per instance — so
    # identity semantics are all that is needed.
    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other

    # ------------------------------------------------------------------ #
    # Sharded-K (2-level mesh) surface
    # ------------------------------------------------------------------ #
    @property
    def k_shard_axis(self):
        """The mesh axis the ensemble K batch axis can shard over —
        the comm's free (non-reduced) axis on a 2-level mesh
        (:func:`~multigrad_tpu.parallel.ensemble_comm`) — or ``None``
        on ordinary one-axis comms and off-mesh models."""
        comm = self.comm
        if comm is None:
            return None
        free = comm.free_axes
        return free[-1] if free else None

    @property
    def k_shard_replicas(self) -> int:
        """Replica-slice count of the 2-level mesh (1 when the model
        has no :attr:`k_shard_axis`)."""
        axis = self.k_shard_axis
        return int(self.comm.mesh.shape[axis]) if axis else 1

    def _require_k_shard_axis(self) -> str:
        axis = self.k_shard_axis
        if axis is None:
            raise ValueError(
                "this model's comm has no free replica axis to shard "
                "the K batch axis over; build it on a 2-level mesh "
                "with multigrad_tpu.parallel.ensemble_comm("
                "n_replicas=R) (see docs/distributed.md, 'Sharded "
                "ensembles')")
        return axis

    def k_sharding(self, ndim: int = 2) -> NamedSharding:
        """NamedSharding that partitions a ``(K, ...)`` array's
        leading (ensemble/chain/bucket) axis over the replica axis —
        what the K-sharded entry points place their parameter
        batches, Adam carries and trajectories with."""
        axis = self._require_k_shard_axis()
        return NamedSharding(
            self.comm.mesh,
            PartitionSpec(axis, *([None] * (max(int(ndim), 1) - 1))))

    # ------------------------------------------------------------------ #
    # SPMD program construction
    # ------------------------------------------------------------------ #
    def _local_model(self, aux_local):
        """A shallow copy of self whose aux_data is this shard's view."""
        model = dataclasses.replace(self, aux_data=aux_local, comm=None)
        return model

    def _build_local_fn(self, kind: str, with_key: bool):
        """The per-shard kernel behind one of the SPMD entry points.

        kind ∈ {"sumstats_total", "sumstats_partial", "loss",
                "loss_and_grad", "loss_and_grad_gns", "grad",
                "lhs_batch", "batched_loss_and_grad",
                "batched_loss_and_grad_sharded",
                "sumstats_jac_fwd", "sumstats_jac_rev"}.
        "batched_loss_and_grad_sharded" is the identical per-shard
        kernel as "batched_loss_and_grad" — the variants differ only
        in how :meth:`_build_program` maps the K batch axis onto the
        mesh (replicated vs partitioned over the free replica axis of
        a 2-level :func:`~multigrad_tpu.parallel.ensemble_comm`
        mesh), never in the math.
        Returns a plain function ``(params, dynamic_aux_leaves, key)``
        whose collectives reduce over ``self.comm`` — valid *inside* a
        ``shard_map`` block over that comm (or anywhere when comm is
        None).  :meth:`_build_program` wraps it into a compiled
        program; :meth:`spmd_kernel` exposes it for composition into
        *new* SPMD programs (the inference subsystem's HMC sampler
        builds its whole leapfrog/scan machinery around the
        "batched_loss_and_grad" kernel and compiles ONE program via
        :meth:`wrap_spmd`).
        """
        if kind == "batched_loss_and_grad_sharded":
            kind = "batched_loss_and_grad"
        comm = self.comm
        _, static_leaves, treedef = _split_aux(self.aux_data)
        sum_has_aux = self.sumstats_func_has_aux
        loss_has_aux = self.loss_func_has_aux
        distributed = comm is not None

        def stack_aux(aux):
            """Give shard-local aux values a leading shard axis.

            The reference hands each MPI rank *its own* aux; with one
            controller the faithful equivalent is all shards' aux,
            stacked — aux outputs have leading dim ``comm.size``.
            """
            if not distributed:
                return aux
            return jax.tree_util.tree_map(lambda a: jnp.asarray(a)[None],
                                          aux)

        def local_fn(params, dynamic_leaves, key):
            kwargs = {"randkey": key} if with_key else {}
            aux_local = _merge_aux(dynamic_leaves, static_leaves, treedef)
            model = self._local_model(aux_local)

            if kind == "lhs_batch":
                # One (sumstats, loss) evaluation, vmapped over a batch
                # of parameter vectors: the whole LHS scan is a single
                # program dispatch (SURVEY §7.6 — the improvement the
                # reference's Python loop leaves on the table,
                # multigrad.py:354-388).  Aux values are dropped from
                # the batched return, matching the loop path.
                def single_eval(p):
                    out = model.calc_partial_sumstats_from_params(
                        p, **kwargs)
                    ss_aux = None
                    if sum_has_aux:
                        y, ss_aux = out
                    else:
                        y = out
                    y = _psum(y, comm.axis_name) if distributed else y
                    args = (y, ss_aux) if sum_has_aux else (y,)
                    loss = model.calc_loss_from_sumstats(*args, **kwargs)
                    if loss_has_aux:
                        loss = loss[0]
                    return y, loss

                return jax.vmap(single_eval)(params)

            def sumstats_func(p):
                return model.calc_partial_sumstats_from_params(p, **kwargs)

            if kind == "sumstats_partial":
                y = sumstats_func(params)
                ss_aux = None
                if sum_has_aux:
                    y, ss_aux = y
                y = y[None] if distributed else y
                if sum_has_aux:
                    return y, stack_aux(ss_aux)
                return y

            if kind in ("sumstats_total", "loss"):
                y = sumstats_func(params)
                ss_aux = None
                if sum_has_aux:
                    y, ss_aux = y
                y = _psum(y, comm.axis_name) if distributed else y
                if kind == "sumstats_total":
                    return (y, stack_aux(ss_aux)) if sum_has_aux else y
                args = (y, ss_aux) if sum_has_aux else (y,)
                out = model.calc_loss_from_sumstats(*args, **kwargs)
                if loss_has_aux:
                    loss, laux = out
                    return loss, stack_aux(laux)
                return out

            if kind in ("sumstats_jac_fwd", "sumstats_jac_rev"):
                # Total-sumstats Jacobian dy/dparams: per-shard (and,
                # via the streaming twin "chunk_jac", per-chunk)
                # Jacobians psum exactly like the sumstats themselves —
                # J = Σ_r ∂y_r/∂p — so the communication stays
                # O(|y|·|p|) independent of data size.  The inference
                # subsystem's Fisher matrices are built on this.
                # Sumstats must be a single array here (every shipped
                # model's contract); aux values are dropped.
                def sumstats_only(p):
                    out = sumstats_func(p)
                    return out[0] if sum_has_aux else out

                if kind == "sumstats_jac_fwd":
                    # Forward mode: the tangent map has no transpose,
                    # so the shard reduction is explicit on every jax.
                    y = sumstats_only(params)
                    jac = jax.jacfwd(sumstats_only)(params)
                    if distributed:
                        y = _psum(y, comm.axis_name)
                        jac = _psum(jac, comm.axis_name)
                    return y, jac
                # Reverse mode: one VJP row per sumstat, with the same
                # transpose semantics as the loss_and_grad path below
                # (vma-era jax inserts the shard psum; pre-vma needs
                # it explicit).
                y_r, vjp_func = jax.vjp(sumstats_only, params)
                y = _psum(y_r, comm.axis_name) if distributed \
                    else y_r
                basis = jnp.eye(y_r.size, dtype=y_r.dtype).reshape(
                    (y_r.size,) + y_r.shape)

                def one_row(ct):
                    if distributed:
                        ct = pvary(ct, comm.axis_name)
                    g = vjp_func(ct)[0]
                    if distributed and PRE_VMA:
                        g = _psum(g, comm.axis_name)
                    elif distributed:
                        # vma-era transpose inserts the row's psum
                        # itself; account for it (same traffic).
                        _record_collective("psum", g)
                    return g

                jac = jax.vmap(one_row)(basis)
                return y, jac.reshape(y_r.shape + params.shape[-1:])

            def fused_loss_and_grad(p):
                # The two-stage VJP chain rule (multigrad.py:508-538)
                # as one in-graph computation.
                vjp_results = jax.vjp(sumstats_func, p,
                                      has_aux=sum_has_aux)
                y, vjp_func = vjp_results[:2]
                y = _psum(y, comm.axis_name) if distributed else y
                args = (y, *vjp_results[2:])

                grad_loss = jax.grad(model.calc_loss_from_sumstats,
                                     has_aux=loss_has_aux)
                dloss_dsumstats = grad_loss(*args, **kwargs)
                if loss_has_aux:
                    dloss_dsumstats = dloss_dsumstats[0]

                if distributed:
                    # The cotangent is built from the replicated
                    # (psum'd) total, but the VJP's primal output was
                    # device-varying; cast it back (jax>=0.7 vma
                    # types).
                    dloss_dsumstats = jax.tree_util.tree_map(
                        lambda t: pvary(t, comm.axis_name),
                        dloss_dsumstats)
                # NB: on vma-era jax (0.7+) — unlike the reference,
                # whose host-local VJP needs an explicit allreduce of
                # the partial gradients (multigrad.py:531-532) — the
                # in-graph transpose already inserts the psum over the
                # mesh axis: `params` is replicated (unvarying), so
                # its cotangent is reduced to replicated
                # automatically, and adding another psum would
                # multiply the gradient by comm.size.  Pre-vma jax has
                # no mesh-aware transpose inside the body, so the
                # allreduce must be explicit there (PRE_VMA).
                dloss_dparams = vjp_func(dloss_dsumstats)[0]
                if distributed and PRE_VMA:
                    dloss_dparams = _psum(dloss_dparams,
                                          comm.axis_name)
                elif distributed:
                    # vma-era jax: the transpose-inserted psum is
                    # invisible to the instrumented wrappers; record
                    # it so comm accounting is jax-version-invariant.
                    _record_collective("psum", dloss_dparams)
                out = model.calc_loss_from_sumstats(*args, **kwargs)
                return out, dloss_dparams

            if kind == "batched_loss_and_grad":
                # A batch of parameter vectors through the fused chain
                # rule — vmapped INSIDE the SPMD block, so one program
                # serves K independent evaluations (collectives
                # batch).  Powers the inference subsystem's multi-
                # start ensembles and per-chain HMC potentials.  Loss
                # aux values are dropped from the batched return
                # (matching "lhs_batch").
                def single(p):
                    out, g = fused_loss_and_grad(p)
                    return (out[0] if loss_has_aux else out), g

                return jax.vmap(single)(params)

            if kind == "loss_and_grad_gns":
                # The fused chain rule with the PER-SHARD gradient
                # kept visible pre-reduction, feeding the gradient-
                # noise-scale convergence diagnostic — both norms are
                # already computed in the step, the diagnostic only
                # reduces them differently.  On pre-vma jax the
                # in-body VJP is mesh-unaware, so its cotangent IS the
                # local gradient (the reduction below is the explicit
                # psum fused_loss_and_grad already needs there); on
                # vma-era jax the transpose of a replicated params
                # input would insert the reduction itself, so the VJP
                # is taken wrt a device-varying copy (pvary) — the
                # cotangent stays per-shard and the psum is explicit
                # on both eras, keeping the comm accounting visible.
                p_in = params if (not distributed or PRE_VMA) \
                    else pvary(params, comm.axis_name)
                vjp_results = jax.vjp(sumstats_func, p_in,
                                      has_aux=sum_has_aux)
                y, vjp_func = vjp_results[:2]
                y = _psum(y, comm.axis_name) if distributed else y
                args = (y, *vjp_results[2:])
                grad_loss = jax.grad(model.calc_loss_from_sumstats,
                                     has_aux=loss_has_aux)
                dloss_dsumstats = grad_loss(*args, **kwargs)
                if loss_has_aux:
                    dloss_dsumstats = dloss_dsumstats[0]
                if distributed:
                    dloss_dsumstats = jax.tree_util.tree_map(
                        lambda t: pvary(t, comm.axis_name),
                        dloss_dsumstats)
                g_local = vjp_func(dloss_dsumstats)[0]
                g_total = _psum(g_local, comm.axis_name) \
                    if distributed else g_local
                size = comm.size if distributed else 1
                # Per-shard gradient second moment, averaged over the
                # mesh — one extra SCALAR psum (O(1) payload: the
                # O(|y|+|params|) bound is untouched).
                sq_local = jnp.sum(g_local * g_local, axis=-1)
                mean_sq = _psum(sq_local, comm.axis_name) / size \
                    if distributed else sq_local
                g_bar = g_total / size
                sq_mean = jnp.sum(g_bar * g_bar, axis=-1)
                # Relative per-shard gradient variance: ~0 when the
                # shards agree on the descent direction (signal-
                # dominated), large when per-shard noise drowns the
                # mean gradient — the convergence/batch-size signal
                # of the gradient-noise-scale literature, with shards
                # as the "small batches".
                noise = jnp.maximum(mean_sq - sq_mean, 0.0)
                diag = {
                    "grad_noise_scale": noise / (sq_mean + GNS_EPS),
                    "grad_norm_shard": jnp.sqrt(mean_sq),
                }
                out = model.calc_loss_from_sumstats(*args, **kwargs)
                if loss_has_aux:
                    loss, laux = out
                    return (loss, stack_aux(laux)), g_total, diag
                return out, g_total, diag

            out, dloss_dparams = fused_loss_and_grad(params)
            if kind == "grad":
                return dloss_dparams
            if loss_has_aux:
                loss, laux = out
                return (loss, stack_aux(laux)), dloss_dparams
            return out, dloss_dparams

        return local_fn

    def _program_out_specs(self, kind: str):
        """Output partition specs of `kind`'s program: replicated for
        totals/losses/grads/jacobians (psum products or functions
        thereof), shard-stacked for partials and aux values (shard-
        local by nature).  A single PartitionSpec at an aux subtree
        position is a prefix covering all its leaves."""
        comm = self.comm
        sum_has_aux = self.sumstats_func_has_aux
        loss_has_aux = self.loss_func_has_aux
        REP = PartitionSpec()
        STACKED = PartitionSpec(comm.axis_name) if comm is not None \
            else REP
        if kind == "batched_loss_and_grad_sharded":
            # Losses/grads stay partitioned along the K axis: each
            # replica slice computed (and owns) its K/R members'
            # rows; nothing is gathered.
            axis = self._require_k_shard_axis()
            return (PartitionSpec(axis), PartitionSpec(axis, None))
        if kind in ("lhs_batch", "batched_loss_and_grad"):
            return (REP, REP)
        if kind in ("sumstats_jac_fwd", "sumstats_jac_rev"):
            return (REP, REP)
        if kind == "sumstats_partial":
            return (STACKED, STACKED) if sum_has_aux else STACKED
        if kind == "sumstats_total":
            return (REP, STACKED) if sum_has_aux else REP
        if kind == "loss":
            return (REP, STACKED) if loss_has_aux else REP
        if kind == "grad":
            return REP
        if kind == "loss_and_grad_gns":
            # (out, grad, diag dict) — all reduction products; the
            # bare spec at the dict position is a prefix over its
            # leaves.
            out = (REP, STACKED) if loss_has_aux else REP
            return (out, REP, REP)
        # loss_and_grad
        return ((REP, STACKED), REP) if loss_has_aux else (REP, REP)

    def wrap_spmd(self, local_fn, out_specs, n_extra: int = 0,
                  donate_argnums=(), params_spec=None):
        """Compile a per-shard kernel into one SPMD program.

        The public composition hook paired with :meth:`spmd_kernel`:
        ``local_fn(params, dynamic_aux_leaves, key, *extra)`` — with
        ``params``/``key`` and the ``n_extra`` trailing arguments
        replicated, aux leaves entering shard-by-shard per the module
        sharding contract — becomes ``jit(shard_map(local_fn))`` over
        the model's mesh (plain ``jit`` when ``comm`` is None).
        ``out_specs`` follow :func:`shard_map`'s convention
        (``PartitionSpec()`` for replicated outputs).  ``params_spec``
        overrides the params argument's in-spec (default replicated)
        — the K-sharded program family partitions its ``(K, ndim)``
        batch over the replica axis with it, so each shard's kernel
        sees only its own ``K/R`` rows.
        """
        comm = self.comm
        if comm is None:
            return jax.jit(local_fn, donate_argnums=donate_argnums)
        # Sharding specs are read off the concrete aux arrays once at
        # build time (aux_data is part of the model's identity; swap
        # data by constructing a new model).
        dynamic0, _, _ = _split_aux(self.aux_data)
        aux_specs = [_leaf_spec(leaf, comm) for leaf in dynamic0]
        REP = PartitionSpec()
        p_spec = REP if params_spec is None else params_spec
        mapped = shard_map(
            local_fn, mesh=comm.mesh,
            in_specs=(p_spec, aux_specs, REP) + (REP,) * n_extra,
            out_specs=out_specs)
        return jax.jit(mapped, donate_argnums=donate_argnums)

    def spmd_kernel(self, kind: str, with_key: bool = False):
        """The model's per-shard kernel for `kind`, uncompiled.

        A plain function ``(params, dynamic_aux_leaves, key) -> out``
        whose collectives reduce over ``self.comm`` — valid *inside* a
        ``shard_map`` block over that comm (or anywhere when ``comm``
        is None).  Compose it into new in-graph algorithms and compile
        with :meth:`wrap_spmd`; the inference subsystem's HMC sampler
        (``multigrad_tpu/inference/hmc.py``) is the worked example.
        """
        return self._build_local_fn(kind, with_key)

    def _build_program(self, kind: str, with_key: bool):
        """Compile one of the model's SPMD entry points.

        Each program takes ``(params, dynamic_aux_leaves, randkey)``
        and runs fully in-graph (collectives included); kinds are
        listed on :meth:`_build_local_fn`.  The
        ``batched_loss_and_grad_sharded`` kind compiles the SAME
        per-shard kernel as ``batched_loss_and_grad`` with the K
        batch axis partitioned over the mesh's free replica axis
        instead of replicated.
        """
        params_spec = None
        if kind == "batched_loss_and_grad_sharded":
            params_spec = PartitionSpec(self._require_k_shard_axis(),
                                        None)
        return self.wrap_spmd(self._build_local_fn(kind, with_key),
                              self._program_out_specs(kind),
                              params_spec=params_spec)

    def _get_program(self, kind: str, with_key: bool):
        cache_key = (kind, with_key)
        if cache_key not in self._program_cache:
            self._program_cache[cache_key] = self._build_program(
                kind, with_key)
        return self._program_cache[cache_key]

    # ------------------------------------------------------------------ #
    # Aux re-binding and chunked (streaming) entry points
    # ------------------------------------------------------------------ #
    def replace_aux(self, **updates):
        """A new model whose ``aux_data`` has `updates` rebound.

        The public aux re-binding hook (aux_data is part of a model's
        identity — see :meth:`_build_program` — so swapping data means
        constructing a new model; this does it without re-specifying
        the model's configuration).  Requires dict aux_data, which all
        shipped models use.
        """
        if not isinstance(self.aux_data, dict):
            raise TypeError(
                "replace_aux needs dict aux_data, got "
                f"{type(self.aux_data).__name__}")
        return dataclasses.replace(
            self, aux_data={**self.aux_data, **updates})

    def _rebound_local_model(self, aux_local, stream_names, chunk_leaves):
        """Local-shard model with streamed leaves rebound into aux.

        The streaming contract: ``self.aux_data`` (a dict) holds the
        *resident* leaves; the streamed catalog arrives per chunk and
        is bound under ``stream_names`` here, so the user's sumstats
        method reads ``self.aux_data[name]`` identically in resident
        and streamed execution.
        """
        if not isinstance(aux_local, dict):
            raise TypeError(
                "streaming requires dict aux_data (stream leaves are "
                f"rebound by key), got {type(aux_local).__name__}")
        return self._local_model(
            {**aux_local, **dict(zip(stream_names, chunk_leaves))})

    def _build_stream_program(self, kind: str, with_key: bool,
                              stream_names: tuple,
                              remat_policy="dots"):
        """Compile one of the chunked-streaming SPMD entry points.

        kind ∈ {"chunk_sumstats", "chunk_vjp", "chunk_scan"}:

        * ``chunk_sumstats(params, chunk_leaves, aux_leaves, key)`` —
          this chunk's TOTAL sumstats (psummed over the mesh,
          replicated).  With ``sumstats_func_has_aux``, the aux is
          accumulated the same way (streaming requires additive aux —
          it is a summary statistic in the same algebra).
        * ``chunk_vjp(params, chunk_leaves, aux_leaves, ct, key)`` —
          this chunk's contribution to ``dL/dparams``: the VJP of the
          chunk's partial sumstats against the replicated cotangent
          ``ct = dL/dy``, all-reduced over the mesh.  Summing over
          chunks reproduces the resident gradient exactly (chain rule
          + additivity), which is pass 2 of the streamed algebra.
        * ``chunk_jac(params, chunk_leaves, aux_leaves, key)`` — this
          chunk's TOTAL ``(sumstats, jacobian)`` contribution (both
          psummed over the mesh, replicated).  The Jacobian
          ``∂y_k/∂params`` psums exactly like ``y_k`` itself, so
          summing over chunks reproduces the resident
          ``sumstats_jac`` program — Fisher matrices for catalogs
          that never fit in HBM (``multigrad_tpu/inference/fisher``).
        * ``chunk_scan(params, chunk_stack_leaves, aux_leaves, key)``
          — the single-dispatch path: all chunks stacked on a leading
          axis, summed by an in-graph ``lax.scan`` with
          ``jax.checkpoint`` per chunk (VJP residuals are recomputed,
          never materialized for more than one chunk), then the
          standard two-stage loss-and-grad.  For catalogs that fit
          HBM while their VJP residuals would not.  ``remat_policy``
          (chunk_scan only; see :func:`resolve_remat_policy`) selects
          what the per-chunk checkpoint SAVES — default ``"dots"``
          keeps dot/matmul results and recomputes the elementwise
          transcendental work, trading a few saved residuals for a
          cheaper backward sweep.

        Chunk leaves are sharded along their row axis (axis 0; axis 1
        for the scan's stacked form) over the comm — produce them with
        ``jax.device_put(chunk, comm.sharding(...))`` (the prefetcher
        does this).  The chunk buffers of the per-chunk kinds are
        donated on TPU/GPU so pass k+1's transfer can reuse pass k's
        HBM (donation is a no-op on CPU and skipped to avoid the
        warning).
        """
        comm = self.comm
        _, static_leaves, treedef = _split_aux(self.aux_data)
        sum_has_aux = self.sumstats_func_has_aux
        loss_has_aux = self.loss_func_has_aux
        distributed = comm is not None

        REP = PartitionSpec()

        def psum_tree(tree):
            if not distributed:
                return tree
            return jax.tree_util.tree_map(
                lambda t: _psum(t, comm.axis_name), tree)

        def chunk_sumstats(params, chunk_leaves, dynamic_leaves, key):
            kwargs = {"randkey": key} if with_key else {}
            aux_local = _merge_aux(dynamic_leaves, static_leaves, treedef)
            model = self._rebound_local_model(aux_local, stream_names,
                                              chunk_leaves)
            out = model.calc_partial_sumstats_from_params(params, **kwargs)
            if sum_has_aux:
                y, ss_aux = out
                return psum_tree(y), psum_tree(ss_aux)
            return psum_tree(out)

        def chunk_vjp(params, chunk_leaves, dynamic_leaves, ct, key):
            kwargs = {"randkey": key} if with_key else {}
            aux_local = _merge_aux(dynamic_leaves, static_leaves, treedef)
            model = self._rebound_local_model(aux_local, stream_names,
                                              chunk_leaves)

            def sumstats_func(p):
                return model.calc_partial_sumstats_from_params(p, **kwargs)

            vjp_results = jax.vjp(sumstats_func, params,
                                  has_aux=sum_has_aux)
            vjp_func = vjp_results[1]
            if distributed:
                # ct is replicated (built from the psummed total);
                # the VJP's primal output was device-varying.
                ct = jax.tree_util.tree_map(
                    lambda t: pvary(t, comm.axis_name), ct)
            grad = vjp_func(ct)[0]
            if distributed and PRE_VMA:
                # Pre-vma jax: mesh-unaware transpose, explicit
                # allreduce (see the resident loss_and_grad path).
                grad = _psum(grad, comm.axis_name)
            elif distributed:
                # vma-era implicit transpose psum: record the traffic.
                _record_collective("psum", grad)
            return grad

        def chunk_jac(params, chunk_leaves, dynamic_leaves, key):
            kwargs = {"randkey": key} if with_key else {}
            aux_local = _merge_aux(dynamic_leaves, static_leaves, treedef)
            model = self._rebound_local_model(aux_local, stream_names,
                                              chunk_leaves)

            def sumstats_only(p):
                out = model.calc_partial_sumstats_from_params(p, **kwargs)
                return out[0] if sum_has_aux else out

            # Forward mode (params are few, sumstats many): the local
            # tangent map has no transpose, so the explicit shard
            # psum is correct on every jax version.
            y = sumstats_only(params)
            jac = jax.jacfwd(sumstats_only)(params)
            return psum_tree(y), psum_tree(jac)

        def chunk_scan(params, chunk_stacks, dynamic_leaves, key):
            kwargs = {"randkey": key} if with_key else {}
            aux_local = _merge_aux(dynamic_leaves, static_leaves, treedef)

            def one_chunk(p, chunk_leaves):
                model = self._rebound_local_model(
                    aux_local, stream_names, chunk_leaves)
                return model.calc_partial_sumstats_from_params(
                    p, **kwargs)

            def sumstats_func(p):
                @partial(jax.checkpoint,
                         policy=resolve_remat_policy(remat_policy))
                def body(acc, chunk_leaves):
                    out = one_chunk(p, list(chunk_leaves))
                    return jax.tree_util.tree_map(jnp.add, acc, out), None

                first = [c[0] for c in chunk_stacks]
                out_shape = jax.eval_shape(one_chunk, params, first)
                init = jax.tree_util.tree_map(
                    lambda s: pvary_like(
                        jnp.zeros(s.shape, s.dtype), chunk_stacks[0]),
                    out_shape)
                total, _ = lax.scan(body, init, tuple(chunk_stacks))
                return (total[0], total[1]) if sum_has_aux else total

            # From here on: the identical two-stage chain rule as the
            # resident loss_and_grad program (kind="loss_and_grad").
            vjp_results = jax.vjp(sumstats_func, params,
                                  has_aux=sum_has_aux)
            y, vjp_func = vjp_results[:2]
            y = psum_tree(y)
            ss_aux = psum_tree(vjp_results[2]) if sum_has_aux else None
            args = (y, ss_aux) if sum_has_aux else (y,)
            loss_model = self._local_model(aux_local)
            grad_loss = jax.grad(loss_model.calc_loss_from_sumstats,
                                 has_aux=loss_has_aux)
            dloss_dsumstats = grad_loss(*args, **kwargs)
            if loss_has_aux:
                dloss_dsumstats = dloss_dsumstats[0]
            if distributed:
                dloss_dsumstats = jax.tree_util.tree_map(
                    lambda t: pvary(t, comm.axis_name), dloss_dsumstats)
            dloss_dparams = vjp_func(dloss_dsumstats)[0]
            if distributed and PRE_VMA:
                dloss_dparams = _psum(dloss_dparams, comm.axis_name)
            elif distributed:
                _record_collective("psum", dloss_dparams)
            out = loss_model.calc_loss_from_sumstats(*args, **kwargs)
            if loss_has_aux:
                out = out[0]
            return out, dloss_dparams

        fns = {"chunk_sumstats": chunk_sumstats, "chunk_vjp": chunk_vjp,
               "chunk_jac": chunk_jac, "chunk_scan": chunk_scan}
        local_fn = fns[kind]
        # Donate per-chunk buffers (arg position 1) where donation is
        # real; the resident scan stack is reused across steps, so
        # never donated.
        donate = (1,) if (kind != "chunk_scan"
                          and jax.default_backend() in ("tpu", "gpu")) \
            else ()

        if not distributed:
            return jax.jit(local_fn, donate_argnums=donate)

        dynamic0, _, _ = _split_aux(self.aux_data)
        aux_specs = [_leaf_spec(leaf, comm) for leaf in dynamic0]
        row_axis_spec = PartitionSpec(comm.axis_name)
        stacked_spec = PartitionSpec(None, comm.axis_name)
        chunk_specs = [stacked_spec if kind == "chunk_scan"
                       else row_axis_spec for _ in stream_names]
        if kind == "chunk_sumstats":
            in_specs = (REP, chunk_specs, aux_specs, REP)
            out_specs = (REP, REP) if sum_has_aux else REP
        elif kind == "chunk_jac":
            in_specs = (REP, chunk_specs, aux_specs, REP)
            out_specs = (REP, REP)
        elif kind == "chunk_vjp":
            in_specs = (REP, chunk_specs, aux_specs, REP, REP)
            out_specs = REP
        else:  # chunk_scan: (loss, grad); loss aux (if any) is dropped
            in_specs = (REP, chunk_specs, aux_specs, REP)
            out_specs = (REP, REP)
        mapped = shard_map(local_fn, mesh=comm.mesh, in_specs=in_specs,
                           out_specs=out_specs)
        return jax.jit(mapped, donate_argnums=donate)

    def _get_stream_program(self, kind: str, with_key: bool,
                            stream_names, remat_policy="dots"):
        stream_names = tuple(stream_names)
        # The policy joins the cache key (strings, None and policy
        # callables are all hashable), so switching policies compiles
        # a sibling program instead of silently retracing — and only
        # chunk_scan varies with it (the per-chunk kinds have no
        # in-graph remat), so they normalize to one entry.
        policy_key = remat_policy if kind == "chunk_scan" else None
        cache_key = (kind, with_key, stream_names, policy_key)
        if cache_key not in self._program_cache:
            self._program_cache[cache_key] = self._build_stream_program(
                kind, with_key, stream_names, remat_policy=remat_policy)
        return self._program_cache[cache_key]

    def chunk_sumstats_fn(self, stream_names, with_key: bool = False):
        """Raw jitted ``(params, chunk_leaves, aux_leaves, key) ->
        total chunk sumstats`` program (pass 1 of the streamed
        algebra); see :meth:`_build_stream_program`."""
        return self._get_stream_program("chunk_sumstats", with_key,
                                        stream_names)

    def chunk_vjp_fn(self, stream_names, with_key: bool = False):
        """Raw jitted ``(params, chunk_leaves, aux_leaves, ct, key) ->
        dL/dparams contribution`` program (pass 2)."""
        return self._get_stream_program("chunk_vjp", with_key,
                                        stream_names)

    def chunk_jac_fn(self, stream_names, with_key: bool = False):
        """Raw jitted ``(params, chunk_leaves, aux_leaves, key) ->
        (chunk total sumstats, chunk total Jacobian)`` program — the
        streamed twin of the ``sumstats_jac`` entry point (sum the
        outputs over chunks to reproduce the resident pair)."""
        return self._get_stream_program("chunk_jac", with_key,
                                        stream_names)

    def chunk_scan_loss_and_grad_fn(self, stream_names,
                                    with_key: bool = False,
                                    remat_policy="dots"):
        """Raw jitted ``(params, chunk_stack_leaves, aux_leaves, key)
        -> (loss, grad)`` single-dispatch scan-over-chunks program.
        ``remat_policy`` configures the per-chunk checkpoint (see
        :func:`resolve_remat_policy`)."""
        return self._get_stream_program("chunk_scan", with_key,
                                        stream_names,
                                        remat_policy=remat_policy)

    def _run(self, kind: str, params, randkey=None):
        params = jnp.asarray(params) if not isinstance(params, tuple) \
            else jnp.asarray(jnp.stack([jnp.asarray(p) for p in params]))
        dynamic, _, _ = _split_aux(self.aux_data)
        with_key = randkey is not None
        key = init_randkey(randkey) if with_key else jnp.zeros(())
        program = self._get_program(kind, with_key)
        return program(params, dynamic, key)

    # ------------------------------------------------------------------ #
    # Public API (parity: multigrad.py:398-538)
    # ------------------------------------------------------------------ #
    def calc_sumstats_from_params(self, params, total=True, randkey=None):
        """Compute summary statistics at given parameters.

        Parity with ``multigrad.py:400-427``.  With ``total=True``
        (default) returns the sum over all shards (replicated).  With
        ``total=False`` the reference returned *this rank's* partial;
        under a single controller the faithful equivalent is the
        stacked per-shard partials, shape ``(comm.size, *sumstats)``.
        """
        kind = "sumstats_total" if total else "sumstats_partial"
        return self._run(kind, params, randkey)

    def calc_dloss_dsumstats(self, sumstats, sumstats_aux=None, randkey=None):
        """d(loss)/d(sumstats) at the given *total* sumstats
        (parity: ``multigrad.py:430-436``)."""
        kwargs = {} if randkey is None else {"randkey": init_randkey(randkey)}
        sumstats = jnp.asarray(sumstats)
        args = (sumstats, sumstats_aux) if self.sumstats_func_has_aux \
            else (sumstats,)
        return self._grad_loss_from_sumstats(*args, **kwargs)

    def calc_loss_from_params(self, params, randkey=None):
        """Loss at the given parameters (parity: ``multigrad.py:439-460``)."""
        return self._run("loss", params, randkey)

    def calc_dloss_dparams(self, params, randkey=None):
        """Gradient of the loss wrt parameters
        (parity: ``multigrad.py:463-479``)."""
        return self._run("grad", params, randkey)

    def calc_loss_and_grad_from_params(self, params, randkey=None):
        """Loss and gradient in one fused in-graph program.

        Parity with ``multigrad.py:482-505``; as there, this is much
        cheaper than computing the two separately (the forward pass
        and VJP residuals are shared).
        """
        return self._run("loss_and_grad", params, randkey)

    def calc_sumstats_and_jac_from_params(self, params, randkey=None,
                                          mode: str = "fwd"):
        """Total sumstats AND their Jacobian wrt params, distributed.

        The second-order extension of the paper's identity: the
        per-shard Jacobians ``∂y_r/∂p`` psum exactly like ``y_r``
        (``J = Σ_r J_r``), so the total ``(|y|, |p|)`` Jacobian costs
        one pass over the data and O(|y|·|p|) communication.  The
        foundation of :func:`multigrad_tpu.inference.fisher_information`
        (Gauss–Newton Fisher ``Jᵀ H_y J`` and Laplace covariances).

        Parameters
        ----------
        mode : {"fwd", "rev"}
            ``jacfwd`` (default — params are few in every shipped
            model) or ``jacrev`` (when ``|params| > |sumstats|``).

        Returns
        -------
        (sumstats, jac) : replicated totals, shapes ``(*y,)`` and
            ``(*y, ndim)``.  Sumstats aux values (if any) are dropped;
            fetch them via :meth:`calc_sumstats_from_params`.
        """
        if mode not in ("fwd", "rev"):
            raise ValueError(f"mode must be 'fwd' or 'rev', got {mode!r}")
        return self._run(f"sumstats_jac_{mode}", params, randkey)

    def loss_and_grad_fn(self, with_key: bool = False):
        """The raw jitted ``(params, aux_leaves, key) -> (loss, grad)``
        program — scan-compatible, for in-graph optimizer loops.
        Obtain ``aux_leaves`` from :meth:`aux_leaves`."""
        return self._get_program("loss_and_grad", with_key)

    def batched_loss_and_grad_fn(self, with_key: bool = False,
                                 k_sharded: bool = False):
        """Raw jitted ``(params_batch, aux_leaves, key) ->
        (losses, grads)`` program: K parameter vectors (shape
        ``(K, ndim)``) through the fused chain rule as ONE dispatch,
        vmapped inside the SPMD block.  Powers multi-start ensembles
        (:func:`multigrad_tpu.inference.run_multistart_adam`) and
        per-chain HMC potentials.  Loss aux values are dropped.

        With ``k_sharded=True`` (requires a 2-level
        :func:`~multigrad_tpu.parallel.ensemble_comm` mesh) the K
        axis is PARTITIONED over the replica axis: each replica
        slice's devices see only their own ``K/R`` rows, every
        data-axis collective carries ``(K/R)·O(|y|+|params|)`` and
        nothing crosses the replica axis — place the batch with
        :meth:`k_sharding` (K must divide by the replica count).
        Outputs stay K-sharded.  The two variants live under
        distinct program-cache keys, so toggling never retraces."""
        kind = "batched_loss_and_grad_sharded" if k_sharded \
            else "batched_loss_and_grad"
        return self._get_program(kind, with_key)

    def aux_leaves(self):
        """The model's dynamic aux leaves, in the argument order the
        raw programs (:meth:`loss_and_grad_fn`) expect — the public
        pairing for custom in-graph training loops (static leaves stay
        baked into the compiled program)."""
        dynamic, _, _ = _split_aux(self.aux_data)
        return dynamic

    def check_shard_safety(self, params, **kwargs):
        """Statically verify this model's SPMD programs.

        One-call access to the shard-safety analyzer
        (:func:`multigrad_tpu.analysis.analyze_model`): traces the
        model's programs abstractly (zero FLOPs, no device execution)
        and returns a list of
        :class:`~multigrad_tpu.analysis.Finding` — empty when the
        communication bound, replication invariants, dtype hygiene
        and constant-capture rules all hold.  ``kwargs`` are
        forwarded (``kinds=``, ``randkey=``, ``checks=``,
        ``scale=``, ...); see the analyzer for the full surface, and
        :func:`multigrad_tpu.analysis.assert_clean` for the
        test-suite form.
        """
        from ..analysis import analyze_model
        return analyze_model(self, params, **kwargs)

    # ------------------------------------------------------------------ #
    # Optimizer front-ends (parity: multigrad.py:226-352)
    # ------------------------------------------------------------------ #
    def run_simple_grad_descent(self, guess, nsteps=100, learning_rate=0.01):
        """Fixed-learning-rate gradient descent
        (parity: ``multigrad.py:226-256``).

        Returns a :class:`~multigrad_tpu.utils.util.GradDescentResult`
        with the full loss/params trajectories.
        """
        return _util.simple_grad_descent(
            None, guess=guess, nsteps=nsteps, learning_rate=learning_rate,
            loss_and_grad_func=self.calc_loss_and_grad_from_params,
            has_aux=False)

    def run_adam(self, guess, nsteps=100, param_bounds=None,
                 learning_rate=0.01, randkey=None, const_randkey=False,
                 comm=None, progress=True, checkpoint_dir=None,
                 checkpoint_every=None, telemetry=None,
                 log_every: int = 0, donate_carry=None, flight=None,
                 live=None, alerts=None, diagnostics: bool = False):
        """Adam optimization (parity: ``multigrad.py:259-307``).

        Runs the whole optimization as a single ``lax.scan`` over the
        fused SPMD loss-and-grad program — there is no root/worker
        command protocol to replicate; every step stays on-device.
        Returns the full parameter trajectory, shape
        ``(nsteps+1, ndim)``, on every host.

        With ``checkpoint_dir`` the fit checkpoints restart state
        every ``checkpoint_every`` steps and resumes automatically on
        re-invocation (see :func:`multigrad_tpu.optim.adam
        .run_adam_scan`) — a capability addition over the reference.

        With ``telemetry`` (a :class:`multigrad_tpu.telemetry
        .MetricsLogger`) and ``log_every > 0``, in-graph taps stream
        loss/|grad|/|params|/|update| out of the jitted scan every
        ``log_every`` steps, and a ``comm`` record up front carries
        the trace-time collective accounting — the measured
        O(|sumstats|+|params|) bytes/step (see
        :mod:`multigrad_tpu.telemetry`).

        With ``flight`` (a :class:`multigrad_tpu.telemetry.flight
        .FlightRecorder`) the in-graph non-finite sentinel is armed:
        a NaN/Inf loss or gradient inside the scan dumps a postmortem
        bundle and the fit raises with the bundle path (see
        :func:`multigrad_tpu.optim.adam.run_adam_scan`).

        ``live``/``alerts`` attach the online monitors (the
        ``/metrics``+``/status`` endpoint of
        :class:`multigrad_tpu.telemetry.LiveServer`, the non-fatal
        rules of :class:`multigrad_tpu.telemetry.AlertEngine`) to the
        record stream — they are wired here, before the comm record,
        so the live view carries the bytes-per-step accounting too.
        ``diagnostics=True`` compiles the in-graph convergence
        diagnostics into the fit: the loss-EMA plateau tap
        (``loss_ema``/``loss_ema_slope``) and the gradient-noise-
        scale tap (``grad_noise_scale``/``grad_norm_shard`` — the
        per-shard vs. all-reduced gradient norms the step already
        computes, reduced into the relative shard-gradient variance).
        Like every tap these are static: one extra cached program
        build, zero retraces within and across fits.
        """
        del comm  # SPMD: no per-rank result broadcast needed
        guess = jnp.asarray(
            jnp.stack([jnp.asarray(g) for g in guess])
            if isinstance(guess, tuple) else guess)
        if const_randkey and randkey is None:
            # Explicit raise (not assert): user-facing argument
            # validation must survive `python -O`.
            raise ValueError("Must pass randkey if const_randkey")
        if donate_carry is None:
            # A tuned donation verdict for this model's shape (the
            # autotuner's table) takes precedence over the backend
            # auto rule; None stays None on a cold table and
            # resolve_donate applies the historical default.
            from ..tune.resolve import resolve_donate_carry
            donate_carry = resolve_donate_carry(self)

        from ..telemetry.live import wire_monitoring
        telemetry, log_every, owned = wire_monitoring(
            telemetry, log_every, live, alerts)
        try:
            if telemetry is not None:
                from ..telemetry.comm import measure_model_comm
                cc = measure_model_comm(self, guess, randkey=randkey)
                telemetry.log(
                    "comm", **cc.step_record(scope="loss_and_grad_step"))

            dynamic, _, _ = _split_aux(self.aux_data)
            with_key = randkey is not None
            # diagnostics route through the gns-instrumented kernel,
            # whose wrapper returns (loss, grad, diag) — a separate
            # stable wrapper object, so both variants stay cached.
            # Without a tap (no logger, or log_every=0) nothing would
            # ever emit, so don't pay the instrumented kernel's extra
            # per-step reductions for discarded values.
            diag = bool(diagnostics) and telemetry is not None \
                and log_every > 0
            kind = "loss_and_grad_gns" if diag else "loss_and_grad"
            # The scan wrapper must be a stable function object: the
            # compiled whole-fit executable is cached on its identity
            # (aux leaves travel as runtime args, so data stays fresh).
            cache_key = ("adam_scan_wrapper", with_key, kind)
            if cache_key not in self._program_cache:
                program = self._get_program(kind, with_key)

                def wrapper(p, key, dynamic_leaves):
                    return program(p, dynamic_leaves, key)

                self._program_cache[cache_key] = wrapper

            return _adam.run_adam_scan(
                self._program_cache[cache_key], guess, nsteps=nsteps,
                param_bounds=param_bounds, learning_rate=learning_rate,
                randkey=randkey, const_randkey=const_randkey,
                progress=progress, fn_args=(dynamic,),
                checkpoint_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every,
                telemetry=telemetry, log_every=log_every,
                donate_carry=donate_carry, flight=flight,
                diagnostics=diag, fn_diag=diag)
        finally:
            if owned is not None:
                owned.close()

    def run_bfgs(self, guess, maxsteps=100, param_bounds=None, randkey=None,
                 comm=None, progress=True):
        """L-BFGS-B optimization (parity: ``multigrad.py:310-352``).

        The scipy driver runs identically on every host (its inputs —
        psum results — are replicated, so all hosts follow the same
        control flow); no command protocol exists.  Returns the same
        ``OptimizeResult`` contract as the reference.
        """
        del comm
        return _bfgs.run_bfgs(
            self.calc_loss_and_grad_from_params, guess, maxsteps=maxsteps,
            param_bounds=param_bounds, randkey=randkey, progress=progress)

    def run_lhs_param_scan(self, xmins, xmaxs, n_dim, num_evaluations,
                           seed=None, randkey=None, batched=True):
        """Evaluate sumstats+loss over a Latin-Hypercube sample
        (parity: ``multigrad.py:354-388``).

        Improvement over the reference's Python loop (SURVEY §7.6):
        with ``batched=True`` (default) all ``num_evaluations``
        parameter vectors run through ONE vmapped SPMD program — a
        single device dispatch for the whole scan.  ``batched=False``
        falls back to a per-sample loop for models whose user
        functions are not vmappable.
        """
        params = _util.latin_hypercube_sampler(
            xmins, xmaxs, n_dim, num_evaluations, seed=seed)
        if batched:
            dynamic, _, _ = _split_aux(self.aux_data)
            with_key = randkey is not None
            key = init_randkey(randkey) if with_key else jnp.zeros(())
            program = self._get_program("lhs_batch", with_key)
            sumstats, losses = program(jnp.asarray(params), dynamic, key)
            return params, np.asarray(sumstats), np.asarray(losses)
        loss_kwargs = {} if randkey is None \
            else {"randkey": init_randkey(randkey)}
        sumstats, losses = [], []
        for x in params:
            ss = self.calc_sumstats_from_params(x, randkey=randkey)
            if self.sumstats_func_has_aux:
                # Keep only the sumstats for the stacked return; the
                # loss goes through the fused path so aux is threaded
                # correctly (the reference mis-handles this case,
                # multigrad.py:386-387).
                ss = ss[0]
                loss = self.calc_loss_from_params(x, randkey=randkey)
            else:
                # Total sumstats in hand: the loss is the O(|sumstats|)
                # user function — no second pass over the data.
                loss = self.calc_loss_from_sumstats(ss, **loss_kwargs)
            if self.loss_func_has_aux:
                loss = loss[0]
            sumstats.append(ss)
            losses.append(loss)
        return params, np.array(sumstats), np.array(losses)
