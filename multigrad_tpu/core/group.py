"""Multi-model (MPMD) composition.

Port of ``multigrad.OnePointGroup``
(``/root/reference/multigrad/multigrad.py:547-607``): several
:class:`~multigrad_tpu.core.model.OnePointModel`\\ s, each owning its
own communicator, fit jointly by summing their losses and gradients.

The reference implements this with sub-communicators, per-subcomm-root
zeroing, and a host ``allgather`` (``multigrad.py:571-580``).  Under a
single controller the same semantics collapse to one of two execution
shapes, picked automatically:

* **Fused (same-mesh) path** — when every member's communicator is
  backed by the *same* device mesh (including the common cases: all
  members share one comm, members reduce over different axes of one
  hybrid mesh, or all members are single-device ``comm=None``), the
  joint loss-and-grad compiles into ONE XLA program: each member's
  ``shard_map`` block is inlined into a single ``jit``, and the group
  Adam fit runs the whole optimization as a single ``lax.scan`` with
  zero per-step host round-trips — the same fast path a solo
  :meth:`OnePointModel.run_adam` takes.  (The reference's group step
  is inherently host-interleaved, ``multigrad.py:571-580``; on a
  tunneled TPU runtime that shape is RTT-bound at ~15 steps/s while
  the fused scan sustains thousands.)
* **Host (MPMD) path** — when members own *disjoint* device subsets
  (built with :func:`multigrad_tpu.parallel.split_subcomms`), one
  program per member is dispatched asynchronously before blocking on
  any result, so the sub-meshes genuinely execute concurrently — true
  MPMD task parallelism with no protocol.

Typical setup (mirrors the reference's subcomm pattern)::

    subcomms, n, _ = split_subcomms(num_groups=2)
    smf_model = SMFModel(aux_data=smf_data, comm=subcomms[0])
    wp_model = WpModel(aux_data=wp_data, comm=subcomms[1])
    group = OnePointGroup(models=(smf_model, wp_model))
    result = group.run_bfgs(guess)
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .model import OnePointModel
from ..optim import adam as _adam
from ..optim import bfgs as _bfgs
from ..optim.adam import init_randkey
from ..utils import util as _util


def param_view(model: OnePointModel,
               indices: Sequence[int]) -> OnePointModel:
    """Adapt `model` to read its parameters from a slice of a shared
    joint parameter vector.

    The reference's idiomatic :class:`OnePointGroup` usage feeds every
    component model the *same* params (SURVEY §3.4) — which only works
    when all probes share one parameterization.  ``param_view`` makes
    heterogeneous multi-probe fits (BASELINE config 5: joint SMF +
    wp(rp)) expressible: each component sees
    ``joint_params[indices]``, and the VJP of the gather scatters its
    gradient back into the right slots of the joint gradient.

    ::

        joint = OnePointGroup(models=(
            param_view(smf_model, [0, 1]),    # (log_shmrat, sigma)
            param_view(wp_model, [0, 2]),     # (log_shmrat, softness)
        ))
        joint.run_bfgs(guess=jnp.array([-1.0, 0.5, -0.5]))

    Returns a new model of a derived class; the wrapped model is not
    mutated and can still be used standalone.
    """
    cls = type(model)
    idx = tuple(int(i) for i in indices)
    if idx and min(idx) < 0:
        # jnp.take clamps out-of-range/negative indices under jit, so a
        # negative index would silently read (and scatter gradients to)
        # the wrong joint slot; reject it here instead.
        raise ValueError(
            f"param_view indices must be non-negative, got {idx}")
    if not idx:
        raise ValueError("param_view requires at least one index")

    @dataclass(eq=False, repr=False)
    class _ParamView(cls):
        def calc_partial_sumstats_from_params(self, params,
                                              randkey=None):
            params = jnp.asarray(params)
            if max(idx) >= params.shape[0]:
                raise ValueError(
                    f"param_view indices {idx} out of range for "
                    f"joint parameter vector of length "
                    f"{params.shape[0]}")
            sub = jnp.take(params, jnp.asarray(idx), axis=0)
            if randkey is None:
                # Forward only when present: randkey is optional in
                # the model contract and some models omit it.
                return cls.calc_partial_sumstats_from_params(self, sub)
            return cls.calc_partial_sumstats_from_params(
                self, sub, randkey=randkey)

    _ParamView.__name__ = f"ParamView({cls.__name__}, {idx})"
    field_values = {f.name: getattr(model, f.name)
                    for f in dataclasses.fields(model) if f.init}
    return _ParamView(**field_values)


@dataclass
class OnePointGroup:
    """Sum-of-models joint objective (parity: ``multigrad.py:547-607``).

    Parameters
    ----------
    models : tuple[OnePointModel] | OnePointModel
        The component models.  All receive the *same* parameter vector
        — different probes of one parameter space, exactly the
        reference's idiomatic usage (SURVEY §3.4).
    main_comm : Any, optional
        Accepted for signature parity; the single controller already
        spans all devices, so no umbrella communicator is needed.
    """

    models: Union[Tuple[OnePointModel, ...], OnePointModel]
    main_comm: Any = None

    def __post_init__(self):
        if isinstance(self.models, OnePointModel):
            self.models = (self.models,)
        if not (self.models
                and all(isinstance(m, OnePointModel)
                        for m in self.models)):
            raise TypeError(
                "OnePointGroup.models must be one OnePointModel or a "
                "non-empty tuple of them")
        self._program_cache = {}

    @property
    def fused(self) -> bool:
        """Whether the joint step compiles into one XLA program.

        True when every member's communicator is backed by the same
        device mesh (``comm=None`` members are mesh-agnostic and never
        block fusion).  Members with ``loss_func_has_aux`` keep the
        host path: the group contract sums plain scalar losses
        (parity: ``multigrad.py:571-580``), and threading stacked aux
        values through the fused sum has no reference semantics.
        """
        if any(m.loss_func_has_aux for m in self.models):
            return False
        meshes = [m.comm.mesh for m in self.models if m.comm is not None]
        return all(m == meshes[0] for m in meshes[1:])

    def _get_fused_program(self, with_key: bool):
        """One jitted program: every member's loss-and-grad + the sum.

        Each member's SPMD program (``shard_map`` included) is traced
        inline, so the whole joint step — N sumstats kernels, 2N
        psums, N VJPs, the final sums — is a single XLA computation:
        one dispatch per step, and XLA is free to schedule members'
        collectives and compute concurrently.
        """
        cache_key = ("fused_loss_and_grad", with_key)
        if cache_key not in self._program_cache:
            programs = [m._get_program("loss_and_grad", with_key)
                        for m in self.models]

            def fused(params, all_dynamic, key):
                loss = jnp.zeros((), jnp.result_type(float))
                grad = jnp.zeros_like(jnp.asarray(params))
                for program, dyn in zip(programs, all_dynamic):
                    loss_m, grad_m = program(params, dyn, key)
                    loss = loss + loss_m
                    grad = grad + grad_m
                return loss, grad

            self._program_cache[cache_key] = jax.jit(fused)
        return self._program_cache[cache_key]

    def _all_dynamic(self):
        """Every member's dynamic aux leaves, in member order — the
        runtime arguments of the fused program."""
        return tuple(m.aux_leaves() for m in self.models)

    @staticmethod
    def _as_params(guess):
        return jnp.asarray(
            jnp.stack([jnp.asarray(g) for g in guess])
            if isinstance(guess, tuple) else guess)

    def calc_loss_and_grad_from_params(self, params, randkey=None):
        """Joint loss and gradient: sum over component models.

        Fused groups (every member on one shared mesh, no member with
        ``loss_func_has_aux`` — see :attr:`fused`) run the
        single-program path; all other groups dispatch every model's
        program before blocking on any result so disjoint sub-meshes
        overlap (async MPMD; replaces the zero-and-allgather dance of
        ``multigrad.py:571-580``).
        """
        if self.fused:
            params = self._as_params(params)
            with_key = randkey is not None
            key = init_randkey(randkey) if with_key else jnp.zeros(())
            program = self._get_fused_program(with_key)
            return program(params, self._all_dynamic(), key)
        results = [m.calc_loss_and_grad_from_params(params, randkey=randkey)
                   for m in self.models]
        # Block and sum on host: O(|params|) scalars, negligible.
        # A loss_func_has_aux member returns ((loss, aux), grad); the
        # group contract sums plain scalar losses, so its aux is
        # dropped here (the reference's group crashes on this case —
        # res[0]*0 on a tuple, multigrad.py:576-577).
        loss = sum(np.asarray(r[0][0] if m.loss_func_has_aux else r[0])
                   for m, r in zip(self.models, results))
        grad = sum(np.asarray(r[1]) for r in results)
        return jnp.asarray(loss), jnp.asarray(grad)

    # ------------------------------------------------------------------ #
    # Optimizer proxies (parity: multigrad.py:583-599)
    # ------------------------------------------------------------------ #
    def run_simple_grad_descent(self, guess, nsteps=100, learning_rate=0.01):
        return _util.simple_grad_descent(
            None, guess=guess, nsteps=nsteps, learning_rate=learning_rate,
            loss_and_grad_func=self.calc_loss_and_grad_from_params,
            has_aux=False)

    def run_bfgs(self, guess, maxsteps=100, param_bounds=None, randkey=None,
                 progress=True):
        return _bfgs.run_bfgs(
            self.calc_loss_and_grad_from_params, guess, maxsteps=maxsteps,
            param_bounds=param_bounds, randkey=randkey, progress=progress)

    def run_adam(self, guess, nsteps=100, param_bounds=None,
                 learning_rate=0.01, randkey=None, const_randkey=False,
                 progress=True, checkpoint_dir=None,
                 checkpoint_every=None):
        """Adam over the joint objective.

        Fused groups (see :attr:`fused`: one shared mesh, no
        ``loss_func_has_aux`` member) run the whole fit as one
        ``lax.scan`` over the fused joint program — the identical fast
        path (and preemption-safe ``checkpoint_dir`` machinery) as
        :meth:`OnePointModel.run_adam`.  Non-fused groups fall back
        to the host-loop driver (one async MPMD dispatch round per
        step); same trajectory contract either way.
        """
        guess = self._as_params(guess)
        if const_randkey and randkey is None:
            raise ValueError("Must pass randkey if const_randkey")

        if self.fused:
            with_key = randkey is not None
            cache_key = ("fused_adam_wrapper", with_key)
            if cache_key not in self._program_cache:
                program = self._get_fused_program(with_key)

                def wrapper(p, key, all_dynamic):
                    return program(p, all_dynamic, key)

                self._program_cache[cache_key] = wrapper
            return _adam.run_adam_scan(
                self._program_cache[cache_key], guess, nsteps=nsteps,
                param_bounds=param_bounds, learning_rate=learning_rate,
                randkey=randkey, const_randkey=const_randkey,
                progress=progress, fn_args=(self._all_dynamic(),),
                checkpoint_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every)

        if checkpoint_dir is not None:
            raise ValueError(
                "checkpoint_dir requires the fused group path (every "
                "member on one shared mesh and no member with "
                "loss_func_has_aux — see OnePointGroup.fused); this "
                "group runs the host-loop driver, which does not "
                "checkpoint")
        if const_randkey:
            const_key = _adam.init_randkey(randkey)

            def loss_and_grad_fn(x, _data, **kw):
                return self.calc_loss_and_grad_from_params(
                    x, randkey=const_key)
            randkey = None
        else:
            def loss_and_grad_fn(x, _data, **kw):
                return self.calc_loss_and_grad_from_params(x, **kw)

        return _adam.run_adam(
            loss_and_grad_fn, params=guess, data=None, nsteps=nsteps,
            param_bounds=param_bounds, learning_rate=learning_rate,
            randkey=randkey, progress=progress)

    def check_shard_safety(self, params, **kwargs):
        """Statically verify the group's joint program(s).

        Fused groups are checked as the ONE compiled joint program;
        MPMD groups member-by-member — see
        :func:`multigrad_tpu.analysis.analyze_group`.
        """
        from ..analysis import analyze_group
        return analyze_group(self, params, **kwargs)

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other
