"""Multi-model (MPMD) composition.

Port of ``multigrad.OnePointGroup``
(``/root/reference/multigrad/multigrad.py:547-607``): several
:class:`~multigrad_tpu.core.model.OnePointModel`\\ s, each owning its
own communicator, fit jointly by summing their losses and gradients.

The reference implements this with sub-communicators, per-subcomm-root
zeroing, and a host ``allgather`` (``multigrad.py:571-580``).  Under a
single controller the same semantics collapse to one of two execution
shapes, picked automatically:

* **Fused (same-mesh) path** — when every member's communicator is
  backed by the *same* device mesh (including the common cases: all
  members share one comm, members reduce over different axes of one
  hybrid mesh, or all members are single-device ``comm=None``), the
  joint loss-and-grad compiles into ONE XLA program: each member's
  ``shard_map`` block is inlined into a single ``jit``, and the group
  Adam fit runs the whole optimization as a single ``lax.scan`` with
  zero per-step host round-trips — the same fast path a solo
  :meth:`OnePointModel.run_adam` takes.  (The reference's group step
  is inherently host-interleaved, ``multigrad.py:571-580``; on a
  tunneled TPU runtime that shape is RTT-bound at ~15 steps/s while
  the fused scan sustains thousands.)
* **Host (MPMD) path** — when members own *disjoint* device subsets
  (built with :func:`multigrad_tpu.parallel.split_subcomms`), one
  program per member is dispatched asynchronously before blocking on
  any result, so the sub-meshes genuinely execute concurrently — true
  MPMD task parallelism with no protocol.

Typical setup (mirrors the reference's subcomm pattern)::

    subcomms, n, _ = split_subcomms(num_groups=2)
    smf_model = SMFModel(aux_data=smf_data, comm=subcomms[0])
    wp_model = WpModel(aux_data=wp_data, comm=subcomms[1])
    group = OnePointGroup(models=(smf_model, wp_model))
    result = group.run_bfgs(guess)
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import NamedSharding, PartitionSpec

from .model import OnePointModel, _leaf_spec, _split_aux
from ..parallel._shard_map_compat import shard_map
from ..optim import adam as _adam
from ..optim import bfgs as _bfgs
from ..optim.adam import init_randkey
from ..utils import util as _util


def param_view(model: OnePointModel,
               indices: Sequence[int]) -> OnePointModel:
    """Adapt `model` to read its parameters from a slice of a shared
    joint parameter vector.

    The reference's idiomatic :class:`OnePointGroup` usage feeds every
    component model the *same* params (SURVEY §3.4) — which only works
    when all probes share one parameterization.  ``param_view`` makes
    heterogeneous multi-probe fits (BASELINE config 5: joint SMF +
    wp(rp)) expressible: each component sees
    ``joint_params[indices]``, and the VJP of the gather scatters its
    gradient back into the right slots of the joint gradient.

    ::

        joint = OnePointGroup(models=(
            param_view(smf_model, [0, 1]),    # (log_shmrat, sigma)
            param_view(wp_model, [0, 2]),     # (log_shmrat, softness)
        ))
        joint.run_bfgs(guess=jnp.array([-1.0, 0.5, -0.5]))

    Returns a new model of a derived class; the wrapped model is not
    mutated and can still be used standalone.
    """
    cls = type(model)
    idx = tuple(int(i) for i in indices)
    if idx and min(idx) < 0:
        # jnp.take clamps out-of-range/negative indices under jit, so a
        # negative index would silently read (and scatter gradients to)
        # the wrong joint slot; reject it here instead.
        raise ValueError(
            f"param_view indices must be non-negative, got {idx}")
    if not idx:
        raise ValueError("param_view requires at least one index")

    @dataclass(eq=False, repr=False)
    class _ParamView(cls):
        def calc_partial_sumstats_from_params(self, params,
                                              randkey=None):
            params = jnp.asarray(params)
            if max(idx) >= params.shape[0]:
                raise ValueError(
                    f"param_view indices {idx} out of range for "
                    f"joint parameter vector of length "
                    f"{params.shape[0]}")
            sub = jnp.take(params, jnp.asarray(idx), axis=0)
            if randkey is None:
                # Forward only when present: randkey is optional in
                # the model contract and some models omit it.
                return cls.calc_partial_sumstats_from_params(self, sub)
            return cls.calc_partial_sumstats_from_params(
                self, sub, randkey=randkey)

    _ParamView.__name__ = f"ParamView({cls.__name__}, {idx})"
    field_values = {f.name: getattr(model, f.name)
                    for f in dataclasses.fields(model) if f.init}
    return _ParamView(**field_values)


@dataclass
class OnePointGroup:
    """Sum-of-models joint objective (parity: ``multigrad.py:547-607``).

    Parameters
    ----------
    models : tuple[OnePointModel] | OnePointModel
        The component models.  All receive the *same* parameter vector
        — different probes of one parameter space, exactly the
        reference's idiomatic usage (SURVEY §3.4).
    main_comm : Any, optional
        Accepted for signature parity; the single controller already
        spans all devices, so no umbrella communicator is needed.
    """

    models: Union[Tuple[OnePointModel, ...], OnePointModel]
    main_comm: Any = None

    def __post_init__(self):
        if isinstance(self.models, OnePointModel):
            self.models = (self.models,)
        if not (self.models
                and all(isinstance(m, OnePointModel)
                        for m in self.models)):
            raise TypeError(
                "OnePointGroup.models must be one OnePointModel or a "
                "non-empty tuple of them")
        self._program_cache = {}

    @property
    def fused(self) -> bool:
        """Whether the joint step compiles into one XLA program.

        True when every member's communicator is backed by the same
        device mesh (``comm=None`` members are mesh-agnostic and never
        block fusion).  Members with ``loss_func_has_aux`` keep the
        host path: the group contract sums plain scalar losses
        (parity: ``multigrad.py:571-580``), and threading stacked aux
        values through the fused sum has no reference semantics.
        """
        if any(m.loss_func_has_aux for m in self.models):
            return False
        meshes = [m.comm.mesh for m in self.models if m.comm is not None]
        return all(m == meshes[0] for m in meshes[1:])

    def _get_fused_program(self, with_key: bool):
        """One jitted program: every member's loss-and-grad + the sum.

        Each member's SPMD program (``shard_map`` included) is traced
        inline, so the whole joint step — N sumstats kernels, 2N
        psums, N VJPs, the final sums — is a single XLA computation:
        one dispatch per step, and XLA is free to schedule members'
        collectives and compute concurrently.
        """
        cache_key = ("fused_loss_and_grad", with_key)
        if cache_key not in self._program_cache:
            programs = [m._get_program("loss_and_grad", with_key)
                        for m in self.models]

            def fused(params, all_dynamic, key):
                loss = jnp.zeros((), jnp.result_type(float))
                grad = jnp.zeros_like(jnp.asarray(params))
                for program, dyn in zip(programs, all_dynamic):
                    loss_m, grad_m = program(params, dyn, key)
                    loss = loss + loss_m
                    grad = grad + grad_m
                return loss, grad

            self._program_cache[cache_key] = jax.jit(fused)
        return self._program_cache[cache_key]

    def _all_dynamic(self):
        """Every member's dynamic aux leaves, in member order — the
        runtime arguments of the fused program."""
        return tuple(m.aux_leaves() for m in self.models)

    @staticmethod
    def _as_params(guess):
        return jnp.asarray(
            jnp.stack([jnp.asarray(g) for g in guess])
            if isinstance(guess, tuple) else guess)

    def calc_loss_and_grad_from_params(self, params, randkey=None):
        """Joint loss and gradient: sum over component models.

        Fused groups (every member on one shared mesh, no member with
        ``loss_func_has_aux`` — see :attr:`fused`) run the
        single-program path; all other groups dispatch every model's
        program before blocking on any result so disjoint sub-meshes
        overlap (async MPMD; replaces the zero-and-allgather dance of
        ``multigrad.py:571-580``).
        """
        if self.fused:
            params = self._as_params(params)
            with_key = randkey is not None
            key = init_randkey(randkey) if with_key else jnp.zeros(())
            program = self._get_fused_program(with_key)
            return program(params, self._all_dynamic(), key)
        results = [m.calc_loss_and_grad_from_params(params, randkey=randkey)
                   for m in self.models]
        # Block and sum on host: O(|params|) scalars, negligible.
        # A loss_func_has_aux member returns ((loss, aux), grad); the
        # group contract sums plain scalar losses, so its aux is
        # dropped here (the reference's group crashes on this case —
        # res[0]*0 on a tuple, multigrad.py:576-577).
        loss = sum(np.asarray(r[0][0] if m.loss_func_has_aux else r[0])
                   for m, r in zip(self.models, results))
        grad = sum(np.asarray(r[1]) for r in results)
        return jnp.asarray(loss), jnp.asarray(grad)

    # ------------------------------------------------------------------ #
    # Serving / inference surface (fused groups)
    #
    # A fused group quacks like one OnePointModel to every downstream
    # consumer that composes SPMD programs — the fit-fleet scheduler
    # (multigrad_tpu.serve), the multi-start ensemble driver, HMC and
    # the Fisher/Laplace machinery — so a joint multi-probe likelihood
    # (e.g. SMF + wp(rp) via param_view) can be served, swept, and
    # sampled through exactly the same entry points as a solo model.
    # The contract mirrors OnePointModel's composition hooks:
    # spmd_kernel/wrap_spmd/aux_leaves/batched_loss_and_grad_fn plus
    # the sharded-K topology properties; "params" is always the JOINT
    # parameter vector, and the dynamic-aux argument is the tuple of
    # per-member leaf lists from aux_leaves().
    # ------------------------------------------------------------------ #
    def _require_fused(self):
        if not self.fused:
            raise ValueError(
                "this OnePointGroup is not fused (members on disjoint "
                "meshes, or a member with loss_func_has_aux); the "
                "serving/inference surface (spmd_kernel, wrap_spmd, "
                "batched_loss_and_grad_fn, FitScheduler, HMC) "
                "requires the fused single-program path — see "
                "OnePointGroup.fused")

    @property
    def comm(self):
        """The shared communicator of a fused group: the first
        comm-ful member's (all comm-ful members share one mesh —
        see :attr:`fused`), or ``None`` for an all-single-device
        group."""
        self._require_fused()
        for m in self.models:
            if m.comm is not None:
                return m.comm
        return None

    # The group objective sums plain scalar losses; member-internal
    # aux never crosses the group boundary (fused excludes
    # loss_func_has_aux members outright).
    loss_func_has_aux = False
    sumstats_func_has_aux = False

    def aux_leaves(self):
        """The group's dynamic aux leaves — one tuple of per-member
        leaf lists, in member order — in the argument position the
        raw programs (:meth:`loss_and_grad_fn`,
        :meth:`batched_loss_and_grad_fn`) expect."""
        return self._all_dynamic()

    def spmd_kernel(self, kind: str, with_key: bool = False):
        """The group's per-shard kernel for `kind`, uncompiled: the
        sum of every member's kernel, each fed its own dynamic
        leaves.  Signature ``(params, all_dynamic, key) ->
        (loss[_batch], grad[_batch])``; valid inside one
        ``shard_map`` block over the group's shared mesh (member
        collectives reduce over their own comm axes, which all live
        on that mesh).  Kinds are the loss-and-grad family only —
        the group has no joint sumstats object.
        """
        self._require_fused()
        if kind not in ("loss_and_grad", "batched_loss_and_grad",
                        "batched_loss_and_grad_sharded"):
            raise ValueError(
                f"OnePointGroup.spmd_kernel supports the "
                f"loss-and-grad kinds, got {kind!r}")
        kernels = [m.spmd_kernel(kind, with_key) for m in self.models]

        def local_fn(params, all_dynamic, key):
            loss = grad = None
            for kernel, dyn in zip(kernels, all_dynamic):
                loss_m, grad_m = kernel(params, dyn, key)
                loss = loss_m if loss is None else loss + loss_m
                grad = grad_m if grad is None else grad + grad_m
            return loss, grad

        return local_fn

    def wrap_spmd(self, local_fn, out_specs, n_extra: int = 0,
                  donate_argnums=(), params_spec=None):
        """Compile a per-shard kernel into one SPMD program over the
        group's shared mesh (plain ``jit`` when every member is
        ``comm=None``) — the group twin of
        :meth:`OnePointModel.wrap_spmd`.  ``local_fn(params,
        all_dynamic, key, *extra)`` takes the joint params and the
        tuple-of-leaf-lists from :meth:`aux_leaves`; each member's
        leaves enter under that member's own sharding contract
        (``comm=None`` members' leaves are replicated).
        """
        self._require_fused()
        comm = self.comm
        if comm is None:
            return jax.jit(local_fn, donate_argnums=donate_argnums)
        aux_specs = tuple(
            [_leaf_spec(leaf, m.comm) if m.comm is not None
             else PartitionSpec()
             for leaf in _split_aux(m.aux_data)[0]]
            for m in self.models)
        REP = PartitionSpec()
        p_spec = REP if params_spec is None else params_spec
        mapped = shard_map(
            local_fn, mesh=comm.mesh,
            in_specs=(p_spec, aux_specs, REP) + (REP,) * n_extra,
            out_specs=out_specs)
        return jax.jit(mapped, donate_argnums=donate_argnums)

    def loss_and_grad_fn(self, with_key: bool = False):
        """The raw jitted ``(params, aux_leaves, key) ->
        (loss, grad)`` joint program — scan-compatible; pair with
        :meth:`aux_leaves`."""
        self._require_fused()
        return self._get_fused_program(with_key)

    def batched_loss_and_grad_fn(self, with_key: bool = False,
                                 k_sharded: bool = False):
        """Raw jitted ``(params_batch, aux_leaves, key) ->
        (losses, grads)`` joint program: K joint parameter vectors
        through every member's fused chain rule as ONE dispatch —
        the group twin of
        :meth:`OnePointModel.batched_loss_and_grad_fn`, powering
        served buckets, multi-start ensembles and per-chain HMC
        potentials over a joint likelihood.  ``k_sharded=True``
        partitions the K axis over the mesh's free replica axis
        (which must be free for EVERY member — see
        :attr:`k_shard_axis`)."""
        self._require_fused()
        kind = "batched_loss_and_grad_sharded" if k_sharded \
            else "batched_loss_and_grad"
        cache_key = (kind, with_key)
        if cache_key not in self._program_cache:
            params_spec = None
            if k_sharded:
                axis = self._require_k_shard_axis()
                params_spec = PartitionSpec(axis, None)
                out_specs = (PartitionSpec(axis),
                             PartitionSpec(axis, None))
            else:
                out_specs = (PartitionSpec(), PartitionSpec())
            self._program_cache[cache_key] = self.wrap_spmd(
                self.spmd_kernel(kind, with_key), out_specs,
                params_spec=params_spec)
        return self._program_cache[cache_key]

    # -- sharded-K (2-level mesh) topology ----------------------------- #
    @property
    def k_shard_axis(self):
        """The mesh axis the K batch axis can shard over: an axis
        free (non-reduced) for EVERY comm-ful member — a member's
        reduce axis carries its data collectives, so sharding K over
        it would split that member's sumstats sum.  ``None`` when no
        such axis exists (ordinary one-axis comms, off-mesh
        groups)."""
        self._require_fused()
        free = None
        for m in self.models:
            if m.comm is None:
                continue
            member_free = set(m.comm.free_axes)
            free = member_free if free is None else free & member_free
        if not free:
            return None
        ordered = [a for a in self.comm.mesh.axis_names if a in free]
        return ordered[-1] if ordered else None

    @property
    def k_shard_replicas(self) -> int:
        axis = self.k_shard_axis
        return int(self.comm.mesh.shape[axis]) if axis else 1

    def _require_k_shard_axis(self) -> str:
        axis = self.k_shard_axis
        if axis is None:
            raise ValueError(
                "this group's shared mesh has no axis left free by "
                "every member to shard the K batch axis over; build "
                "the members on a 2-level mesh with multigrad_tpu."
                "parallel.ensemble_comm(n_replicas=R) (see docs/"
                "distributed.md, 'Sharded ensembles')")
        return axis

    def k_sharding(self, ndim: int = 2) -> NamedSharding:
        """NamedSharding partitioning a ``(K, ...)`` array's leading
        axis over the group's replica axis — the group twin of
        :meth:`OnePointModel.k_sharding`."""
        axis = self._require_k_shard_axis()
        return NamedSharding(
            self.comm.mesh,
            PartitionSpec(axis, *([None] * (max(int(ndim), 1) - 1))))

    # ------------------------------------------------------------------ #
    # Optimizer proxies (parity: multigrad.py:583-599)
    # ------------------------------------------------------------------ #
    def run_simple_grad_descent(self, guess, nsteps=100, learning_rate=0.01):
        return _util.simple_grad_descent(
            None, guess=guess, nsteps=nsteps, learning_rate=learning_rate,
            loss_and_grad_func=self.calc_loss_and_grad_from_params,
            has_aux=False)

    def run_bfgs(self, guess, maxsteps=100, param_bounds=None, randkey=None,
                 progress=True):
        return _bfgs.run_bfgs(
            self.calc_loss_and_grad_from_params, guess, maxsteps=maxsteps,
            param_bounds=param_bounds, randkey=randkey, progress=progress)

    def run_adam(self, guess, nsteps=100, param_bounds=None,
                 learning_rate=0.01, randkey=None, const_randkey=False,
                 progress=True, checkpoint_dir=None,
                 checkpoint_every=None):
        """Adam over the joint objective.

        Fused groups (see :attr:`fused`: one shared mesh, no
        ``loss_func_has_aux`` member) run the whole fit as one
        ``lax.scan`` over the fused joint program — the identical fast
        path (and preemption-safe ``checkpoint_dir`` machinery) as
        :meth:`OnePointModel.run_adam`.  Non-fused groups fall back
        to the host-loop driver (one async MPMD dispatch round per
        step); same trajectory contract either way.
        """
        guess = self._as_params(guess)
        if const_randkey and randkey is None:
            raise ValueError("Must pass randkey if const_randkey")

        if self.fused:
            with_key = randkey is not None
            cache_key = ("fused_adam_wrapper", with_key)
            if cache_key not in self._program_cache:
                program = self._get_fused_program(with_key)

                def wrapper(p, key, all_dynamic):
                    return program(p, all_dynamic, key)

                self._program_cache[cache_key] = wrapper
            return _adam.run_adam_scan(
                self._program_cache[cache_key], guess, nsteps=nsteps,
                param_bounds=param_bounds, learning_rate=learning_rate,
                randkey=randkey, const_randkey=const_randkey,
                progress=progress, fn_args=(self._all_dynamic(),),
                checkpoint_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every)

        if checkpoint_dir is not None:
            raise ValueError(
                "checkpoint_dir requires the fused group path (every "
                "member on one shared mesh and no member with "
                "loss_func_has_aux — see OnePointGroup.fused); this "
                "group runs the host-loop driver, which does not "
                "checkpoint")
        if const_randkey:
            const_key = _adam.init_randkey(randkey)

            def loss_and_grad_fn(x, _data, **kw):
                return self.calc_loss_and_grad_from_params(
                    x, randkey=const_key)
            randkey = None
        else:
            def loss_and_grad_fn(x, _data, **kw):
                return self.calc_loss_and_grad_from_params(x, **kw)

        return _adam.run_adam(
            loss_and_grad_fn, params=guess, data=None, nsteps=nsteps,
            param_bounds=param_bounds, learning_rate=learning_rate,
            randkey=randkey, progress=progress)

    def check_shard_safety(self, params, **kwargs):
        """Statically verify the group's joint program(s).

        Fused groups are checked as the ONE compiled joint program;
        MPMD groups member-by-member — see
        :func:`multigrad_tpu.analysis.analyze_group`.
        """
        from ..analysis import analyze_group
        return analyze_group(self, params, **kwargs)

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other
