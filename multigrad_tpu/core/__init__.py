from .model import OnePointModel
from .group import OnePointGroup

__all__ = ["OnePointModel", "OnePointGroup"]
