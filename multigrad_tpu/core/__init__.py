from .model import OnePointModel
from .group import OnePointGroup, param_view

__all__ = ["OnePointModel", "OnePointGroup", "param_view"]
