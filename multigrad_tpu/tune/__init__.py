"""Cost-model-driven autotuner: make the fast path the default path.

The repo's perf knobs — ``bin_mode``/``bin_window`` (fused
scatter-into-bins), chunk size, ``remat_policy``, ``donate_carry``,
serve bucket quantization — are all data- and hardware-dependent:
BENCH_r06's fused-bins A/B is **2.15x at sigma≈0.05 and 0.57x at
sigma≈0.2**, so any hand-set value is a regression on the wrong
workload.  This package closes the loop PR 8's ingredients opened
(:func:`~multigrad_tpu.telemetry.costmodel.model_cost` +
:data:`~multigrad_tpu.telemetry.costmodel.DEVICE_SPECS` +
:func:`~multigrad_tpu.telemetry.costmodel.roofline_record`):

* :mod:`.space` — enumerate the knob space for a model/workload;
* :mod:`.tuner` — **prune statically** (per-candidate roofline
  prediction, zero device FLOPs), **confirm the survivors with short
  measured trials** (warmed, best-of-N, RTT floor subtracted,
  noise-aware ranking on the :mod:`~multigrad_tpu.telemetry.regress`
  tolerance rules), and emit every decision as a ``tune`` telemetry
  record (static prediction AND measured confirmation);
* :mod:`.table` — persist the winner per **(model class,
  catalog-shape bucket, backend, device kind)** in an on-disk
  :class:`TuningTable` beside the XLA compile cache, so a fresh
  process (or a fleet worker sharing the cache volume) starts tuned
  — a warm table resolves every knob with zero measured trials;
* :mod:`.resolve` — the ``"auto"`` hooks consumers call:
  ``bin_mode="auto"`` / ``chunk_size="auto"`` on the models,
  ``chunk_rows="auto"`` / ``remat_policy="auto"`` on streaming,
  ``donate_carry=None`` pickup on fits, ``buckets="auto"`` on the
  serve scheduler.  Cold-table resolution is exactly the historical
  hand-set default — turning on ``"auto"`` can never regress an
  untuned deployment.

One-shot::

    python -m multigrad_tpu.tune          # tune the SMF workload,
                                          # print the TUNE OK receipt

or in process::

    from multigrad_tpu.tune import tune_model
    res = tune_model(model, params, sigma_max=0.32)
    model = model.replace_aux(bin_mode="auto")   # now resolves tuned

Pin any knob to a concrete value to opt out — ``"auto"`` is the only
value resolution touches.
"""
from .table import (TuningTable, default_table_path,  # noqa: F401
                    make_key, model_shape_key, rows_bucket)
from .space import (bucket_candidates,  # noqa: F401
                    model_candidates, streaming_candidates,
                    DEFAULT_BUCKET_CANDIDATES,
                    SHARDED_BUCKET_CANDIDATES)
from .tuner import (TuneResult, tune_model, tune_buckets,  # noqa
                    tune_streaming, within_noise, measure_rtt)
from .resolve import (resolve_auto_aux,  # noqa: F401
                      resolve_buckets, resolve_donate_carry,
                      resolve_op_bin_mode, resolve_stream_knobs)

__all__ = [
    "TuningTable", "default_table_path", "make_key",
    "model_shape_key", "rows_bucket",
    "model_candidates", "streaming_candidates",
    "bucket_candidates", "DEFAULT_BUCKET_CANDIDATES",
    "SHARDED_BUCKET_CANDIDATES",
    "TuneResult", "tune_model", "tune_buckets", "tune_streaming",
    "within_noise", "measure_rtt",
    "resolve_auto_aux", "resolve_buckets", "resolve_donate_carry",
    "resolve_op_bin_mode", "resolve_stream_knobs",
]
