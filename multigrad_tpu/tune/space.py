"""Knob-space enumeration: which configurations a model can run as.

A **candidate** is a plain dict of knob values — aux knobs
(``bin_mode``/``bin_window``/``chunk_size``) applied via
``model.replace_aux`` and fit knobs (``donate_carry``) forwarded to
the optimizer — with the hand-set default always candidate 0 (the
tuner force-includes it in the measured-confirm stage, so a tuned
pick can never silently regress the default it replaces).

The space is deliberately data- and hardware-aware rather than a raw
cross product:

* fused-bin candidates exist only when the model has a concrete bin
  grid and a ``sigma_max`` to derive the float32-exact window from
  (:func:`multigrad_tpu.ops.binned.fused_bin_window`);
* chunk-size candidates only appear at row counts where chunking is a
  real memory/speed tradeoff (a 10k-halo fit has nothing to chunk);
* donation candidates only appear on backends where donation is real
  (TPU/GPU) — on CPU it is a designed no-op and measuring it would
  only burn trial budget (BENCH_r06's ``adam_donated`` A/B: 0.99x).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["model_candidates", "streaming_candidates",
           "DEFAULT_BUCKET_CANDIDATES",
           "SHARDED_BUCKET_CANDIDATES", "bucket_candidates",
           "find_bin_edges", "MAX_CANDIDATES"]

#: Bucket-size candidates for the serve-scheduler ladder search.
DEFAULT_BUCKET_CANDIDATES = (1, 2, 4, 8, 16, 32, 64)

#: The extended rungs a sharded-K mesh unlocks: with the batch (and
#: both Adam moment sets) partitioned K/R per device, buckets past
#: the replicated ceiling become runnable — the tuner measures them
#: instead of stopping at a hardcoded max.
SHARDED_BUCKET_CANDIDATES = DEFAULT_BUCKET_CANDIDATES + (128, 256)


def bucket_candidates(model, nsteps: int, ndim: int = 2,
                      k_sharded: bool = False,
                      budget_bytes=None) -> tuple:
    """The bucket-size candidate set for one model/workload: the
    sharded ladder when the K axis shards, capped by the sharded-K
    memory model (:func:`~multigrad_tpu.inference.max_k_for_budget`)
    when a per-device budget is given — the cap is *derived*, never
    a hardcoded max.  Each rung is judged under the layout it would
    actually run (the tuner's dispatch rule: only rungs the replica
    count divides run K-partitioned; indivisible rungs run
    replicated at full per-device state, so the sharded cap must not
    admit them).  The smallest rung always survives."""
    from ..inference.ensemble import k_shards_bucket, max_k_for_budget

    cands = SHARDED_BUCKET_CANDIDATES if k_sharded \
        else DEFAULT_BUCKET_CANDIDATES
    if budget_bytes is None:
        return cands
    n_replicas = model.k_shard_replicas if k_sharded else 1
    cap_rep = max_k_for_budget(int(budget_bytes), int(ndim),
                               int(nsteps))
    cap_sh = max_k_for_budget(int(budget_bytes), int(ndim),
                              int(nsteps), n_replicas=n_replicas) \
        if k_sharded else cap_rep
    kept = tuple(
        b for b in cands
        if b <= (cap_sh if k_shards_bucket(b, k_sharded, n_replicas)
                 else cap_rep))
    return kept or cands[:1]

#: Cap on the enumerated cross product (the static prune keeps the
#: measured stage short anyway; the cap bounds the trace budget).
MAX_CANDIDATES = 16

#: Chunk the particle axis only above this many per-shard rows — below
#: it the whole catalog is one comfortable block and every chunk
#: candidate is pure scan overhead.
_CHUNK_MIN_ROWS = 1 << 19


def find_bin_edges(aux_data) -> Optional[np.ndarray]:
    """The model's concrete bin grid, if it has one (the shipped
    models store it under ``bin_edges`` / ``smf_bin_edges``)."""
    if not isinstance(aux_data, dict):
        return None
    for key in ("bin_edges", "smf_bin_edges"):
        edges = aux_data.get(key)
        if edges is not None:
            return np.asarray(edges)
    return None


def _chunk_candidates(n_rows: int, current) -> list:
    """Chunk sizes worth trying at this scale (always includes the
    current setting first — the hand-set default)."""
    out = [current]
    if n_rows >= _CHUNK_MIN_ROWS:
        for c in (1 << 18, 1 << 20, 1 << 22):
            if c < n_rows and c != current:
                out.append(c)
    return out


def model_candidates(model, params=None, sigma_max=None,
                     backend: Optional[str] = None) -> list:
    """Enumerate the knob space for an
    :class:`~multigrad_tpu.core.model.OnePointModel`.

    Returns a list of candidate dicts (default first), each with the
    keys ``bin_mode``, ``bin_window``, ``chunk_size`` (aux knobs) and
    ``donate_carry`` (fit knob).  ``sigma_max`` bounds the smoothing
    width the fit can reach (read from ``aux_data["sigma_max"]`` when
    not passed); without it no fused candidate is generated — the
    window would not be provably float32-exact.
    """
    from .table import catalog_rows

    if backend is None:
        import jax
        backend = jax.default_backend()
    aux = model.aux_data if isinstance(model.aux_data, dict) else {}
    n_rows = catalog_rows(aux, getattr(model, "comm", None))
    edges = find_bin_edges(aux)
    if sigma_max is None:
        sigma_max = aux.get("sigma_max")

    cur_mode = aux.get("bin_mode", "dense")
    cur_window = aux.get("bin_window")
    if cur_mode == "auto":            # tuning resolves "auto" itself
        cur_mode = "dense"
    bin_cands = [(cur_mode, cur_window if cur_mode == "fused"
                  else None)]
    if edges is not None and sigma_max is not None:
        from ..ops.binned import fused_bin_window
        window = fused_bin_window(edges, float(sigma_max))
        for cand in (("dense", None), ("fused", window)):
            if cand not in bin_cands:
                bin_cands.append(cand)

    chunk_cands = _chunk_candidates(n_rows, aux.get("chunk_size"))
    donate_cands = [None] if backend not in ("tpu", "gpu") \
        else [None, True, False]

    out = []
    for mode, window in bin_cands:
        for chunk in chunk_cands:
            for donate in donate_cands:
                out.append({"bin_mode": mode, "bin_window": window,
                            "chunk_size": chunk,
                            "donate_carry": donate})
                if len(out) >= MAX_CANDIDATES:
                    return out
    return out


def streaming_candidates(smodel, use_scan: bool = False) -> list:
    """Enumerate the knob space for a
    :class:`~multigrad_tpu.data.StreamingOnePointModel`:
    ``chunk_rows`` always (powers of two around the current setting),
    ``remat_policy`` only with ``use_scan=True`` (the per-chunk
    checkpoint exists only in the single-dispatch scan path)."""
    n_rows = smodel.n_rows
    current = int(smodel.chunk_rows)
    rows_cands = [current]
    for c in (current // 4, current * 4):
        if 1024 <= c < n_rows and c not in rows_cands:
            rows_cands.append(c)
    remat_cands = [smodel.remat_policy]
    if use_scan:
        for policy in ("dots", "nothing", "everything"):
            if policy not in remat_cands:
                remat_cands.append(policy)
    out = []
    for rows in rows_cands:
        for policy in remat_cands:
            out.append({"chunk_rows": rows, "remat_policy": policy})
            if len(out) >= MAX_CANDIDATES:
                return out
    return out
