"""``"auto"`` knob resolution: the read side of the tuning table.

These hooks are what makes the tuner's output the *default* path:
consumers ask for ``"auto"`` and get the tuned winner when the table
holds one, or the historical hand-set default when it does not —
resolution **never** raises and never changes behavior on a cold
table.

Who calls what:

* :class:`~multigrad_tpu.models.smf.SMFModel` /
  :class:`~multigrad_tpu.models.galhalo_hist.GalhaloHistModel`
  ``__post_init__`` → :func:`resolve_auto_aux` — rewrites
  ``bin_mode="auto"`` / ``chunk_size="auto"`` to concrete values
  before any program is built (knobs stay static in the compiled
  program; resolution happens once per model construction, outside
  any trace).
* :meth:`~multigrad_tpu.core.model.OnePointModel.run_adam` →
  :func:`resolve_donate_carry` — a ``donate_carry=None`` fit picks
  up a tuned donation verdict before falling back to the backend
  auto rule.
* :class:`~multigrad_tpu.data.StreamingOnePointModel`
  ``__post_init__`` → :func:`resolve_stream_knobs` —
  ``chunk_rows="auto"`` / ``remat_policy="auto"``.
* :class:`~multigrad_tpu.serve.FitScheduler` (and fleet workers) →
  :func:`resolve_buckets` — ``buckets="auto"`` becomes the measured
  fits/hour ladder, or ``DEFAULT_BUCKETS`` cold.
* :func:`~multigrad_tpu.ops.binned.binned_erf_counts` →
  :func:`resolve_op_bin_mode` — the standalone-op fallback (models
  resolve first under their class-named key; a direct op call with
  ``bin_mode="auto"`` resolves under the op's own key, dense cold).

All lookups are tracer-safe (only *shapes* are read off aux leaves)
and wrapped: any table problem — missing file, torn write, version
skew — degrades to the hand-set default silently.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from .table import (TuningTable, catalog_rows, make_key,
                    model_shape_key)

__all__ = ["resolve_auto_aux", "resolve_donate_carry",
           "resolve_stream_knobs", "resolve_buckets",
           "resolve_op_bin_mode", "aux_model_key",
           "DEFAULT_STREAM_CHUNK_ROWS"]

#: Cold-table fallback for ``chunk_rows="auto"`` (bounded, power of
#: two; a catalog smaller than this streams as one chunk).
DEFAULT_STREAM_CHUNK_ROWS = 1 << 20


def _table(table) -> TuningTable:
    return table if isinstance(table, TuningTable) else \
        TuningTable(table)


def _edges_count(aux: dict) -> Optional[int]:
    """Edge count of the model's bin grid, shape-only (tracer-safe)."""
    for key in ("bin_edges", "smf_bin_edges"):
        e = aux.get(key)
        if e is not None:
            shape = getattr(e, "shape", None)
            if shape is None:
                shape = np.shape(e)
            return int(shape[0]) if shape else None
    return None


def aux_model_key(model_name: str, aux: dict, comm=None,
                  bin_window=None, backend=None,
                  device_kind=None) -> str:
    """The ``model``-kind table key for an aux configuration (write
    and read sides share this; see :func:`~multigrad_tpu.tune.tuner
    .model_key`)."""
    n_rows = catalog_rows(aux, comm)
    n_edges = _edges_count(aux)
    if bin_window is None:
        bin_window = aux.get("bin_window")
    if bin_window is None and aux.get("sigma_max") is not None:
        # Mirror the write side (tuner.model_key): an aux carrying a
        # sigma bound but no stored window — e.g. built with the
        # default dense mode — keys under the window that bound
        # derives, not 0, so read and write can never disagree.
        try:
            from ..ops.binned import fused_bin_window
            from .space import find_bin_edges
            edges = find_bin_edges(aux)
            if edges is not None:
                bin_window = fused_bin_window(
                    edges, float(aux["sigma_max"]))
        except Exception:
            pass
    window = int(bin_window) if isinstance(bin_window, (int,
                                                        np.integer)) \
        else 0
    return make_key("model", model_name,
                    model_shape_key(n_rows, n_edges,
                                    window if n_edges else None),
                    backend, device_kind)


def _model_knobs(model_name: str, aux: dict, comm,
                 table) -> Tuple[dict, str]:
    key = aux_model_key(model_name, aux, comm)
    entry = _table(table).lookup(key)
    return (dict(entry.get("knobs", {})) if entry else {}), key


def resolve_auto_aux(model_name: str, aux, comm=None,
                     table=None):
    """Rewrite any ``"auto"`` aux knobs to concrete values.

    Returns `aux` unchanged (same object) when nothing is ``"auto"``
    — the hot path for every in-trace ``dataclasses.replace`` — or a
    new dict with ``bin_mode``/``chunk_size`` resolved from the
    tuning table (``bin_mode`` → ``"dense"`` cold, ``chunk_size`` →
    ``None`` cold: the historical defaults).
    """
    if not isinstance(aux, dict):
        return aux
    auto_bin = aux.get("bin_mode") == "auto"
    auto_chunk = aux.get("chunk_size") == "auto"
    if not (auto_bin or auto_chunk):
        return aux
    try:
        knobs, _key = _model_knobs(model_name, aux, comm, table)
    except Exception:
        knobs = {}
    out = dict(aux)
    if auto_bin:
        mode = knobs.get("bin_mode", "dense")
        out["bin_mode"] = mode if mode in ("dense", "fused") \
            else "dense"
        if out["bin_mode"] == "fused":
            window = knobs.get("bin_window") or aux.get("bin_window")
            if window:
                out["bin_window"] = int(window)
            else:                    # no exact window derivable
                out["bin_mode"] = "dense"
    if auto_chunk:
        chunk = knobs.get("chunk_size")
        out["chunk_size"] = int(chunk) if chunk else None
    return out


def resolve_donate_carry(model, table=None):
    """Tuned ``donate_carry`` verdict for this model's key, or
    ``None`` (→ the backend auto rule in
    :func:`~multigrad_tpu.optim.adam.resolve_donate`)."""
    try:
        aux = model.aux_data if isinstance(model.aux_data, dict) \
            else {}
        knobs, _ = _model_knobs(type(model).__name__, aux,
                                getattr(model, "comm", None), table)
        donate = knobs.get("donate_carry")
        return bool(donate) if donate is not None else None
    except Exception:
        return None


def resolve_stream_knobs(model_name: str, n_rows: int, comm=None,
                         chunk_rows="auto", remat_policy="auto",
                         table=None) -> Tuple[int, object]:
    """Concrete ``(chunk_rows, remat_policy)`` for a streaming model.
    Cold fallbacks: ``min(n_rows, DEFAULT_STREAM_CHUNK_ROWS)`` and
    ``"dots"`` (the historical defaults)."""
    knobs = {}
    try:
        per_shard = max(1, int(n_rows) //
                        (comm.size if comm is not None else 1))
        key = make_key("stream", model_name,
                       model_shape_key(per_shard))
        entry = _table(table).lookup(key)
        knobs = dict(entry.get("knobs", {})) if entry else {}
    except Exception:
        pass
    if chunk_rows == "auto":
        chunk_rows = int(knobs.get("chunk_rows") or
                         min(int(n_rows), DEFAULT_STREAM_CHUNK_ROWS))
    if remat_policy == "auto":
        remat_policy = knobs.get("remat_policy", "dots")
    return int(chunk_rows), remat_policy


def resolve_buckets(model, table=None) -> tuple:
    """The serve scheduler's bucket ladder for this model: the
    measured fits/hour ladder :func:`~multigrad_tpu.tune.tuner
    .tune_buckets` persisted, or the hardcoded
    :data:`~multigrad_tpu.serve.compile_cache.DEFAULT_BUCKETS`
    cold."""
    from ..serve.compile_cache import DEFAULT_BUCKETS

    try:
        aux = model.aux_data if isinstance(model.aux_data, dict) \
            else {}
        shape = model_shape_key(
            catalog_rows(aux, getattr(model, "comm", None)))
        key = make_key("buckets", type(model).__name__, shape)
        entry = _table(table).lookup(key)
        if entry:
            buckets = entry.get("knobs", {}).get("buckets")
            if buckets:
                return tuple(sorted(set(int(b) for b in buckets)))
    except Exception:
        pass
    return DEFAULT_BUCKETS


def resolve_op_bin_mode(n_values: int, n_edges: int, bin_window,
                        table=None) -> Tuple[str, Optional[int]]:
    """Standalone-op ``bin_mode="auto"`` resolution for
    :func:`~multigrad_tpu.ops.binned.binned_erf_counts` (model-level
    resolution normally runs first and rewrites the knob; this covers
    direct op calls).  Dense cold, or without a static window."""
    try:
        window = int(bin_window) if bin_window else 0
        key = make_key("model", "binned_erf_counts",
                       model_shape_key(int(n_values), int(n_edges),
                                       window))
        entry = _table(table).lookup(key)
        knobs = dict(entry.get("knobs", {})) if entry else {}
        mode = knobs.get("bin_mode", "dense")
        if mode == "fused":
            window = int(knobs.get("bin_window") or window)
            if window >= 2:
                return "fused", window
        return "dense", (int(bin_window) if bin_window else None)
    except Exception:
        return "dense", (int(bin_window) if bin_window else None)
