"""On-disk tuning table: persisted autotuner decisions, shared like
the compile cache.

The persistence half of the autotuner (:mod:`.tuner` writes,
:mod:`.resolve` reads): one JSON file mapping **tuning keys** —
``(kind, model class, catalog-shape bucket, backend, device kind)``
flattened to a string — to the winning knob set plus provenance (the
static prediction, the measured confirmation, trial counts, versions).
The default location is **beside the persistent XLA compile cache**
(``<cache_dir>.tuning.json``): the two files are the same kind of
asset — a warm start for a fresh process — and a fleet of workers
sharing the compile cache shares the tuning table automatically (the
fleet-wide warm asset).  Override with the ``MGT_TUNING_TABLE``
environment variable or an explicit path.

Shape bucketing: catalog sizes are keyed by ``round(log2(rows))`` (a
1e6-row catalog and a 1.3e6-row one share an entry; 1e6 and 1e8 do
not), rows are **per shard** (``global rows / comm.size`` — the same
denominator the static cost model uses), and binned-kernel keys carry
the edge count and the derived fused window, because the
window-to-grid ratio is exactly what flips the fused-vs-dense verdict
(BENCH_r06: 2.15x at window 10/41, 0.57x at 33/41 — same model, same
rows, different sigma regime, different key).

Concurrency: writes are read-merge-replace with an atomic
``os.replace`` — two processes tuning different keys both land; two
processes racing the *same* key keep one winner (either is a valid
measurement).  Reads re-load on mtime change, so a long-lived serving
process sees entries a tuner process adds later.
"""
from __future__ import annotations

import json
import math
import os
import tempfile
import time
from typing import Optional

import numpy as np

__all__ = ["TuningTable", "default_table_path", "make_key",
           "rows_bucket", "model_shape_key", "catalog_rows",
           "device_kind_tag", "TABLE_VERSION"]

TABLE_VERSION = 1

#: Environment override for the default table location (tests set it
#: to keep tier-1 hermetic; fleets set it to a shared volume).
ENV_TABLE = "MGT_TUNING_TABLE"


def default_table_path() -> str:
    """The table's default home: beside the persistent XLA compile
    cache (``<cache_dir>.tuning.json``), falling back to the same
    stable per-machine tempdir location
    :func:`~multigrad_tpu.serve.compile_cache.enable_compile_cache`
    defaults to.  ``MGT_TUNING_TABLE`` overrides both."""
    env = os.environ.get(ENV_TABLE)
    if env:
        return env
    cache_dir = None
    try:
        import jax
        cache_dir = getattr(jax.config, "jax_compilation_cache_dir",
                            None)
    except Exception:
        pass
    if not cache_dir:
        cache_dir = os.path.join(tempfile.gettempdir(),
                                 "multigrad_tpu_jax_cache")
    return str(cache_dir).rstrip("/\\") + ".tuning.json"


def rows_bucket(n_rows: int) -> int:
    """Catalog-shape bucket of a row count: ``round(log2(rows))``."""
    return int(round(math.log2(max(int(n_rows), 1))))


def device_kind_tag(device_kind: Optional[str] = None) -> str:
    """Normalized device-kind tag (default: the backend's first
    device), safe to embed in a key string."""
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:
            device_kind = "unknown"
    return str(device_kind).strip().lower().replace(" ", "_")


def _backend_tag(backend: Optional[str] = None) -> str:
    if backend is None:
        try:
            import jax
            backend = jax.default_backend()
        except Exception:
            backend = "unknown"
    return str(backend)


def make_key(kind: str, model: str, shape: str,
             backend: Optional[str] = None,
             device_kind: Optional[str] = None) -> str:
    """Flatten key components to the table's string key form:
    ``kind|model|shape|backend|device_kind``."""
    return "|".join((kind, model, shape, _backend_tag(backend),
                     device_kind_tag(device_kind)))


def catalog_rows(aux_data, comm=None) -> int:
    """Per-shard catalog rows of a model's aux pytree: the largest
    leading dimension among its array leaves, divided by the comm
    size (the per-device denominator every cost in this repo uses).
    Tracer-safe — only shapes are read."""
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(aux_data)
    except Exception:
        leaves = aux_data if isinstance(aux_data, (list, tuple)) else []
    rows = 0
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is None and isinstance(leaf, np.ndarray):
            shape = leaf.shape
        if shape:
            rows = max(rows, int(shape[0]))
    if comm is not None and getattr(comm, "size", 1):
        rows = max(1, rows // int(comm.size))
    return rows


def model_shape_key(n_rows: int, n_edges: Optional[int] = None,
                    bin_window: Optional[int] = None) -> str:
    """Catalog-shape bucket string for model-knob keys.

    ``rows2^B`` always; ``|e{E}|w{W}`` when the model runs the binned
    kernels (the window — derived from the fit's ``sigma_max`` — is
    the sigma-regime discriminator; see the module docstring)."""
    shape = f"rows2^{rows_bucket(n_rows)}"
    if n_edges is not None:
        shape += f"|e{int(n_edges)}"
        shape += f"|w{int(bin_window)}" if bin_window else "|w0"
    return shape


class TuningTable:
    """One on-disk tuning table (see module docstring).

    Parameters
    ----------
    path : str, optional
        Table file.  Default: :func:`default_table_path` — beside the
        XLA compile cache, shared by every process that shares the
        cache.
    """

    def __init__(self, path: Optional[str] = None):
        self.path = os.path.abspath(path or default_table_path())
        self._entries: dict = {}
        self._mtime: Optional[float] = None

    # -------------------------------------------------------------- #
    def _load(self) -> dict:
        try:
            mtime = os.path.getmtime(self.path)
        except OSError:
            self._entries, self._mtime = {}, None
            return self._entries
        if mtime != self._mtime:
            try:
                with open(self.path) as f:
                    raw = json.load(f)
                entries = raw.get("entries", {})
                self._entries = entries if isinstance(entries, dict) \
                    else {}
            except (OSError, ValueError):
                # A torn/corrupt table is a cache miss, never a crash:
                # the tuner re-measures and the next write repairs it.
                self._entries = {}
            self._mtime = mtime
        return self._entries

    def entries(self) -> dict:
        """All entries, freshly loaded (re-read on mtime change)."""
        return dict(self._load())

    def lookup(self, key: str) -> Optional[dict]:
        """The entry for `key`, or ``None`` (a miss resolves to the
        hand-set default — lookups must never fail a model build)."""
        try:
            return self._load().get(key)
        except Exception:
            return None

    def record(self, key: str, knobs: dict, **meta) -> dict:
        """Persist a winning knob set under `key` (read-merge-replace,
        atomic).  ``meta`` carries provenance (``predicted_s``,
        ``measured_s``, ``baseline_s``, ``trials``, ...).  Returns the
        stored entry."""
        entry = {"knobs": dict(knobs), "created": time.time(),
                 "table_version": TABLE_VERSION}
        entry.update(meta)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        # Serialize the read-merge-replace across processes: without
        # the lock, two workers cold-tuning DIFFERENT keys can load
        # the same base state and the second os.replace silently
        # drops the first one's entry (defeating the fleet-wide
        # zero-trial warm start the module docstring promises).
        lock_fd = None
        try:
            import fcntl
            lock_fd = os.open(self.path + ".lock",
                              os.O_CREAT | os.O_RDWR, 0o644)
            fcntl.flock(lock_fd, fcntl.LOCK_EX)
        except Exception:       # no fcntl / unlockable fs: best-effort
            if lock_fd is not None:
                os.close(lock_fd)
                lock_fd = None
        try:
            # Merge against the freshest on-disk state (under the
            # lock, so concurrent tuners of different keys all land).
            self._mtime = None
            merged = dict(self._load())
            merged[key] = entry
            payload = {"table_version": TABLE_VERSION,
                       "entries": merged}
            fd, tmp = tempfile.mkstemp(
                prefix=os.path.basename(self.path) + ".",
                dir=os.path.dirname(self.path) or ".")
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(payload, f, indent=1, sort_keys=True)
                os.replace(tmp, self.path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        finally:
            if lock_fd is not None:
                os.close(lock_fd)       # releases the flock
        self._entries, self._mtime = merged, None
        return entry

    def __len__(self):
        return len(self._load())

    def __repr__(self):
        return f"TuningTable({self.path!r}, {len(self)} entries)"
