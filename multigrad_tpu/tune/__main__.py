"""Autotuner CLI: ``python -m multigrad_tpu.tune``.

Tunes a shipped workload end to end — model knobs (static prune →
measured confirm) and, with ``--tune-buckets``, the serve scheduler's
bucket ladder — persists the winners in the on-disk tuning table, then
**proves resolution**: the same model rebuilt with ``bin_mode="auto"``
/ ``chunk_size="auto"`` must resolve to the tuned knobs, and a
:class:`~multigrad_tpu.serve.FitScheduler` booted with
``buckets="auto"`` must come up on the tuned ladder.  Exits nonzero
(no ``TUNE OK`` receipt) if any of that fails — the CI smoke greps
the receipt.

A second invocation against the same table is the warm-start proof:
every knob resolves with **zero measured trials** (``warm=True`` in
the receipt lines).
"""
from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m multigrad_tpu.tune",
        description="Two-stage autotuner: static cost-model prune, "
                    "short measured confirm, on-disk tuning table.")
    ap.add_argument("--model", default="smf",
                    choices=("smf", "galhalo_hist"),
                    help="workload to tune (default: smf)")
    ap.add_argument("--num-halos", type=int, default=100_000)
    ap.add_argument("--table", default=None,
                    help="tuning-table path (default: beside the XLA "
                         "compile cache; MGT_TUNING_TABLE overrides)")
    ap.add_argument("--telemetry", default=None,
                    help="JSONL path for tune records")
    ap.add_argument("--sigma-max", type=float, default=None,
                    help="largest smoothing width the fit can reach "
                         "(bounds the fused window; default: the "
                         "workload's bench convention)")
    ap.add_argument("--trial", default=None,
                    choices=("eval", "fit"),
                    help="trial shape (default: auto)")
    ap.add_argument("--trial-steps", type=int, default=8)
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--top-k", type=int, default=3)
    ap.add_argument("--force", action="store_true",
                    help="re-measure even on a warm table")
    ap.add_argument("--tune-buckets", action="store_true",
                    help="also tune the serve bucket ladder from "
                         "measured fits/hour")
    ap.add_argument("--bucket-candidates", default="1,2,4,8,16",
                    help="comma list of bucket sizes to measure")
    ap.add_argument("--bucket-nsteps", type=int, default=20)
    ap.add_argument("--json", action="store_true",
                    help="emit the results as one JSON object")
    args = ap.parse_args(argv)

    import jax.numpy as jnp
    import numpy as np

    from . import TuningTable, tune_buckets, tune_model
    from .resolve import resolve_buckets

    table = TuningTable(args.table)
    telemetry = None
    if args.telemetry:
        from ..telemetry import JsonlSink, MetricsLogger
        telemetry = MetricsLogger(
            JsonlSink(args.telemetry),
            run_config={"tool": "tune", "table": table.path})

    if args.model == "smf":
        from ..models.smf import SMFModel, make_smf_data
        sigma_max = args.sigma_max if args.sigma_max is not None \
            else 0.6
        aux = make_smf_data(args.num_halos, sigma_max=sigma_max)
        model = SMFModel(aux_data=aux)
        params = jnp.array([-1.0, 0.5])
    else:
        from ..models.galhalo_hist import (GalhaloHistModel, TRUTH,
                                           make_galhalo_hist_data)
        sigma_max = args.sigma_max if args.sigma_max is not None \
            else 0.32
        aux = make_galhalo_hist_data(args.num_halos,
                                     sigma_max=sigma_max)
        model = GalhaloHistModel(aux_data=aux)
        params = jnp.asarray(np.asarray(TRUTH))

    out = {"table": table.path, "model": type(model).__name__}
    ok = True

    res = tune_model(
        model, params, sigma_max=sigma_max, table=table,
        telemetry=telemetry, top_k=args.top_k, reps=args.reps,
        trial_steps=args.trial_steps, trial=args.trial,
        force=args.force)
    out["model_knobs"] = {
        "key": res.key, "chosen": res.chosen, "warm": res.warm,
        "trials": res.n_trials, "predicted_s": res.predicted_s,
        "measured_s": res.measured_s,
        "baseline_s": res.baseline_s}
    print(f"TUNE model={type(model).__name__} key={res.key} "
          f"chosen={json.dumps(res.chosen)} warm={res.warm} "
          f"trials={res.n_trials}", file=sys.stderr)

    # Resolution proof: an "auto" model must come up on the tuned
    # knobs (this is the exact path a production consumer takes).
    auto_aux = dict(aux, bin_mode="auto", chunk_size="auto")
    auto_model = type(model)(aux_data=auto_aux, comm=model.comm)
    resolved = {k: auto_model.aux_data.get(k)
                for k in ("bin_mode", "bin_window", "chunk_size")}
    out["resolved_aux"] = resolved
    for knob in ("bin_mode", "chunk_size"):
        want = res.chosen.get(knob)
        got = resolved.get(knob)
        if knob == "bin_mode" and want is not None and got != want:
            ok = False
        if knob == "chunk_size" and want is not None \
                and (got or None) != (want or None):
            ok = False
    print(f"TUNE resolve bin_mode=auto -> {resolved}",
          file=sys.stderr)

    if args.tune_buckets:
        candidates = tuple(int(b) for b
                           in args.bucket_candidates.split(","))
        bres = tune_buckets(
            model, np.asarray(params), candidates=candidates,
            nsteps=args.bucket_nsteps, reps=args.reps, table=table,
            telemetry=telemetry, force=args.force)
        ladder = resolve_buckets(model, table=table)
        out["buckets"] = {
            "key": bres.key, "chosen": bres.chosen,
            "warm": bres.warm, "resolved": list(ladder),
            "fits_per_hour": {
                str(c["knobs"]["bucket"]): c.get("fits_per_hour")
                for c in bres.candidates}}
        print(f"TUNE buckets key={bres.key} "
              f"ladder={json.dumps(bres.chosen.get('buckets'))} "
              f"warm={bres.warm}", file=sys.stderr)
        if tuple(ladder) != tuple(sorted(set(
                bres.chosen.get("buckets", [])))):
            ok = False
        # Boot proof: the serve scheduler must come up tuned.
        from ..serve.scheduler import FitScheduler
        sched = FitScheduler(model, buckets="auto",
                             tuning_table=table, start=False)
        out["scheduler_buckets"] = list(sched.buckets)
        print(f"TUNE scheduler boots buckets={list(sched.buckets)}",
              file=sys.stderr)
        if sched.buckets != tuple(ladder):
            ok = False
        sched.close(drain=False)

    if telemetry is not None:
        telemetry.close()
    if args.json:
        print(json.dumps(out, indent=1, default=str))
    if not ok:
        print("TUNE FAILED: resolution disagrees with the tuned "
              "table", file=sys.stderr)
        return 1
    print("TUNE OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
