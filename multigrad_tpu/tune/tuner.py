"""The two-stage autotuner: static prune, measured confirm, persist.

The loop the ROADMAP names ("close the loop: predict per-config costs
statically, confirm the top candidates with short measured trials,
persist the winning config beside the compile cache"):

1. **Enumerate** the knob space for a model/workload
   (:mod:`.space`): ``bin_mode``/``bin_window``, chunk size, carry
   donation — plus serve bucket quantization via
   :func:`tune_buckets` and streaming knobs via
   :func:`tune_streaming`.
2. **Prune statically**: every candidate is traced (zero device
   FLOPs) through :func:`~multigrad_tpu.telemetry.costmodel
   .model_cost` and folded against the live backend's
   :data:`~multigrad_tpu.telemetry.costmodel.DEVICE_SPECS` roofline
   (:func:`~multigrad_tpu.telemetry.costmodel.predicted_time_s`).
   Only the top-k predicted survivors — **plus the hand-set default,
   always** — reach hardware.
3. **Confirm measured**: short warmed trials, bench.py's protocol
   (warm-up first, best of N reps, the dispatch/tunnel RTT floor
   measured separately and subtracted), ranked with the same noise
   tolerance the :mod:`~multigrad_tpu.telemetry.regress` gate uses —
   a candidate only displaces the default by beating it beyond the
   relative threshold AND the RTT-derived floor.  This is what fixes
   the BENCH_r06 trap: the static model says fused is always cheaper
   (fewer transcendentals), the measurement says it is 0.57x at
   window 33/41 — the measured stage keeps dense there and fused at
   window 10/41.
4. **Persist** the winner in the on-disk :class:`~multigrad_tpu.tune
   .table.TuningTable` beside the XLA compile cache, so a fresh
   process (or a fleet worker sharing the cache volume) starts tuned:
   a warm table entry resolves every knob with **zero measured
   trials**.

Every decision is emitted as a ``tune`` telemetry record carrying the
static prediction AND the measured confirmation, so
``python -m multigrad_tpu.telemetry.report`` (and the dashboard's
record stream) can show *why* a config was chosen.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .space import (bucket_candidates, model_candidates,
                    streaming_candidates)
from .table import TuningTable, catalog_rows, make_key, model_shape_key

__all__ = ["TuneResult", "tune_model", "tune_buckets",
           "tune_streaming", "within_noise", "measure_rtt"]

#: Default relative threshold (%) a candidate must beat the hand-set
#: default by to displace it — mirrors the regress gate's --pct
#: philosophy, tighter because trials here are same-session A/Bs
#: (BENCH_NOTES ±20% is *cross*-session variance).
DEFAULT_PCT = 10.0


def measure_rtt(reps: int = 8) -> float:
    """Dispatch + host-fetch floor, min over reps (bench.py's
    ``measure_fetch_rtt`` protocol: the *floor* every trial pays; a
    mean polluted by one hiccup would over-subtract)."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda a: a + 1.0)
    np.asarray(f(jnp.float32(0.0)))
    best = float("inf")
    for i in range(reps):
        t0 = time.perf_counter()
        np.asarray(f(jnp.float32(i)))
        best = min(best, time.perf_counter() - t0)
    return best


def _sub_rtt(elapsed: float, rtt: float) -> float:
    """Subtract the dispatch floor without eating real signal (the
    bench.py rule: never remove more than half the measurement)."""
    return elapsed - rtt if elapsed - rtt >= 0.5 * elapsed else elapsed


def within_noise(cand_s: float, best_s: float,
                 pct: float = DEFAULT_PCT,
                 floor_ms: float = 0.0) -> bool:
    """Is ``cand_s`` indistinguishable from (or better than)
    ``best_s``?  The tuner's tie rule, same tolerance machinery as
    :mod:`~multigrad_tpu.telemetry.regress`: quiet inside the
    relative threshold OR inside the absolute time floor."""
    if cand_s <= best_s:
        return True
    if best_s > 0 and (cand_s - best_s) / best_s * 100.0 <= pct:
        return True
    return (cand_s - best_s) * 1e3 <= floor_ms


@dataclass
class TuneResult:
    """Outcome of one tuning pass.

    ``chosen`` is the winning knob dict (what the table now resolves
    ``"auto"`` to); ``warm=True`` means the table already held the
    entry and **zero measured trials** ran.  ``candidates`` holds one
    record per enumerated candidate: knobs, ``predicted_s`` (static),
    ``measured_s`` (None when statically pruned), ``chosen``.
    """

    key: str
    chosen: dict
    warm: bool = False
    candidates: list = field(default_factory=list)
    baseline_s: Optional[float] = None
    measured_s: Optional[float] = None
    predicted_s: Optional[float] = None
    rtt_ms: Optional[float] = None
    table_path: Optional[str] = None

    @property
    def n_trials(self) -> int:
        """Measured trials run (0 on a warm start)."""
        return sum(1 for c in self.candidates
                   if c.get("measured_s") is not None)


def _as_table(table) -> TuningTable:
    return table if isinstance(table, TuningTable) else \
        TuningTable(table)


def _log_tune(telemetry, key, table_path, cand):
    if telemetry is not None:
        telemetry.log("tune", key=key, table=table_path, **cand)


def _warm_result(key, entry, table, telemetry, scope) -> TuneResult:
    res = TuneResult(
        key=key, chosen=dict(entry.get("knobs", {})), warm=True,
        baseline_s=entry.get("baseline_s"),
        measured_s=entry.get("measured_s"),
        predicted_s=entry.get("predicted_s"),
        table_path=table.path)
    _log_tune(telemetry, key, table.path, {
        "scope": scope, "knobs": res.chosen, "warm": True,
        "chosen": True, "predicted_s": res.predicted_s,
        "measured_s": res.measured_s})
    return res


def model_key(model, sigma_max=None, bin_window=None) -> str:
    """The tuning-table key of a model's knob entry — shared verbatim
    by the tuner (write side) and the ``"auto"`` resolution hooks
    (read side), so they can never disagree.  The catalog-shape
    bucket carries per-shard rows, the edge count and the fused
    window derived from ``sigma_max`` (the sigma-regime
    discriminator; falls back to the aux's stored ``bin_window``)."""
    from .resolve import aux_model_key

    aux = model.aux_data if isinstance(model.aux_data, dict) else {}
    if bin_window is None and sigma_max is not None:
        from .space import find_bin_edges
        edges = find_bin_edges(aux)
        if edges is not None:
            from ..ops.binned import fused_bin_window
            bin_window = fused_bin_window(edges, float(sigma_max))
    return aux_model_key(type(model).__name__, aux,
                         comm=getattr(model, "comm", None),
                         bin_window=bin_window)


def _record_op_aliases(table, key: str, knobs: dict) -> None:
    """Mirror a binned-kernel winner under the standalone-op key
    :func:`~multigrad_tpu.tune.resolve.resolve_op_bin_mode` reads, so
    a direct ``binned_erf_counts(bin_mode="auto")`` call on the tuned
    workload's shape resolves to the same mode the model-level tune
    chose.  Only the windowed key is aliased: the window IS the
    sigma-regime discriminator, so a windowless (``w0``) alias would
    hand a tight-sigma fused window to a wide-sigma caller — wrong
    counts, not just a slow path.  A windowless ``"auto"`` op call
    therefore stays dense."""
    if "bin_mode" not in knobs:
        return
    parts = key.split("|")
    # model|<name>|rows2^B|e{E}|w{W}|backend|device — the windowed
    # form is the only one the binned kernels produce.
    if len(parts) != 7 or not parts[4].startswith("w") \
            or parts[4] == "w0":
        return
    op_knobs = {"bin_mode": knobs.get("bin_mode"),
                "bin_window": knobs.get("bin_window")}
    try:
        alias = "|".join(["model", "binned_erf_counts", parts[2],
                          parts[3], parts[4], parts[5], parts[6]])
        table.record(alias, op_knobs, alias_of=key)
    except Exception:
        pass            # aliases are best-effort; the model key won


def _variant(model, cand: dict):
    """The model re-configured with a candidate's aux knobs (fit
    knobs like ``donate_carry`` ride separately)."""
    if not isinstance(model.aux_data, dict):
        return model
    updates = {k: cand.get(k) for k in
               ("bin_mode", "bin_window", "chunk_size")
               if k in cand}
    return model.replace_aux(**updates) if updates else model


def tune_model(model, params, *, sigma_max=None, table=None,
               telemetry=None, top_k: int = 3, reps: int = 2,
               trial_steps: int = 8, trial: Optional[str] = None,
               pct: float = DEFAULT_PCT, randkey=None,
               learning_rate: float = 0.01, force: bool = False,
               candidates: Optional[list] = None) -> TuneResult:
    """Tune an :class:`~multigrad_tpu.core.model.OnePointModel`'s
    knob set and persist the winner (see the module docstring for the
    four stages).

    Parameters
    ----------
    model, params
        The workload: the model as currently (hand-)configured and a
        representative parameter vector — trials run at these
        parameters, so pass the regime the fit will live in (the
        sigma value is what decides fused vs dense).
    sigma_max : float, optional
        Largest smoothing width the fit can reach (bounds the fused
        window).  Default: ``aux_data["sigma_max"]``; without either,
        no fused candidate is enumerated.
    trial : {"eval", "fit"}, optional
        Trial shape: ``"eval"`` times one full
        ``calc_loss_and_grad_from_params`` (the BENCH_r06 A/B
        protocol), ``"fit"`` times a ``trial_steps``-step Adam scan
        (needed for fit-level knobs).  Default: ``"fit"`` when any
        candidate varies ``donate_carry``, else ``"eval"``.
    force : bool
        Re-measure even when the table already holds the key (the
        warm-start short-circuit returns zero-trial results
        otherwise).
    """
    import jax.numpy as jnp

    from ..telemetry.costmodel import model_cost, predicted_time_s

    table = _as_table(table)
    key = model_key(model, sigma_max=sigma_max)
    if not force:
        entry = table.lookup(key)
        if entry is not None:
            return _warm_result(key, entry, table, telemetry, "model")

    params = jnp.asarray(params)
    cands = list(candidates if candidates is not None
                 else model_candidates(model, params,
                                       sigma_max=sigma_max))
    if not cands:
        raise ValueError("empty candidate space")
    if trial is None:
        trial = "fit" if any(c.get("donate_carry") is not None
                             for c in cands) else "eval"
    if trial == "eval":
        # The eval trial never exercises carry donation, so donate
        # variants run IDENTICAL programs and would be ranked on pure
        # timing noise — collapse them (donate_carry stays untuned →
        # the backend auto rule) instead of persisting a verdict no
        # trial measured.
        seen, collapsed = set(), []
        for c in cands:
            c = dict(c)
            c.pop("donate_carry", None)
            sig = tuple(sorted(c.items()))
            if sig not in seen:
                seen.add(sig)
                collapsed.append(c)
        cands = collapsed

    # ---- stage 2: static prune (roofline fold, zero device FLOPs) --
    records = []
    for cand in cands:
        rec = dict(knobs=dict(cand), predicted_s=None,
                   measured_s=None, chosen=False, scope="model")
        try:
            cost = model_cost(_variant(model, cand), params,
                              randkey=randkey)
            rec["predicted_s"] = float(
                predicted_time_s(cost)["predicted_s"])
        except Exception as e:      # a candidate that cannot trace
            rec["error"] = repr(e)  # cannot win either
        records.append(rec)

    ranked = sorted((r for r in records[1:]
                     if r["predicted_s"] is not None),
                    key=lambda r: r["predicted_s"])
    survivors = [records[0]] + ranked[:max(int(top_k) - 1, 0)] \
        if records[0].get("error") is None else ranked[:int(top_k)]
    if not survivors:
        raise RuntimeError(
            "no candidate produced a static cost estimate")

    # ---- stage 3: measured confirm (warmed, RTT-floored) -----------
    rtt = measure_rtt()
    for rec in survivors:
        variant = _variant(model, rec["knobs"])
        donate = rec["knobs"].get("donate_carry")
        if trial == "eval":
            def run():
                loss, grad = \
                    variant.calc_loss_and_grad_from_params(
                        params, randkey=randkey)
                return float(loss), np.asarray(grad)  # fetch = fence
            per = 1
        else:
            def run():
                traj = variant.run_adam(
                    guess=params, nsteps=trial_steps,
                    learning_rate=learning_rate, randkey=randkey,
                    progress=False, donate_carry=donate)
                return np.asarray(traj)               # fetch = fence
            per = trial_steps
        run()                                         # warm-up/compile
        best = float("inf")
        for _ in range(max(int(reps), 1)):
            t0 = time.perf_counter()
            run()
            best = min(best,
                       _sub_rtt(time.perf_counter() - t0, rtt) / per)
        rec["measured_s"] = best

    # ---- stage 4: rank, prefer the default on a tie, persist -------
    floor_ms = 2.0 * rtt * 1e3
    measured = [r for r in survivors if r["measured_s"] is not None]
    winner = min(measured, key=lambda r: r["measured_s"])
    baseline = records[0]
    baseline_s = baseline.get("measured_s")
    if baseline_s is not None and within_noise(
            baseline_s, winner["measured_s"], pct, floor_ms):
        winner = baseline        # a tie keeps the hand-set default
    winner["chosen"] = True

    for rec in records:
        _log_tune(telemetry, key, table.path, rec)
    table.record(
        key, winner["knobs"], predicted_s=winner["predicted_s"],
        measured_s=winner["measured_s"], baseline_s=baseline_s,
        baseline_knobs=baseline["knobs"], trial=trial,
        trials=len(measured) * max(int(reps), 1),
        rtt_ms=round(rtt * 1e3, 4), pct=pct)
    _record_op_aliases(table, key, winner["knobs"])
    return TuneResult(
        key=key, chosen=dict(winner["knobs"]), warm=False,
        candidates=records, baseline_s=baseline_s,
        measured_s=winner["measured_s"],
        predicted_s=winner["predicted_s"],
        rtt_ms=round(rtt * 1e3, 4), table_path=table.path)


def tune_buckets(model, guess, config=None, candidates=None,
                 nsteps: int = 20, reps: int = 2, table=None,
                 telemetry=None, min_gain: float = 0.08,
                 max_sizes: int = 4, k_sharded="auto",
                 budget_bytes=None,
                 force: bool = False) -> TuneResult:
    """Tune the serve scheduler's bucket-quantization ladder from
    **measured fits/hour**, replacing the hardcoded ``{1, 4, 16,
    64}``.

    For each candidate bucket size K, one warmed ``(K, ndim)``
    batched Adam dispatch — the exact program a
    :class:`~multigrad_tpu.serve.FitScheduler` bucket runs — is
    timed, yielding ``fits/hour(K) = K · 3600 / t``.  The ladder
    keeps K=1 (singleton latency) plus every size whose throughput
    beats the last kept size by ``min_gain`` (the efficiency
    frontier), capped at ``max_sizes`` rungs so compiled-program
    variants stay bounded.  Static prediction is recorded per K but
    never prunes here: the cost model scales linearly in K, so the
    quantity that decides the ladder — per-dispatch overhead
    amortization — is only visible measured.

    ``candidates=None`` derives the candidate set from the model's
    topology (:func:`~multigrad_tpu.tune.space.bucket_candidates`):
    on a sharded-K mesh (``k_sharded="auto"`` → shard whenever the
    model has a replica axis) the EXTENDED rungs past the replicated
    ceiling are measured — through the K-partitioned program and
    carry, exactly what a ``FitScheduler(k_sharded=...)`` dispatch
    runs — and ``budget_bytes`` caps the set by the sharded-K memory
    model instead of any hardcoded max.

    The winner persists under the ``buckets`` table key;
    ``FitScheduler(buckets="auto")`` (the default) and fleet workers
    resolve it at boot.
    """
    import jax
    import jax.numpy as jnp

    from ..inference.ensemble import batched_fit_wrapper
    from ..optim import adam as _adam
    from ..telemetry.costmodel import model_cost, predicted_time_s

    table = _as_table(table)
    aux = model.aux_data if isinstance(model.aux_data, dict) else {}
    shape = model_shape_key(
        catalog_rows(aux, getattr(model, "comm", None)))
    key = make_key("buckets", type(model).__name__, shape)
    if not force:
        entry = table.lookup(key)
        if entry is not None:
            return _warm_result(key, entry, table, telemetry,
                                "buckets")

    if config is None:
        from ..serve.queue import FitConfig
        config = FitConfig(nsteps=int(nsteps))
    guess = np.asarray(guess, dtype=float)
    if guess.ndim != 1:
        raise ValueError(f"guess must be 1-D, got shape {guess.shape}")
    from ..inference.ensemble import resolve_k_shard_topology
    sharded, n_replicas = resolve_k_shard_topology(model, k_sharded)
    if candidates is None:
        candidates = bucket_candidates(
            model, config.nsteps, ndim=guess.shape[0],
            k_sharded=sharded, budget_bytes=budget_bytes)
    dynamic = model.aux_leaves()
    rtt = measure_rtt()

    try:
        pred1 = predicted_time_s(
            model_cost(model, guess))["predicted_s"]
    except Exception:
        pred1 = None

    records, rates = [], {}
    for k in sorted(set(int(b) for b in candidates)):
        # The scheduler's dispatch rule (the shared predicate):
        # rungs the replica count divides run the K-partitioned
        # program and carry; indivisible rungs (K=1) run replicated.
        from ..inference.ensemble import k_shards_bucket
        k_shard = k_shards_bucket(k, sharded, n_replicas)
        wrapper = batched_fit_wrapper(model, config.with_key,
                                      k_sharded=k_shard)
        inits = jnp.asarray(np.tile(guess, (k, 1)))
        carry_sharding = None
        if k_shard:
            carry_sharding = model.k_sharding(2)
            inits = jax.device_put(inits, carry_sharding)

        def run():
            traj = _adam.run_adam_scan(
                wrapper, inits, nsteps=config.nsteps,
                param_bounds=config.bounds_list(),
                learning_rate=config.learning_rate,
                randkey=config.randkey,
                const_randkey=config.const_randkey, progress=False,
                fn_args=(dynamic,),
                carry_sharding=carry_sharding)
            return np.asarray(traj)           # host fetch = fence

        run()                                 # warm-up/compile
        best = float("inf")
        for _ in range(max(int(reps), 1)):
            t0 = time.perf_counter()
            run()
            best = min(best, _sub_rtt(time.perf_counter() - t0, rtt))
        rates[k] = k * 3600.0 / best
        records.append(dict(
            scope="buckets", knobs={"bucket": k}, chosen=False,
            k_sharded=k_shard,
            predicted_s=(pred1 * config.nsteps * k
                         if pred1 is not None else None),
            measured_s=best,
            fits_per_hour=round(rates[k], 1)))

    ladder, last = [], 0.0
    for k in sorted(rates):               # smallest K always kept —
        if not ladder or rates[k] > last * (1.0 + min_gain):
            ladder.append(k)              # the K=1 solo rung
            last = rates[k]
    if len(ladder) > max_sizes:           # keep 1 + the top rungs
        ladder = ladder[:1] + (ladder[-(max_sizes - 1):]
                               if max_sizes > 1 else [])
    for rec in records:
        rec["chosen"] = rec["knobs"]["bucket"] in ladder
        _log_tune(telemetry, key, table.path, rec)

    chosen = {"buckets": ladder}
    best_k = max(rates, key=rates.get)
    table.record(
        key, chosen,
        fits_per_hour={str(k): round(v, 1) for k, v in rates.items()},
        measured_s=records[-1]["measured_s"],
        nsteps=config.nsteps, rtt_ms=round(rtt * 1e3, 4),
        best_bucket=best_k, k_sharded=sharded,
        n_replicas=n_replicas)
    return TuneResult(
        key=key, chosen=chosen, warm=False, candidates=records,
        measured_s=records[-1]["measured_s"],
        rtt_ms=round(rtt * 1e3, 4), table_path=table.path)


def tune_streaming(smodel, params, *, table=None, telemetry=None,
                   use_scan: bool = False, trial_steps: int = 2,
                   reps: int = 2, pct: float = DEFAULT_PCT,
                   randkey=None, learning_rate: float = 0.01,
                   force: bool = False,
                   candidates: Optional[list] = None) -> TuneResult:
    """Tune a :class:`~multigrad_tpu.data.StreamingOnePointModel`'s
    ``chunk_rows`` (and, with ``use_scan=True``, ``remat_policy``)
    from short streamed fits.  Static predictions ride along per
    candidate (per-chunk cost × chunk count), but chunk-size
    tradeoffs are transfer/dispatch-bound — the measurement decides.
    Winner persists under the ``stream`` key;
    ``chunk_rows="auto"`` / ``remat_policy="auto"`` resolve it."""
    import dataclasses

    import jax.numpy as jnp

    table = _as_table(table)
    comm = smodel.comm
    per_shard = smodel.n_rows // (comm.size if comm is not None else 1)
    key = make_key("stream", type(smodel.model).__name__,
                   model_shape_key(per_shard))
    if not force:
        entry = table.lookup(key)
        if entry is not None:
            return _warm_result(key, entry, table, telemetry,
                                "stream")

    params = jnp.asarray(params)
    cands = list(candidates if candidates is not None
                 else streaming_candidates(smodel, use_scan=use_scan))
    rtt = measure_rtt()
    records = []
    for cand in cands:
        rec = dict(scope="stream", knobs=dict(cand),
                   predicted_s=None, measured_s=None, chosen=False)
        variant = dataclasses.replace(
            smodel, chunk_rows=int(cand["chunk_rows"]),
            remat_policy=cand["remat_policy"], last_stats=None)
        rec["n_chunks"] = variant.plan().n_chunks
        try:
            rec["predicted_s"] = _streaming_predicted_s(
                variant, params, randkey)
        except Exception:
            pass

        def run():
            traj = variant.run_adam(
                guess=params, nsteps=trial_steps,
                learning_rate=learning_rate, randkey=randkey,
                progress=False, use_scan=use_scan)
            return np.asarray(traj)
        run()                                  # warm-up/compile
        best = float("inf")
        for _ in range(max(int(reps), 1)):
            t0 = time.perf_counter()
            run()
            best = min(best, _sub_rtt(time.perf_counter() - t0, rtt)
                       / trial_steps)
        rec["measured_s"] = best
        records.append(rec)

    floor_ms = 2.0 * rtt * 1e3
    winner = min(records, key=lambda r: r["measured_s"])
    baseline = records[0]
    if within_noise(baseline["measured_s"], winner["measured_s"],
                    pct, floor_ms):
        winner = baseline
    winner["chosen"] = True
    for rec in records:
        _log_tune(telemetry, key, table.path, rec)
    table.record(
        key, winner["knobs"], predicted_s=winner["predicted_s"],
        measured_s=winner["measured_s"],
        baseline_s=baseline["measured_s"],
        baseline_knobs=baseline["knobs"], use_scan=bool(use_scan),
        trials=len(records) * max(int(reps), 1),
        rtt_ms=round(rtt * 1e3, 4), pct=pct)
    return TuneResult(
        key=key, chosen=dict(winner["knobs"]), warm=False,
        candidates=records, baseline_s=baseline["measured_s"],
        measured_s=winner["measured_s"],
        predicted_s=winner["predicted_s"],
        rtt_ms=round(rtt * 1e3, 4), table_path=table.path)


def _streaming_predicted_s(smodel, params, randkey) -> float:
    """Static roofline prediction of one streamed loss-and-grad step:
    (pass-1 + pass-2 per-chunk cost) × chunk count.  Mirrors
    ``StreamingOnePointModel.measure_comm``'s trace shapes."""
    import jax

    from ..telemetry.costmodel import (estimate_program_cost,
                                       predicted_time_s)

    with_key = randkey is not None
    plan = smodel.plan()
    aux = smodel.model.aux_leaves()
    key = smodel._key_arg(randkey)

    def chunk_struct(name):
        row = smodel.streams[name].read(0, 1)
        return jax.ShapeDtypeStruct(
            (plan.rows_per_chunk,) + row.shape[1:], row.dtype)

    chunks = [chunk_struct(n) for n in smodel._names]
    p1 = smodel.model._build_stream_program(
        "chunk_sumstats", with_key, smodel._names)
    c1 = estimate_program_cost(p1, params, chunks, aux, key)
    total = jax.eval_shape(p1, params, chunks, aux, key)
    ct = total[0] if smodel.model.sumstats_func_has_aux else total
    p2 = smodel.model._build_stream_program(
        "chunk_vjp", with_key, smodel._names)
    c2 = estimate_program_cost(p2, params, chunks, aux, ct, key)
    per_chunk = predicted_time_s(c1)["predicted_s"] \
        + predicted_time_s(c2)["predicted_s"]
    return float(per_chunk * plan.n_chunks)
