"""Multi-start optimization ensembles: basin-hop the loss, batched.

One-point losses are rarely convex (erf-CDF bins saturate; history
models fold multiple epochs through shared parameters), so a single
Adam/L-BFGS fit finds *a* basin, not necessarily *the* basin — and an
HMC run warm-started from a secondary mode burns its whole warmup
escaping it.  This module runs K independent fits as ONE program:

* :func:`run_multistart_adam` exploits Adam's per-coordinate update
  rule — K fits stacked into a ``(K, ndim)`` parameter matrix advance
  through the *same* ``optax.adam`` segment scan the solo fast path
  uses (``optim/adam._adam_segment_program``), with the model's
  ``batched_loss_and_grad`` kernel vmapping the K evaluations inside
  the SPMD block.  Running K starts is one dispatch per segment, not
  K.
* :func:`run_multistart_lbfgs` polishes starts through the in-graph
  L-BFGS scan (curvature pairs couple coordinates, so starts run
  sequentially — but the compiled program is built once and reused
  across all K).
* :func:`hmc_init_from_ensemble` turns the winning basin into
  overdispersed chain initializations for
  :func:`~multigrad_tpu.inference.run_hmc`.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..optim import adam as _adam
from ..optim import bfgs as _bfgs
from ..optim.adam import init_randkey
from ..optim.transforms import bounds_to_arrays
from ..utils.util import cached_program, latin_hypercube_sampler

__all__ = ["EnsembleResult", "batched_fit_wrapper",
           "run_multistart_adam", "run_multistart_lbfgs",
           "hmc_init_from_ensemble", "ensemble_memory_model",
           "max_k_for_budget", "resolve_k_sharded",
           "resolve_k_shard_topology", "k_shards_bucket",
           "DEFAULT_K_BUDGET_BYTES"]

#: Per-member resident rows of the batched Adam fit beyond the
#: trajectory: params + Adam's two moment sets + the update
#: transient — each ``ndim`` floats per member.
ENSEMBLE_STATE_ROWS = 4

#: Default per-device memory budget of the ``k_sharded="auto"`` rule
#: (overridable per call and via ``MGT_K_BUDGET_BYTES``): 1 GiB of
#: optimizer+trajectory state — conservative for a v5e's 16 GB HBM
#: once the catalog, executables and XLA scratch take their share.
DEFAULT_K_BUDGET_BYTES = 1 << 30


def ensemble_memory_model(k: int, ndim: int, nsteps: int, *,
                          n_replicas: int = 1,
                          catalog_bytes: int = 0,
                          n_devices: Optional[int] = None,
                          itemsize: Optional[int] = None) -> int:
    """Per-device bytes of a ``(K, ndim)`` batched Adam fit.

    The memory model behind every sharded-K decision — the
    ``k_sharded="auto"`` rule here, the serve scheduler's bucket-
    ladder cap, and ``tune_buckets``' candidate bound.  Counts what
    actually scales with K: the ``(nsteps+1, K, ndim)`` trajectory
    plus :data:`ENSEMBLE_STATE_ROWS` state rows per member
    (params, both Adam moments, the update transient), divided by
    ``n_replicas`` when the K axis is sharded; plus the per-device
    catalog share — ``catalog_bytes · n_replicas / n_devices``,
    because each replica slice spreads a full catalog copy over only
    ``n_devices / n_replicas`` data shards.  That last term is the
    sharded-K trade made explicit: ÷R optimizer state against ×R
    catalog residency, which is why sharding wins exactly when
    K·nsteps·ndim state dominates.
    """
    import math

    if itemsize is None:
        itemsize = np.dtype(jnp.result_type(float)).itemsize
    r = max(int(n_replicas), 1)
    k_local = math.ceil(max(int(k), 0) / r)
    state = k_local * int(ndim) * int(itemsize) \
        * (int(nsteps) + 1 + ENSEMBLE_STATE_ROWS)
    data = 0
    if catalog_bytes and n_devices:
        data = int(catalog_bytes) * r // max(int(n_devices), 1)
    return int(state + data)


def max_k_for_budget(budget_bytes: int, ndim: int, nsteps: int, *,
                     n_replicas: int = 1, catalog_bytes: int = 0,
                     n_devices: Optional[int] = None,
                     itemsize: Optional[int] = None) -> int:
    """Largest K whose :func:`ensemble_memory_model` estimate fits
    ``budget_bytes`` per device.  Scales linearly in ``n_replicas``
    (the sharded-K headline: R replica slices → R× the runnable
    ensemble width at the same per-device budget); 0 when even the
    catalog share alone exceeds the budget."""
    if itemsize is None:
        itemsize = np.dtype(jnp.result_type(float)).itemsize
    r = max(int(n_replicas), 1)
    data = 0
    if catalog_bytes and n_devices:
        data = int(catalog_bytes) * r // max(int(n_devices), 1)
    per_member = int(ndim) * int(itemsize) \
        * (int(nsteps) + 1 + ENSEMBLE_STATE_ROWS)
    if budget_bytes <= data or per_member <= 0:
        return 0
    return ((int(budget_bytes) - data) // per_member) * r


def _k_budget_bytes(budget=None) -> int:
    if budget is not None:
        return int(budget)
    import os
    env = os.environ.get("MGT_K_BUDGET_BYTES")
    return int(env) if env else DEFAULT_K_BUDGET_BYTES


def resolve_k_shard_topology(model, k_sharded="auto"):
    """Validate a ``k_sharded`` knob ("auto" | bool) against the
    model's mesh topology — the ONE resolution rule every sharded-K
    consumer (:func:`run_multistart_adam`,
    :class:`~multigrad_tpu.serve.FitScheduler`,
    :func:`~multigrad_tpu.tune.tune_buckets`) shares.

    Returns ``(sharded, n_replicas)``: explicit ``True`` demands a
    free replica axis (raising with the ``ensemble_comm`` pointer
    without one), explicit ``False`` pins the replicated layout, and
    ``"auto"`` shards exactly when the model was built on a 2-level
    ensemble mesh.  ``n_replicas`` is 1 whenever ``sharded`` is
    False.
    """
    if k_sharded is True:
        model._require_k_shard_axis()
        return True, model.k_shard_replicas
    if k_sharded is False:
        return False, 1
    if k_sharded != "auto":
        raise ValueError(
            f"k_sharded must be True, False or 'auto', got "
            f"{k_sharded!r}")
    if model.k_shard_axis is None:
        return False, 1
    return True, model.k_shard_replicas


def k_shards_bucket(bucket: int, k_sharded: bool,
                    n_replicas: int) -> bool:
    """THE dispatch rule, in one place: a ``(K, ndim)`` batch runs
    the K-partitioned program exactly when sharding is enabled and
    the replica count divides K — indivisible rungs (the K=1
    singleton) run replicated at full per-device state.  Shared by
    the scheduler's dispatch and bucket-ladder cap, bucket warmup,
    and the tuner's rung measurement/candidate cap, so the consumers
    can never drift apart."""
    return bool(k_sharded) and max(int(n_replicas), 1) > 0 \
        and int(bucket) % max(int(n_replicas), 1) == 0


def resolve_k_sharded(model, k: int, ndim: int, nsteps: int,
                      k_sharded="auto", k_budget_bytes=None) -> bool:
    """Resolve a ``k_sharded`` knob ("auto" | bool) for a K-member
    batched fit.

    The auto rule: shard exactly when (a) the model's comm carries a
    free replica axis (:func:`~multigrad_tpu.parallel.ensemble_comm`),
    (b) K is at least the replica count (a sub-R batch has nothing to
    partition), and (c) the REPLICATED layout's per-device state
    estimate exceeds the budget (default
    :data:`DEFAULT_K_BUDGET_BYTES`, env ``MGT_K_BUDGET_BYTES``) —
    i.e. sharding turns on precisely when device memory would start
    bounding ensemble width.  Explicit ``True`` demands the replica
    axis (raising without one); explicit ``False`` pins the
    historical replicated layout.
    """
    sharded, r = resolve_k_shard_topology(model, k_sharded)
    if not sharded or k_sharded != "auto":
        return sharded
    if int(k) < r:
        return False
    replicated = ensemble_memory_model(int(k), int(ndim),
                                       int(nsteps), n_replicas=1)
    return replicated > _k_budget_bytes(k_budget_bytes)


def pad_k_to_replicas(inits, n_replicas: int):
    """Pad a ``(K, ndim)`` batch up to a multiple of the replica
    count by replicating row 0 (Adam's elementwise update makes the
    padding rows inert independent fits — the serve scheduler's
    pad-and-pack convention).  Returns ``(padded, K)`` with the
    original K for slicing results back."""
    k = int(inits.shape[0])
    r = max(int(n_replicas), 1)
    pad = (-k) % r
    if pad:
        inits = jnp.concatenate(
            [inits, jnp.broadcast_to(inits[0], (pad,)
                                     + inits.shape[1:])], axis=0)
    return inits, k


def batched_fit_wrapper(model, with_key: bool,
                        k_sharded: bool = False):
    """The stable scan wrapper over a model's batched kernel.

    ``(params_batch, key, dynamic_leaves) -> (losses, grads)`` in the
    argument order the Adam segment scan expects, closing over the
    model's compiled ``batched_loss_and_grad`` program.  Cached per
    model (:func:`~multigrad_tpu.utils.util.cached_program`) because
    the whole-fit executable is keyed on the wrapper's identity — a
    fresh closure per call would retrace every fit.  Shared by
    :func:`run_multistart_adam` AND the fit-fleet scheduler
    (:class:`multigrad_tpu.serve.FitScheduler`), so ensembles and
    served bucket dispatches of the same shape reuse one compiled
    program.  ``k_sharded=True`` wraps the K-partitioned program
    variant instead (see ``OnePointModel.batched_loss_and_grad_fn``)
    — a SIBLING cache entry, so toggling sharding never retraces the
    other variant's programs.
    """
    cache_key = ("multistart_adam_wrapper", with_key) \
        if not k_sharded \
        else ("multistart_adam_wrapper", with_key, "k_sharded")

    def build():
        program = model.batched_loss_and_grad_fn(
            with_key, k_sharded=k_sharded)

        def wrapper(p, key, dynamic_leaves):
            return program(p, dynamic_leaves, key)

        return wrapper

    return cached_program(model.calc_loss_and_grad_from_params,
                          cache_key, build)


@dataclass(frozen=True)
class EnsembleResult:
    """Outcome of a multi-start fit.

    Attributes
    ----------
    best_params : jnp.ndarray, shape (ndim,)
        Parameters of the lowest-loss basin.
    best_loss : float
        Its loss.
    params : jnp.ndarray, shape (n_starts, ndim)
        Final parameters of every start.
    losses : jnp.ndarray, shape (n_starts,)
        Final losses of every start (``argmin`` picks ``best_params``).
    inits : jnp.ndarray, shape (n_starts, ndim)
        The initializations the starts ran from.
    """

    best_params: jnp.ndarray
    best_loss: float
    params: jnp.ndarray
    losses: jnp.ndarray
    inits: jnp.ndarray
    #: Whether the fit ran on the K-sharded (2-level mesh) path —
    #: what the ``k_sharded="auto"`` rule resolved to.
    k_sharded: bool = False

    @property
    def n_starts(self) -> int:
        return self.params.shape[0]

    def basin_spread(self) -> float:
        """Max distance of any final point from the winner — ~0 means
        every start found the same basin (a unimodality hint); large
        values flag real multimodality."""
        d = np.linalg.norm(np.asarray(self.params)
                           - np.asarray(self.best_params), axis=1)
        return float(np.max(d))


def _sample_inits(param_bounds, n_starts, ndim, seed):
    """Latin-hypercube starts strictly inside the bounds box (pulled
    5% in from each face: the bounds bijection needs interior points)."""
    low, high = bounds_to_arrays(param_bounds, ndim)
    low = np.asarray(low, np.float64)
    high = np.asarray(high, np.float64)
    if not (np.all(np.isfinite(low)) and np.all(np.isfinite(high))):
        raise ValueError(
            "multi-start sampling needs finite (low, high) bounds for "
            "every parameter; pass explicit `inits` for unbounded fits")
    pad = 0.05 * (high - low)
    return jnp.asarray(latin_hypercube_sampler(
        low + pad, high - pad, ndim, n_starts, seed=seed))


def run_multistart_adam(model, param_bounds=None, n_starts: int = 8,
                        nsteps: int = 200, learning_rate: float = 0.01,
                        inits=None, seed: int = 0, randkey=None,
                        const_randkey: bool = False,
                        bound_fits: bool = True,
                        donate_carry=None, telemetry=None,
                        log_every: int = 0, live=None,
                        alerts=None, k_sharded="auto",
                        k_budget_bytes=None) -> EnsembleResult:
    """K independent Adam fits as one batched in-graph scan.

    Adam's update is elementwise, so a ``(K, ndim)`` parameter matrix
    driven by the batched loss-and-grad kernel IS K exact independent
    fits — same trajectories a Python loop over
    :meth:`~multigrad_tpu.core.model.OnePointModel.run_adam` would
    produce, at one dispatch per segment.

    Parameters
    ----------
    model : OnePointModel
        The model to fit (its comm decides the mesh).
    param_bounds : sequence of (low, high), optional
        Finite per-parameter boxes.  Default init sampling draws a
        Latin-hypercube design inside them; with ``bound_fits`` (the
        default) the fits also run through the bounds bijection, so
        every iterate stays inside the box.
    n_starts, nsteps, learning_rate : int, int, float
        Ensemble size and per-start fit schedule.
    inits : array (n_starts, ndim), optional
        Explicit initializations (overrides the LHS design; required
        when ``param_bounds`` is None).
    seed : int
        LHS design seed.
    randkey, const_randkey
        Per-step model randomness, as in
        :func:`~multigrad_tpu.optim.adam.run_adam_scan`.
    donate_carry : bool, optional
        Donate the batched ``(K, ndim)`` Adam carry (params + both
        moment matrices + key) to the segment scan — None = backend
        auto (see :func:`~multigrad_tpu.optim.adam.run_adam_scan`).
        For wide ensembles this halves the resident optimizer state:
        K moment sets instead of 2K.
    telemetry, log_every, live, alerts
        The standard monitoring surface of every fit driver
        (:func:`~multigrad_tpu.optim.adam.run_adam_scan`): in-graph
        ``adam`` taps every ``log_every`` steps (batched — each
        scalar is the K-vector across starts), a ``fit_plan`` up
        front, and — the ensemble's own closing record — a
        ``fit_summary`` carrying ``final_loss`` (the winning basin's
        loss), ``n_starts`` and ``best_start``, so live consumers
        flip to "done" with the ensemble's outcome instead of the
        stream ending silently.
    k_sharded : {"auto", True, False}
        Partition the K axis (params, trajectories, BOTH Adam moment
        sets) over the replica axis of a 2-level
        :func:`~multigrad_tpu.parallel.ensemble_comm` mesh, so
        per-device optimizer state is K/R and device memory stops
        bounding ensemble width.  ``"auto"`` (default) shards once
        the replicated layout's per-device estimate
        (:func:`ensemble_memory_model`) exceeds ``k_budget_bytes``
        (default :data:`DEFAULT_K_BUDGET_BYTES`, env
        ``MGT_K_BUDGET_BYTES``) — a no-op on ordinary one-axis
        comms, so existing callers are unaffected.  K is padded to a
        replica-count multiple with inert row-0 copies (sliced away
        from the result).  Bitwise-equal to the replicated path in
        exact arithmetic; real models agree to float tolerance (the
        data-axis reduction width differs between the layouts).
    """
    if inits is None:
        if param_bounds is None:
            raise ValueError(
                "pass param_bounds (finite boxes; inits are sampled "
                "inside them) or explicit inits")
        ndim = len(param_bounds)
        inits = _sample_inits(param_bounds, n_starts, ndim, seed)
    inits = jnp.asarray(inits, dtype=jnp.result_type(float))
    if inits.ndim != 2:
        raise ValueError(f"inits must be (n_starts, ndim), "
                         f"got shape {inits.shape}")

    with_key = randkey is not None
    if const_randkey and randkey is None:
        raise ValueError("Must pass randkey if const_randkey")
    dynamic = model.aux_leaves()
    sharded = resolve_k_sharded(model, inits.shape[0],
                                inits.shape[1], nsteps,
                                k_sharded=k_sharded,
                                k_budget_bytes=k_budget_bytes)
    n_real = int(inits.shape[0])
    carry_sharding = None
    if sharded:
        # Pad K to a replica multiple (inert row-0 copies, sliced
        # away below) and place the batch — and thereby the whole
        # Adam carry — on the K-partitioned layout.
        inits, n_real = pad_k_to_replicas(inits,
                                          model.k_shard_replicas)
        carry_sharding = model.k_sharding(inits.ndim)
        inits = jax.device_put(inits, carry_sharding)
    wrapper = batched_fit_wrapper(model, with_key, k_sharded=sharded)

    from ..telemetry.live import wire_monitoring
    telemetry, log_every, owned = wire_monitoring(
        telemetry, log_every, live, alerts)
    try:
        traj = _adam.run_adam_scan(
            wrapper, inits, nsteps=nsteps,
            param_bounds=(param_bounds if bound_fits else None),
            learning_rate=learning_rate, randkey=randkey,
            const_randkey=const_randkey, progress=False,
            fn_args=(dynamic,), donate_carry=donate_carry,
            telemetry=telemetry, log_every=log_every,
            carry_sharding=carry_sharding)
        finals = traj[-1]

        key = init_randkey(randkey) if with_key else jnp.zeros(())
        losses, _ = model.batched_loss_and_grad_fn(
            with_key, k_sharded=sharded)(finals, dynamic, key)
        # Slice padding rows away (host-side: K-scale data only).
        finals = finals[:n_real]
        losses = losses[:n_real]
        inits = inits[:n_real]
        best = int(jnp.argmin(jnp.where(jnp.isfinite(losses), losses,
                                        jnp.inf)))
        if telemetry is not None and jax.process_index() == 0:
            # The ensemble's own closing record: the scan's
            # fit_summary carries steps only (it cannot know the
            # basin ranking); this one carries the outcome, so the
            # stream no longer closes silently for ensemble runs.
            telemetry.log("fit_summary", steps=int(nsteps),
                          n_starts=n_real, best_start=best,
                          final_loss=float(losses[best]),
                          k_sharded=sharded)
        return EnsembleResult(
            best_params=finals[best], best_loss=float(losses[best]),
            params=finals, losses=losses, inits=inits,
            k_sharded=sharded)
    finally:
        if owned is not None:
            owned.close()


def _lbfgs_polish_objective(model, with_key: bool):
    """The stable solo loss-and-grad the L-BFGS polish optimizes.

    Routes through the SAME cached :func:`batched_fit_wrapper` the
    Adam ensemble (and the serve scheduler) compile — one row of the
    batched kernel — and is itself cached per model, because
    :func:`~multigrad_tpu.optim.bfgs.run_lbfgs_scan` keys its
    compiled whole-fit scan on the callable's identity: the historical
    fresh-closure-per-call version re-traced the entire L-BFGS
    program on every polish of a model the ensemble had already
    compiled programs for.
    """
    cache_key = ("multistart_lbfgs_objective", with_key)

    def build():
        wrapper = batched_fit_wrapper(model, with_key)
        dynamic = model.aux_leaves()

        def loss_and_grad(p, randkey=None):
            key = randkey if randkey is not None else jnp.zeros(())
            losses, grads = wrapper(p[None], key, dynamic)
            return losses[0], grads[0]

        return loss_and_grad

    return cached_program(model.calc_loss_and_grad_from_params,
                          cache_key, build)


def run_multistart_lbfgs(model, param_bounds=None, n_starts: int = 8,
                         maxsteps: int = 100, inits=None, seed: int = 0,
                         randkey=None, memory_size: int = 10
                         ) -> EnsembleResult:
    """K in-graph L-BFGS fits from scattered starts.

    L-BFGS curvature pairs couple coordinates (no elementwise batching
    trick), so starts run as a host loop over
    :func:`~multigrad_tpu.optim.bfgs.run_lbfgs_scan` — the compiled
    whole-fit scan is built ONCE (stable objective identity via
    :func:`_lbfgs_polish_objective`, which reuses the ensemble's
    cached :func:`batched_fit_wrapper` kernel) and re-executed per
    start AND across repeat polishes of the same model.  Typically
    the polish stage after :func:`run_multistart_adam` has ranked
    the basins.
    """
    if inits is None:
        if param_bounds is None:
            raise ValueError(
                "pass param_bounds (finite boxes; inits are sampled "
                "inside them) or explicit inits")
        inits = _sample_inits(param_bounds, n_starts, len(param_bounds),
                              seed)
    inits = jnp.asarray(inits, dtype=jnp.result_type(float))

    loss_and_grad = _lbfgs_polish_objective(model,
                                            randkey is not None)

    finals, losses = [], []
    for k in range(inits.shape[0]):
        u, traj_losses = _bfgs.run_lbfgs_scan(
            loss_and_grad, inits[k], maxsteps=maxsteps, randkey=randkey,
            memory_size=memory_size, param_bounds=param_bounds)
        finals.append(u)
        losses.append(traj_losses[-1])
    finals = jnp.stack(finals)
    losses = jnp.stack(losses)
    best = int(jnp.argmin(jnp.where(jnp.isfinite(losses), losses,
                                    jnp.inf)))
    return EnsembleResult(
        best_params=finals[best], best_loss=float(losses[best]),
        params=finals, losses=losses, inits=inits)


def hmc_init_from_ensemble(result: EnsembleResult, num_chains: int = 4,
                           spread: float = 1e-2, randkey=0,
                           stderr: Optional[jnp.ndarray] = None
                           ) -> jnp.ndarray:
    """Chain initializations around an ensemble's winning basin.

    Gaussian scatter of scale ``spread`` (componentwise ``spread ·
    stderr`` when Laplace uncertainties are supplied — the natural
    choice is ``FisherResult.stderr()``) around ``best_params``:
    overdispersed enough for split R-hat to mean something, tight
    enough to skip re-finding the mode during warmup.  Returns
    ``(num_chains, ndim)`` for :func:`~multigrad_tpu.inference.run_hmc`.
    """
    best = jnp.asarray(result.best_params)
    scale = spread * (jnp.ones_like(best) if stderr is None
                      else jnp.asarray(stderr, best.dtype))
    noise = jax.random.normal(init_randkey(randkey),
                              (num_chains, best.shape[0]), best.dtype)
    return best[None] + noise * scale[None]
