"""Multi-start optimization ensembles: basin-hop the loss, batched.

One-point losses are rarely convex (erf-CDF bins saturate; history
models fold multiple epochs through shared parameters), so a single
Adam/L-BFGS fit finds *a* basin, not necessarily *the* basin — and an
HMC run warm-started from a secondary mode burns its whole warmup
escaping it.  This module runs K independent fits as ONE program:

* :func:`run_multistart_adam` exploits Adam's per-coordinate update
  rule — K fits stacked into a ``(K, ndim)`` parameter matrix advance
  through the *same* ``optax.adam`` segment scan the solo fast path
  uses (``optim/adam._adam_segment_program``), with the model's
  ``batched_loss_and_grad`` kernel vmapping the K evaluations inside
  the SPMD block.  Running K starts is one dispatch per segment, not
  K.
* :func:`run_multistart_lbfgs` polishes starts through the in-graph
  L-BFGS scan (curvature pairs couple coordinates, so starts run
  sequentially — but the compiled program is built once and reused
  across all K).
* :func:`hmc_init_from_ensemble` turns the winning basin into
  overdispersed chain initializations for
  :func:`~multigrad_tpu.inference.run_hmc`.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..optim import adam as _adam
from ..optim import bfgs as _bfgs
from ..optim.adam import init_randkey
from ..optim.transforms import bounds_to_arrays
from ..utils.util import cached_program, latin_hypercube_sampler

__all__ = ["EnsembleResult", "batched_fit_wrapper",
           "run_multistart_adam", "run_multistart_lbfgs",
           "hmc_init_from_ensemble"]


def batched_fit_wrapper(model, with_key: bool):
    """The stable scan wrapper over a model's batched kernel.

    ``(params_batch, key, dynamic_leaves) -> (losses, grads)`` in the
    argument order the Adam segment scan expects, closing over the
    model's compiled ``batched_loss_and_grad`` program.  Cached per
    model (:func:`~multigrad_tpu.utils.util.cached_program`) because
    the whole-fit executable is keyed on the wrapper's identity — a
    fresh closure per call would retrace every fit.  Shared by
    :func:`run_multistart_adam` AND the fit-fleet scheduler
    (:class:`multigrad_tpu.serve.FitScheduler`), so ensembles and
    served bucket dispatches of the same shape reuse one compiled
    program.
    """
    cache_key = ("multistart_adam_wrapper", with_key)

    def build():
        program = model.batched_loss_and_grad_fn(with_key)

        def wrapper(p, key, dynamic_leaves):
            return program(p, dynamic_leaves, key)

        return wrapper

    return cached_program(model.calc_loss_and_grad_from_params,
                          cache_key, build)


@dataclass(frozen=True)
class EnsembleResult:
    """Outcome of a multi-start fit.

    Attributes
    ----------
    best_params : jnp.ndarray, shape (ndim,)
        Parameters of the lowest-loss basin.
    best_loss : float
        Its loss.
    params : jnp.ndarray, shape (n_starts, ndim)
        Final parameters of every start.
    losses : jnp.ndarray, shape (n_starts,)
        Final losses of every start (``argmin`` picks ``best_params``).
    inits : jnp.ndarray, shape (n_starts, ndim)
        The initializations the starts ran from.
    """

    best_params: jnp.ndarray
    best_loss: float
    params: jnp.ndarray
    losses: jnp.ndarray
    inits: jnp.ndarray

    @property
    def n_starts(self) -> int:
        return self.params.shape[0]

    def basin_spread(self) -> float:
        """Max distance of any final point from the winner — ~0 means
        every start found the same basin (a unimodality hint); large
        values flag real multimodality."""
        d = np.linalg.norm(np.asarray(self.params)
                           - np.asarray(self.best_params), axis=1)
        return float(np.max(d))


def _sample_inits(param_bounds, n_starts, ndim, seed):
    """Latin-hypercube starts strictly inside the bounds box (pulled
    5% in from each face: the bounds bijection needs interior points)."""
    low, high = bounds_to_arrays(param_bounds, ndim)
    low = np.asarray(low, np.float64)
    high = np.asarray(high, np.float64)
    if not (np.all(np.isfinite(low)) and np.all(np.isfinite(high))):
        raise ValueError(
            "multi-start sampling needs finite (low, high) bounds for "
            "every parameter; pass explicit `inits` for unbounded fits")
    pad = 0.05 * (high - low)
    return jnp.asarray(latin_hypercube_sampler(
        low + pad, high - pad, ndim, n_starts, seed=seed))


def run_multistart_adam(model, param_bounds=None, n_starts: int = 8,
                        nsteps: int = 200, learning_rate: float = 0.01,
                        inits=None, seed: int = 0, randkey=None,
                        const_randkey: bool = False,
                        bound_fits: bool = True,
                        donate_carry=None, telemetry=None,
                        log_every: int = 0, live=None,
                        alerts=None) -> EnsembleResult:
    """K independent Adam fits as one batched in-graph scan.

    Adam's update is elementwise, so a ``(K, ndim)`` parameter matrix
    driven by the batched loss-and-grad kernel IS K exact independent
    fits — same trajectories a Python loop over
    :meth:`~multigrad_tpu.core.model.OnePointModel.run_adam` would
    produce, at one dispatch per segment.

    Parameters
    ----------
    model : OnePointModel
        The model to fit (its comm decides the mesh).
    param_bounds : sequence of (low, high), optional
        Finite per-parameter boxes.  Default init sampling draws a
        Latin-hypercube design inside them; with ``bound_fits`` (the
        default) the fits also run through the bounds bijection, so
        every iterate stays inside the box.
    n_starts, nsteps, learning_rate : int, int, float
        Ensemble size and per-start fit schedule.
    inits : array (n_starts, ndim), optional
        Explicit initializations (overrides the LHS design; required
        when ``param_bounds`` is None).
    seed : int
        LHS design seed.
    randkey, const_randkey
        Per-step model randomness, as in
        :func:`~multigrad_tpu.optim.adam.run_adam_scan`.
    donate_carry : bool, optional
        Donate the batched ``(K, ndim)`` Adam carry (params + both
        moment matrices + key) to the segment scan — None = backend
        auto (see :func:`~multigrad_tpu.optim.adam.run_adam_scan`).
        For wide ensembles this halves the resident optimizer state:
        K moment sets instead of 2K.
    telemetry, log_every, live, alerts
        The standard monitoring surface of every fit driver
        (:func:`~multigrad_tpu.optim.adam.run_adam_scan`): in-graph
        ``adam`` taps every ``log_every`` steps (batched — each
        scalar is the K-vector across starts), a ``fit_plan`` up
        front, and — the ensemble's own closing record — a
        ``fit_summary`` carrying ``final_loss`` (the winning basin's
        loss), ``n_starts`` and ``best_start``, so live consumers
        flip to "done" with the ensemble's outcome instead of the
        stream ending silently.
    """
    if inits is None:
        if param_bounds is None:
            raise ValueError(
                "pass param_bounds (finite boxes; inits are sampled "
                "inside them) or explicit inits")
        ndim = len(param_bounds)
        inits = _sample_inits(param_bounds, n_starts, ndim, seed)
    inits = jnp.asarray(inits, dtype=jnp.result_type(float))
    if inits.ndim != 2:
        raise ValueError(f"inits must be (n_starts, ndim), "
                         f"got shape {inits.shape}")

    with_key = randkey is not None
    if const_randkey and randkey is None:
        raise ValueError("Must pass randkey if const_randkey")
    dynamic = model.aux_leaves()
    wrapper = batched_fit_wrapper(model, with_key)

    from ..telemetry.live import wire_monitoring
    telemetry, log_every, owned = wire_monitoring(
        telemetry, log_every, live, alerts)
    try:
        traj = _adam.run_adam_scan(
            wrapper, inits, nsteps=nsteps,
            param_bounds=(param_bounds if bound_fits else None),
            learning_rate=learning_rate, randkey=randkey,
            const_randkey=const_randkey, progress=False,
            fn_args=(dynamic,), donate_carry=donate_carry,
            telemetry=telemetry, log_every=log_every)
        finals = traj[-1]

        key = init_randkey(randkey) if with_key else jnp.zeros(())
        losses, _ = model.batched_loss_and_grad_fn(with_key)(
            finals, dynamic, key)
        best = int(jnp.argmin(jnp.where(jnp.isfinite(losses), losses,
                                        jnp.inf)))
        if telemetry is not None and jax.process_index() == 0:
            # The ensemble's own closing record: the scan's
            # fit_summary carries steps only (it cannot know the
            # basin ranking); this one carries the outcome, so the
            # stream no longer closes silently for ensemble runs.
            telemetry.log("fit_summary", steps=int(nsteps),
                          n_starts=int(inits.shape[0]),
                          best_start=best,
                          final_loss=float(losses[best]))
        return EnsembleResult(
            best_params=finals[best], best_loss=float(losses[best]),
            params=finals, losses=losses, inits=inits)
    finally:
        if owned is not None:
            owned.close()


def run_multistart_lbfgs(model, param_bounds=None, n_starts: int = 8,
                         maxsteps: int = 100, inits=None, seed: int = 0,
                         randkey=None, memory_size: int = 10
                         ) -> EnsembleResult:
    """K in-graph L-BFGS fits from scattered starts.

    L-BFGS curvature pairs couple coordinates (no elementwise batching
    trick), so starts run as a host loop over
    :func:`~multigrad_tpu.optim.bfgs.run_lbfgs_scan` — the compiled
    whole-fit scan is built ONCE (same shapes) and re-executed per
    start.  Typically the polish stage after
    :func:`run_multistart_adam` has ranked the basins.
    """
    if inits is None:
        if param_bounds is None:
            raise ValueError(
                "pass param_bounds (finite boxes; inits are sampled "
                "inside them) or explicit inits")
        inits = _sample_inits(param_bounds, n_starts, len(param_bounds),
                              seed)
    inits = jnp.asarray(inits, dtype=jnp.result_type(float))

    def loss_and_grad(p, randkey=None):
        out = model.calc_loss_and_grad_from_params(p, randkey=randkey)
        loss = out[0][0] if model.loss_func_has_aux else out[0]
        return loss, out[1]

    finals, losses = [], []
    for k in range(inits.shape[0]):
        u, traj_losses = _bfgs.run_lbfgs_scan(
            loss_and_grad, inits[k], maxsteps=maxsteps, randkey=randkey,
            memory_size=memory_size, param_bounds=param_bounds)
        finals.append(u)
        losses.append(traj_losses[-1])
    finals = jnp.stack(finals)
    losses = jnp.stack(losses)
    best = int(jnp.argmin(jnp.where(jnp.isfinite(losses), losses,
                                    jnp.inf)))
    return EnsembleResult(
        best_params=finals[best], best_loss=float(losses[best]),
        params=finals, losses=losses, inits=inits)


def hmc_init_from_ensemble(result: EnsembleResult, num_chains: int = 4,
                           spread: float = 1e-2, randkey=0,
                           stderr: Optional[jnp.ndarray] = None
                           ) -> jnp.ndarray:
    """Chain initializations around an ensemble's winning basin.

    Gaussian scatter of scale ``spread`` (componentwise ``spread ·
    stderr`` when Laplace uncertainties are supplied — the natural
    choice is ``FisherResult.stderr()``) around ``best_params``:
    overdispersed enough for split R-hat to mean something, tight
    enough to skip re-finding the mode during warmup.  Returns
    ``(num_chains, ndim)`` for :func:`~multigrad_tpu.inference.run_hmc`.
    """
    best = jnp.asarray(result.best_params)
    scale = spread * (jnp.ones_like(best) if stderr is None
                      else jnp.asarray(stderr, best.dtype))
    noise = jax.random.normal(init_randkey(randkey),
                              (num_chains, best.shape[0]), best.dtype)
    return best[None] + noise * scale[None]
