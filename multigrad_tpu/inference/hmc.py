"""In-graph Hamiltonian Monte Carlo over sharded sumstats.

Gradient-based posterior sampling on top of the paper's identity: the
potential ``U(θ) = loss(θ)`` (the negative log-posterior, up to a
constant) and its gradient already cost only O(|y| + |params|)
communication per evaluation, so an HMC trajectory is just more of the
same SPMD program.  Following the pjit-era scaling playbook
("Scalable Training of Language Models using JAX pjit and TPUv4",
PAPERS.md), the WHOLE sampler — warmup with per-chain dual-averaging
step-size adaptation, leapfrog integration, Metropolis correction, and
the sampling run — compiles into ONE XLA program:

* chains are vmapped over the replicated parameter axis *inside* the
  SPMD block (the model's ``batched_loss_and_grad`` kernel), so the
  data stays sharded once while C chains integrate in lockstep and
  every psum batches across chains;
* draws advance under a whole-chain ``lax.scan`` (leapfrog is an
  inner scan), so ``num_warmup + num_samples`` draws execute with
  zero host round-trips.

The trajectory is jittered (per-draw uniform step-size perturbation —
the randomized-path defense against resonant trajectories that NUTS
buys with its tree; a fixed-length cousin, not full NUTS) and
divergences are counted.  Momenta use a diagonal mass matrix.

Convergence accounting (split R-hat, bulk effective sample size via
Geyer's initial monotone sequence) runs host-side on the returned
draws — see :func:`split_rhat` / :func:`effective_sample_size`.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec

from ..optim.adam import init_randkey
from ..telemetry.comm import record_collective as _record_collective
from ..utils.util import cached_program, evict_cached_programs

__all__ = ["HMCResult", "run_hmc", "split_rhat",
           "effective_sample_size"]

# Dual-averaging constants (Hoffman & Gelman 2014, §3.2.1 — the Stan
# defaults): adaptation gain, iteration offset, averaging decay.
_DA_GAMMA = 0.05
_DA_T0 = 10.0
_DA_KAPPA = 0.75
# |ΔH| beyond this is a divergence: the integrator left the region
# where the quadrature is meaningful (Stan's divergent-transition
# threshold).
_DIVERGENCE_DH = 1000.0


@dataclass(frozen=True)
class HMCResult:
    """Posterior draws and sampler accounting.

    Attributes
    ----------
    samples : np.ndarray, shape (num_chains, num_samples, ndim)
        Post-warmup draws.
    potential : np.ndarray, shape (num_chains, num_samples)
        ``U = loss`` at each draw (the negative log-posterior up to a
        constant) — for ranking draws and spotting stuck chains.
    accept_prob : np.ndarray, shape (num_chains,)
        Mean Metropolis acceptance probability over the sampling run.
    step_size : np.ndarray, shape (num_chains,)
        Dual-averaged step size each chain sampled with.
    warmup_accept_prob : np.ndarray, shape (num_chains,)
        Mean acceptance over the warmup run — far from
        ``target_accept`` means dual averaging did not converge (NaN
        when ``num_warmup=0``).
    divergences : np.ndarray, shape (num_chains,)
        Divergent-transition count per chain during sampling (any
        nonzero count deserves a smaller ``step_size`` / higher
        ``target_accept``).
    rhat : np.ndarray, shape (ndim,)
        Split-chain potential scale reduction; values ≲ 1.01 (< 1.05
        at minimum) indicate mixed chains.
    ess : np.ndarray, shape (ndim,)
        Bulk effective sample size, combined over chains.
    """

    samples: np.ndarray
    potential: np.ndarray
    accept_prob: np.ndarray
    step_size: np.ndarray
    warmup_accept_prob: np.ndarray
    divergences: np.ndarray
    rhat: np.ndarray
    ess: np.ndarray

    @property
    def num_chains(self) -> int:
        return self.samples.shape[0]

    def mean(self) -> np.ndarray:
        """Posterior mean over all chains and draws."""
        return self.samples.reshape(-1, self.samples.shape[-1]).mean(0)

    def cov(self) -> np.ndarray:
        """Posterior covariance over all chains and draws."""
        flat = self.samples.reshape(-1, self.samples.shape[-1])
        return np.cov(flat, rowvar=False)

    def summary(self) -> dict:
        """Compact per-run scalars (JSON-friendly)."""
        return {
            "num_chains": int(self.num_chains),
            "num_samples": int(self.samples.shape[1]),
            "accept_prob": [round(float(a), 3) for a in self.accept_prob],
            "step_size": [round(float(s), 5) for s in self.step_size],
            "divergences": [int(d) for d in self.divergences],
            "max_rhat": round(float(np.max(self.rhat)), 4),
            "min_ess": round(float(np.min(self.ess)), 1),
        }


def _build_hmc_local(model, num_warmup, num_samples, num_leapfrog,
                     with_key, target_accept, jitter, tap=None,
                     sentinel=None, replica_axis=None,
                     n_replicas=1):
    """The whole sampler as a per-shard kernel (see module docstring).

    Signature: ``(q0 (C, D), dynamic_aux_leaves, model_key, rng_key,
    step_size0, inv_mass) -> dict`` — compiled via
    ``model.wrap_spmd(..., n_extra=3)``.

    ``tap`` (:class:`~multigrad_tpu.telemetry.ScalarTap`) emits
    ``hmc`` records from inside the sampling scan every
    ``tap.log_every`` draws: draw index, the window's mean acceptance,
    cumulative divergence count, and per-chain step sizes.  This
    kernel runs INSIDE shard_map, so the emit is gated on shard 0
    (values are replicated — one shard speaks for all) and, in the
    callback, on process 0.

    ``sentinel`` (:class:`~multigrad_tpu.telemetry.flight
    .NonFiniteSentinel`) watches the chains' potential from inside
    the sampling scan (same shard-0 gate): a NaN potential — bad
    init, broken likelihood — trips the flight recorder the moment
    it happens instead of surfacing afterwards as an inscrutable
    zero-acceptance run.

    ``replica_axis`` (with ``n_replicas``) is the sharded-chains
    variant: the C chain axis is partitioned over a 2-level mesh's
    replica axis (each slice integrates C/R chains over its own
    full-catalog data shards), so chain state — positions, momenta,
    gradients, per-chain dual-averaging state — is C/R per device.
    Randomness is drawn as the FULL ``(C, ...)`` array on every
    device and row-sliced by replica index, so each chain's stream
    is identical to the replicated sampler's — sharded and
    replicated runs agree bitwise in exact arithmetic (real models:
    to reduction tolerance, which HMC's accept decisions then
    amplify — compare posteriors, not paths).  Taps/sentinels gate
    on replica 0 AND data-shard 0; tapped acceptance/divergences are
    reduced over the replica axis (O(1) scalars) so the records stay
    whole-ensemble quantities.
    """
    kernel = model.spmd_kernel("batched_loss_and_grad", with_key)
    comm = model.comm

    def local_fn(q0, dynamic_leaves, model_key, rng_key, step_size0,
                 inv_mass):
        n_chains = q0.shape[0]        # chains on THIS replica slice
        c_total = n_chains * max(int(n_replicas), 1)

        def chain_rows(draw, key, tail):
            """Random draw for this slice's chain rows, bitwise equal
            to the replicated sampler's rows: the full (C_total, ...)
            array is generated (C·ndim scalars — noise next to one
            potential evaluation) and row-sliced by replica index."""
            full = draw(key, (c_total,) + tail, q0.dtype)
            if replica_axis is None:
                return full
            start = lax.axis_index(replica_axis) * n_chains
            return lax.dynamic_slice_in_dim(full, start, n_chains,
                                            axis=0)

        def replica_and_shard0(base_gate):
            """Tap/sentinel gate: one device speaks for the mesh —
            data-shard 0 of replica slice 0."""
            gate = base_gate
            if comm is not None:
                gate = jnp.logical_and(gate, comm.axis_index() == 0)
            if replica_axis is not None:
                gate = jnp.logical_and(
                    gate, lax.axis_index(replica_axis) == 0)
            return gate

        def U_and_grad(q):
            return kernel(q, dynamic_leaves, model_key)

        def kinetic(p):
            return 0.5 * jnp.sum(p * p * inv_mass, axis=-1)

        def leapfrog(q, p, g, U0, eps_col):
            # Kick-drift-kick with the end-of-step gradient carried
            # into the next step: num_leapfrog potential evaluations
            # per trajectory, not 2·num_leapfrog.
            def body(carry, _):
                q, p, g, _ = carry
                p_half = p - 0.5 * eps_col * g
                q = q + eps_col * inv_mass * p_half
                U, g = U_and_grad(q)
                p = p_half - 0.5 * eps_col * g
                return (q, p, g, U), None

            (q, p, g, U), _ = lax.scan(body, (q, p, g, U0), None,
                                       length=num_leapfrog)
            return q, p, g, U

        def draw(q, U, g, eps, key):
            k_mom, k_jit, k_acc = jax.random.split(key, 3)
            p = chain_rows(jax.random.normal, k_mom, q.shape[1:]) \
                / jnp.sqrt(inv_mass)
            # Per-draw step-size jitter: resonance defense (see
            # module docstring).
            eps_d = eps * (1.0 + jitter * (
                2.0 * chain_rows(jax.random.uniform, k_jit, ())
                - 1.0))
            h0 = U + kinetic(p)
            qn, pn, gn, un = leapfrog(q, p, g, U, eps_d[:, None])
            dh = h0 - (un + kinetic(pn))
            finite = jnp.isfinite(dh)
            accept_prob = jnp.where(
                finite, jnp.exp(jnp.minimum(dh, 0.0)), 0.0)
            divergent = ~finite | (dh < -_DIVERGENCE_DH)
            accept = chain_rows(jax.random.uniform, k_acc, ()) \
                < accept_prob
            keep = accept[:, None]
            # ``un`` (the PROPOSAL potential) rides along for the
            # non-finite sentinel: a broken likelihood only ever
            # produces rejected proposals, so the accepted U stays
            # finite forever — un is where the NaN is visible.
            return (jnp.where(keep, qn, q), jnp.where(accept, un, U),
                    jnp.where(keep, gn, g), accept_prob, divergent,
                    un)

        u0, g0 = U_and_grad(q0)
        mu = jnp.log(10.0 * step_size0) * jnp.ones(n_chains, q0.dtype)
        log_eps0 = jnp.log(step_size0) * jnp.ones(n_chains, q0.dtype)

        def warm_watch(t, un, fired):
            # Same NaN-only watch as the sampling scan (see there),
            # armed during warmup too: a NaN-from-step-0 likelihood
            # must trip before 1000 warmup draws burn leapfrog steps
            # on pure NaNs, not at the first post-warmup draw.
            gate = replica_and_shard0(~fired)
            bad = sentinel.watch(
                t, dict(warmup_potential=jnp.where(
                    jnp.isinf(un), jnp.zeros_like(un), un)),
                gate=gate)
            return fired | bad

        def warm_body(carry, t):
            if sentinel is not None:
                q, U, g, h_bar, log_eps, log_eps_bar, fired = carry
            else:
                q, U, g, h_bar, log_eps, log_eps_bar = carry
            q, U, g, accept_prob, _div, un = draw(
                q, U, g, jnp.exp(log_eps), jax.random.fold_in(rng_key, t))
            # Nesterov dual averaging toward the target accept rate,
            # independently per chain (every quantity is (C,)-shaped).
            tt = t.astype(q.dtype) + 1.0
            eta = 1.0 / (tt + _DA_T0)
            h_bar = (1.0 - eta) * h_bar \
                + eta * (target_accept - accept_prob)
            log_eps = mu - jnp.sqrt(tt) / _DA_GAMMA * h_bar
            w = tt ** (-_DA_KAPPA)
            log_eps_bar = w * log_eps + (1.0 - w) * log_eps_bar
            out = (q, U, g, h_bar, log_eps, log_eps_bar)
            if sentinel is not None:
                out = out + (warm_watch(t, un, fired),)
            return out, accept_prob

        fired0 = jnp.zeros((), bool)
        if num_warmup > 0:
            carry0 = (q0, u0, g0, jnp.zeros(n_chains, q0.dtype),
                      log_eps0, log_eps0)
            if sentinel is not None:
                carry0 = carry0 + (fired0,)
            out_carry, warm_accept = lax.scan(
                warm_body, carry0, jnp.arange(num_warmup))
            q, u, g, _, _, log_eps_bar = out_carry[:6]
            if sentinel is not None:
                # Latch carries over: a warmup trip must not fire a
                # second callback per sampling step.
                fired0 = out_carry[6]
            warm_accept = warm_accept.mean(axis=0)
        else:
            q, u, g, log_eps_bar = q0, u0, g0, log_eps0
            warm_accept = jnp.full(n_chains, jnp.nan, q0.dtype)
        eps_sample = jnp.exp(log_eps_bar)

        def sample_body(carry, t):
            if sentinel is not None:
                q, U, g, win_accept, div_total, fired = carry
            else:
                q, U, g, win_accept, div_total = carry
            q, U, g, accept_prob, divergent, un = draw(
                q, U, g, eps_sample,
                jax.random.fold_in(rng_key, num_warmup + t))
            win_accept = win_accept + accept_prob.mean()
            div_total = div_total + divergent.sum()
            if sentinel is not None:
                # A rejected divergence keeps the accepted U finite,
                # and an INF proposal potential is an ordinary
                # exploded trajectory the Metropolis step rejects —
                # sampler business, counted by the (non-fatal)
                # divergence statistics.  A *NaN* proposal potential
                # means the likelihood itself broke: that is the
                # flight-recorder case, so Inf is masked to a finite
                # value before the watch and only NaN trips.
                # Latched (fired rides in the carry, seeded from the
                # warmup scan): one callback per run, gated to
                # shard 0 like the tap.
                gate = replica_and_shard0(~fired)
                bad = sentinel.watch(
                    t + 1, dict(potential=jnp.where(
                        jnp.isinf(un), jnp.zeros_like(un), un)),
                    gate=gate)
                fired = fired | bad
            if tap is not None:
                # Windowed acceptance: mean over the log_every draws
                # since the last emit (draws number from 1, so window
                # 1 closes at t + 1 == log_every).  Sharded chains:
                # the record must carry whole-ensemble quantities, so
                # the per-slice scalars reduce over the replica axis
                # (O(1) payload) and the step sizes gather to the
                # full (C,) vector the replicated tap emits — behind
                # the SAME lax.cond gate as the emit itself, so the
                # replica (slow) axis carries traffic only on the
                # log_every-th draws, not every draw (the predicate
                # is replicated, so every device takes the same
                # branch and the collective schedule stays uniform).
                emit = ((t + 1) % tap.log_every) == 0
                if replica_axis is not None:
                    def _reduced(_):
                        _record_collective("pmean", win_accept)
                        _record_collective("psum", div_total)
                        _record_collective("all_gather", eps_sample)
                        return (lax.pmean(win_accept, replica_axis),
                                lax.psum(div_total, replica_axis),
                                lax.all_gather(eps_sample,
                                               replica_axis, axis=0,
                                               tiled=True))

                    def _skipped(_):
                        return (jnp.zeros_like(win_accept),
                                jnp.zeros_like(div_total),
                                jnp.zeros((c_total,),
                                          eps_sample.dtype))

                    tap_accept, tap_div, tap_eps = lax.cond(
                        emit, _reduced, _skipped, None)
                else:
                    tap_accept, tap_div, tap_eps = (
                        win_accept, div_total, eps_sample)
                tap.maybe_emit(t + 1, dict(
                    accept=tap_accept / tap.log_every,
                    divergences=tap_div,
                    step_size=tap_eps),
                    gate=None if comm is None
                    else replica_and_shard0(jnp.asarray(True)))
                win_accept = jnp.where(emit, 0.0, win_accept)
            out_carry = (q, U, g, win_accept, div_total)
            if sentinel is not None:
                out_carry = out_carry + (fired,)
            return out_carry, (q, U, accept_prob, divergent)

        carry0 = (q, u, g, jnp.zeros((), q.dtype),
                  jnp.zeros((), jnp.int32))
        if sentinel is not None:
            carry0 = carry0 + (fired0,)
        _, (qs, us, accepts, divs) = lax.scan(
            sample_body, carry0, jnp.arange(num_samples))
        return {
            "samples": jnp.swapaxes(qs, 0, 1),        # (C, S, D)
            "potential": jnp.swapaxes(us, 0, 1),      # (C, S)
            "accept_prob": accepts.mean(axis=0),      # (C,)
            "warmup_accept_prob": warm_accept,        # (C,)
            "step_size": eps_sample,                  # (C,)
            "divergences": divs.sum(axis=0),          # (C,)
        }

    return local_fn


def run_hmc(model, init, num_samples: int = 1000,
            num_warmup: int = 500, num_chains: int = 4,
            step_size: float = 0.1, num_leapfrog: int = 8,
            inv_mass=None, target_accept: float = 0.8,
            jitter: float = 0.2, randkey=0, model_randkey=None,
            init_spread: float = 0.0, telemetry=None,
            log_every: int = 0, flight=None, live=None,
            alerts=None, k_sharded: bool = False) -> HMCResult:
    """Sample ``p(θ) ∝ exp(-loss(θ))`` with multi-chain in-graph HMC.

    The model's loss must be a negative log-density (e.g. ``½ χ²``) —
    the convention every shipped Gaussian-likelihood model follows up
    to a parameter-independent constant.

    Parameters
    ----------
    model : OnePointModel
        Defines the potential via its fused loss-and-grad kernel; the
        sampler runs under ``shard_map`` over ``model.comm``.
    init : array, shape (ndim,) or (num_chains, ndim)
        Chain initialization — e.g. an MLE from
        :func:`~multigrad_tpu.inference.run_multistart_adam` (use
        ``init_spread`` to scatter chains around a single point, or
        pass per-chain rows directly:
        :func:`~multigrad_tpu.inference.hmc_init_from_ensemble`).
    num_samples, num_warmup : int
        Post-warmup draws per chain / dual-averaging warmup draws.
    num_chains : int
        Ignored when ``init`` is 2-D (its leading dim wins).
    step_size : float
        Initial leapfrog step size; warmup adapts it per chain toward
        ``target_accept`` and sampling runs at the dual-averaged
        value.
    num_leapfrog : int
        Leapfrog steps per draw (trajectory length ≈
        ``num_leapfrog · step_size``).
    inv_mass : array (ndim,), optional
        Diagonal inverse mass matrix (≈ posterior variances, when
        known — e.g. ``diag`` of a Laplace covariance from
        :func:`~multigrad_tpu.inference.fisher_information`).
        Default: identity.
    jitter : float
        Per-draw uniform step-size jitter fraction (0 disables).
    randkey : int | PRNG key
        Sampler randomness (momenta, Metropolis, jitter).
    model_randkey : int | PRNG key, optional
        Forwarded to the model's user methods — held CONSTANT across
        all draws (the potential must be deterministic within a run,
        the same contract as :func:`~multigrad_tpu.optim.bfgs.run_bfgs`).
    init_spread : float
        Std-dev of Gaussian scatter applied to a 1-D ``init`` to
        disperse chains (overdispersed starts make R-hat meaningful).
    telemetry : MetricsLogger, optional
        With ``log_every > 0``, ``hmc`` records stream out of the
        jitted sampling scan every ``log_every``-th draw — windowed
        mean acceptance, cumulative divergences, per-chain step sizes
        — so a long run is observable while it executes (one shard's
        callback fires; process 0 writes).  Static throttle, zero
        retraces — see :mod:`multigrad_tpu.telemetry.taps`.
    flight : FlightRecorder, optional
        Arm the in-graph non-finite watch on the chains' potential
        (:mod:`multigrad_tpu.telemetry.flight`); a NaN potential
        dumps a postmortem bundle and the run raises
        :class:`~multigrad_tpu.telemetry.flight
        .FlightRecorderTripped`.  Add the recorder as a sink of
        ``telemetry`` and its divergence-spike trigger sees the
        ``hmc`` tap records too.
    live : LiveServer | LiveSink, optional
        Attach the live ``/metrics``+``/status`` endpoint
        (:mod:`multigrad_tpu.telemetry.live`); a ``fit_plan`` record
        announces the draw schedule so the live ETA counts sampling
        draws.
    alerts : AlertEngine, optional
        Evaluate the non-fatal alert rules
        (:mod:`multigrad_tpu.telemetry.alerts`) on the stream — the
        divergence-rate rule reads the ``hmc`` tap records emitted
        here.
    k_sharded : bool
        Partition the chain axis over the replica axis of a 2-level
        :func:`~multigrad_tpu.parallel.ensemble_comm` mesh: each
        replica slice integrates ``C/R`` chains over its own
        full-catalog data shards, so chain state (positions, momenta,
        gradients, dual-averaging state) is C/R per device — the
        sharded-K layout for samplers, lifting the chain count the
        same way :func:`~multigrad_tpu.inference.run_multistart_adam`
        lifts ensemble width.  Requires ``num_chains`` divisible by
        the replica count.  Per-chain randomness reproduces the
        replicated sampler's streams exactly (bitwise in exact
        arithmetic; real models' chains diverge at reduction
        tolerance and should be compared as posteriors).

    Returns
    -------
    HMCResult
        Draws shaped ``(num_chains, num_samples, ndim)`` plus
        acceptance/step-size/divergence accounting and host-computed
        split R-hat and bulk ESS.
    """
    init = jnp.asarray(init, dtype=jnp.result_type(float))
    rng = init_randkey(randkey)
    if init.ndim == 1:
        k_init, rng = jax.random.split(rng)
        init = init[None] + init_spread * jax.random.normal(
            k_init, (num_chains, init.shape[0]), init.dtype)
    elif init.ndim != 2:
        raise ValueError(
            f"init must be (ndim,) or (num_chains, ndim), "
            f"got shape {init.shape}")
    ndim = init.shape[1]

    with_key = model_randkey is not None
    model_key = init_randkey(model_randkey) if with_key else jnp.zeros(())
    inv_mass = jnp.ones(ndim, init.dtype) if inv_mass is None \
        else jnp.asarray(inv_mass, init.dtype)
    if inv_mass.shape != (ndim,):
        raise ValueError(
            f"inv_mass must be diagonal, shape ({ndim},); "
            f"got {inv_mass.shape}")
    if not bool(jnp.all(inv_mass > 0)):
        # A zero entry (e.g. stderr()**2 after the pinv fallback gave
        # an unidentifiable direction zero variance) would divide the
        # momentum draw by sqrt(0): inf momenta, all-NaN chains.
        raise ValueError(
            "inv_mass entries must be strictly positive (got "
            f"{np.asarray(inv_mass)}); an unidentifiable direction "
            "(see fisher_diagnostics) cannot be used as a "
            "preconditioner — fall back to ones there")

    replica_axis, n_replicas = None, 1
    if k_sharded:
        replica_axis = model._require_k_shard_axis()
        n_replicas = model.k_shard_replicas
        if init.shape[0] % n_replicas:
            raise ValueError(
                f"k_sharded HMC needs the chain count divisible by "
                f"the replica count: {init.shape[0]} chains on "
                f"{n_replicas} replica slices")
        # Chain state lives partitioned from draw 0: C/R rows of
        # positions/momenta/gradients/adaptation state per device.
        init = jax.device_put(init, model.k_sharding(init.ndim))

    from ..telemetry.live import wire_monitoring
    from ..telemetry.taps import make_tap
    telemetry, log_every, owned = wire_monitoring(
        telemetry, log_every, live, alerts)
    if telemetry is not None:
        # The draw schedule, up front: live ETA counts sampling draws
        # (the tap's step axis) against nsteps.
        telemetry.log("fit_plan", kind="hmc",
                      nsteps=int(num_samples),
                      num_warmup=int(num_warmup),
                      num_chains=int(init.shape[0]),
                      log_every=int(log_every),
                      k_sharded=bool(k_sharded))
    tap = make_tap(telemetry, "hmc", log_every)
    sentinel = flight.sentinel("hmc") if flight is not None else None
    base_key = ("hmc", int(num_warmup), int(num_samples),
                int(num_leapfrog), with_key, float(target_accept),
                float(jitter))
    if k_sharded:
        # Sibling program family: toggling sharding never retraces
        # the replicated sampler (and vice versa).
        base_key = base_key + ("k_sharded",)
    # Tap/sentinel are baked into the traced program; identity-keying
    # them means one build per (logger, recorder) pair, reused across
    # repeat runs — never a per-run retrace.
    cache_key = base_key + tuple(x for x in (tap, sentinel)
                                 if x is not None)

    def build():
        local_fn = _build_hmc_local(
            model, int(num_warmup), int(num_samples), int(num_leapfrog),
            with_key, float(target_accept), float(jitter), tap=tap,
            sentinel=sentinel, replica_axis=replica_axis,
            n_replicas=n_replicas)
        if replica_axis is None:
            return model.wrap_spmd(local_fn,
                                   out_specs=PartitionSpec(),
                                   n_extra=3)
        # Sharded chains: q0 enters partitioned along the replica
        # axis and every per-chain output leaves the same way — the
        # host-side assembly (np.asarray below) is the only gather.
        C1 = PartitionSpec(replica_axis)
        C2 = PartitionSpec(replica_axis, None)
        C3 = PartitionSpec(replica_axis, None, None)
        return model.wrap_spmd(
            local_fn,
            out_specs={"samples": C3, "potential": C2,
                       "accept_prob": C1, "warmup_accept_prob": C1,
                       "step_size": C1, "divergences": C1},
            n_extra=3, params_spec=C2)

    # Cached on the model instance (cached_program keys on the bound
    # method's owner), so repeat runs with the same schedule reuse the
    # compiled sampler.
    program = cached_program(model.calc_loss_and_grad_from_params,
                             cache_key, build)
    if cache_key != base_key:
        # One instrumented sampler per schedule: drop variants keyed
        # to other (possibly closed) loggers/recorders — same
        # rationale as the Adam segment cache.
        evict_cached_programs(
            model.calc_loss_and_grad_from_params,
            lambda k: len(k) > len(base_key)
            and k[:len(base_key)] == base_key,
            keep=cache_key)
    try:
        out = program(init, model.aux_leaves(), model_key, rng,
                      jnp.asarray(float(step_size), init.dtype),
                      inv_mass)
        samples = np.asarray(out["samples"])
        if cache_key != base_key:
            # Flush in-flight (unordered) tap/sentinel callbacks so
            # every record is written before the caller can close the
            # logger.
            jax.effects_barrier()
        if telemetry is not None and jax.process_index() == 0:
            # Close the run in the stream (the contract run_adam_scan
            # established): live consumers flip to "done"/ETA 0 on
            # this record instead of holding a stale partial-window
            # ETA forever.
            summary = {
                "steps": int(num_samples),
                "divergences": int(np.asarray(
                    out["divergences"]).sum()),
                "accept_prob": round(float(np.asarray(
                    out["accept_prob"]).mean()), 4),
            }
            if flight is not None and flight.bundle_path:
                summary["postmortem_bundle"] = flight.bundle_path
            telemetry.log("fit_summary", **summary)
    finally:
        if owned is not None:
            owned.close()
    if flight is not None:
        flight.raise_if_fatal()
    return HMCResult(
        samples=samples,
        potential=np.asarray(out["potential"]),
        accept_prob=np.asarray(out["accept_prob"]),
        step_size=np.asarray(out["step_size"]),
        warmup_accept_prob=np.asarray(out["warmup_accept_prob"]),
        divergences=np.asarray(out["divergences"]),
        rhat=split_rhat(samples),
        ess=effective_sample_size(samples),
    )


# ------------------------------------------------------------------ #
# Convergence diagnostics (host-side numpy)
# ------------------------------------------------------------------ #
def split_rhat(samples) -> np.ndarray:
    """Split-chain potential scale reduction factor (Gelman–Rubin).

    Each chain is split in half (catching within-chain drift that
    whole-chain R-hat misses), then the classic between/within
    variance ratio is computed per dimension.  ``samples`` is
    ``(num_chains, num_draws, ndim)``; returns ``(ndim,)``.
    """
    samples = np.asarray(samples, np.float64)
    n_chains, n_draws, ndim = samples.shape
    half = n_draws // 2
    if half < 2:
        return np.full(ndim, np.nan)
    chains = np.concatenate(
        [samples[:, :half], samples[:, half:2 * half]], axis=0)
    means = chains.mean(axis=1)                       # (2C, D)
    w = chains.var(axis=1, ddof=1).mean(axis=0)       # within
    b = half * means.var(axis=0, ddof=1)              # between
    var_hat = (half - 1) / half * w + b / half
    with np.errstate(divide="ignore", invalid="ignore"):
        return np.sqrt(var_hat / w)


def _autocovariance(x: np.ndarray) -> np.ndarray:
    """Per-chain autocovariance via FFT: ``x`` is (C, S, D), returns
    (C, S, D) with lag along axis 1 (biased 1/S normalization, the
    ESS convention)."""
    c, s, d = x.shape
    x = x - x.mean(axis=1, keepdims=True)
    n = 1 << (2 * s - 1).bit_length()
    f = np.fft.rfft(x, n=n, axis=1)
    acov = np.fft.irfft(f * np.conj(f), n=n, axis=1)[:, :s]
    return acov / s


def effective_sample_size(samples) -> np.ndarray:
    """Bulk ESS, combined over chains (Stan's formulation).

    Per dimension: lag correlations ``ρ_t`` are estimated from the
    chain-averaged autocovariance relative to the pooled variance
    (which deflates ρ for unmixed chains, tying ESS to R-hat), then
    summed under Geyer's initial-monotone-positive-sequence rule.
    ``samples`` is ``(num_chains, num_draws, ndim)``; returns
    ``(ndim,)``, capped at the total draw count.
    """
    samples = np.asarray(samples, np.float64)
    n_chains, n_draws, ndim = samples.shape
    if n_draws < 4:
        return np.full(ndim, np.nan)
    acov = _autocovariance(samples)                    # (C, S, D)
    chain_var = acov[:, 0] * n_draws / (n_draws - 1.0)  # (C, D)
    w = chain_var.mean(axis=0)
    mean_acov = acov.mean(axis=0)                      # (S, D)
    if n_chains > 1:
        means = samples.mean(axis=1)                   # (C, D)
        b = n_draws * means.var(axis=0, ddof=1)
        var_hat = (n_draws - 1.0) / n_draws * w + b / n_draws
    else:
        var_hat = (n_draws - 1.0) / n_draws * w
    ess = np.empty(ndim)
    total = n_chains * n_draws
    for k in range(ndim):
        if var_hat[k] <= 0 or not np.isfinite(var_hat[k]):
            ess[k] = np.nan
            continue
        rho = 1.0 - (w[k] - mean_acov[:, k]) / var_hat[k]
        # Geyer: sum consecutive-lag pairs while positive, enforcing
        # monotone decrease.
        tau = 1.0           # = 1 + 2 Σ ρ_t, built from pair sums
        prev_pair = np.inf
        t = 1
        while t + 1 < n_draws:
            pair = rho[t] + rho[t + 1]
            if pair < 0:
                break
            pair = min(pair, prev_pair)
            tau += 2.0 * pair
            prev_pair = pair
            t += 2
        ess[k] = min(total / tau, float(total))
    return ess
