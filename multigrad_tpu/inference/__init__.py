"""Inference subsystem: uncertainty quantification for fitted models.

The fourth user-facing workload (fit → stream → *infer*): the paper's
O(|sumstats| + |params|) communication identity extends to the
second-order and sampling machinery every real galaxy–halo analysis
needs on top of a point estimate —

* :mod:`.fisher` — distributed sumstats Jacobians (per-shard/per-chunk
  ``∂y_r/∂p`` psums exactly like ``y_r``), Gauss–Newton Fisher
  information, Laplace covariances, conditioning diagnostics.
* :mod:`.hmc` — in-graph multi-chain HMC: leapfrog over the model's
  fused loss-and-grad kernel, chains vmapped over the replicated
  parameter axis inside the SPMD block, dual-averaging step-size
  warmup, the whole run one ``lax.scan`` program; split R-hat / ESS
  diagnostics.
* :mod:`.ensemble` — multi-start Adam (K fits batched through one
  optimizer scan) and L-BFGS polish, feeding the winning basin into
  HMC warm starts.

The canonical pipeline (``examples/smf_posterior.py``):

    ens = run_multistart_adam(model, param_bounds=bounds)
    fr  = fisher_information(model, ens.best_params)
    res = run_hmc(model, hmc_init_from_ensemble(ens),
                  inv_mass=1.0 / jnp.diag(fr.covariance()))
"""
from .fisher import (FisherResult, fisher_diagnostics,  # noqa: F401
                     fisher_information, laplace_covariance,
                     sumstats_jacobian)
from .hmc import (HMCResult, effective_sample_size, run_hmc,  # noqa
                  split_rhat)
from .ensemble import (DEFAULT_K_BUDGET_BYTES,  # noqa
                       EnsembleResult, batched_fit_wrapper,
                       ensemble_memory_model, hmc_init_from_ensemble,
                       max_k_for_budget, resolve_k_sharded,
                       run_multistart_adam, run_multistart_lbfgs)

__all__ = [
    "FisherResult", "fisher_information", "laplace_covariance",
    "fisher_diagnostics", "sumstats_jacobian",
    "HMCResult", "run_hmc", "split_rhat", "effective_sample_size",
    "EnsembleResult", "run_multistart_adam", "run_multistart_lbfgs",
    "hmc_init_from_ensemble", "batched_fit_wrapper",
    "ensemble_memory_model", "max_k_for_budget", "resolve_k_sharded",
    "DEFAULT_K_BUDGET_BYTES",
]
