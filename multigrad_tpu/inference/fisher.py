"""Distributed Fisher information and Laplace uncertainty.

The paper's identity — loss and gradient of a sharded one-point model
cost O(|y| + |params|) communication — extends to second order: the
sumstats Jacobian psums exactly like the sumstats themselves
(``J = Σ_r ∂y_r/∂p``, one psum of |y|·|p| floats), and every
second-order object a one-point analysis needs factors through it.
For loss ``L(y(p))`` the Gauss–Newton Hessian is

    F  =  Jᵀ H_y J,        H_y = ∂²L/∂y²   (|y|×|y|, replicated,
                                            computed ONCE on the host
                                            program — no data pass)

which for the canonical Gaussian likelihood ``L = ½ (y-t)ᵀ Σ⁻¹ (y-t)``
is the *exact* Fisher information ``Jᵀ Σ⁻¹ J``, and at the MLE of any
model whose sumstats are linear in params it equals the exact Hessian.
The Laplace approximation then reads parameter uncertainty straight
off ``F⁻¹``.

Both the resident SPMD Jacobian
(:meth:`~multigrad_tpu.core.model.OnePointModel
.calc_sumstats_and_jac_from_params`) and the streamed chunk
accumulator (:meth:`~multigrad_tpu.data.streaming
.StreamingOnePointModel.calc_sumstats_and_jac_from_params`) feed this
module, so 1e9-halo out-of-core catalogs get Fisher matrices through
the identical algebra.
"""
from __future__ import annotations

import warnings
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..optim.adam import init_randkey

__all__ = ["FisherResult", "sumstats_jacobian", "fisher_information",
           "laplace_covariance", "fisher_diagnostics"]


def sumstats_jacobian(model, params, randkey=None, mode: str = "fwd"):
    """Total sumstats and their Jacobian for a resident OR streamed model.

    Dispatches on the model type: an
    :class:`~multigrad_tpu.core.model.OnePointModel` runs the one-pass
    SPMD ``sumstats_jac`` program; a
    :class:`~multigrad_tpu.data.streaming.StreamingOnePointModel`
    accumulates the per-chunk Jacobian program over its chunk plan
    (``mode`` is forward there — streamed params are always few).

    Returns ``(sumstats, jac)``, both replicated; ``jac`` has shape
    ``(*sumstats.shape, ndim)``.
    """
    if hasattr(model, "streams"):      # StreamingOnePointModel
        return model.calc_sumstats_and_jac_from_params(
            params, randkey=randkey)
    return model.calc_sumstats_and_jac_from_params(
        params, randkey=randkey, mode=mode)


def _loss_model(model):
    """The OnePointModel holding the loss definition (unwraps the
    streaming wrapper)."""
    return model.model if hasattr(model, "streams") else model


@dataclass(frozen=True)
class FisherResult:
    """Fisher information at a parameter point, with its factors.

    Attributes
    ----------
    params : jnp.ndarray, shape (ndim,)
        Evaluation point (typically the MLE).
    fisher : jnp.ndarray, shape (ndim, ndim)
        Gauss–Newton Fisher information ``Jᵀ H_y J``, symmetrized.
    jac : jnp.ndarray, shape (n_sumstats, ndim)
        Total sumstats Jacobian (the distributed psum product).
    sumstats : jnp.ndarray, shape (n_sumstats,)
        Total sumstats at ``params``.
    sumstats_hessian : jnp.ndarray, shape (n_sumstats, n_sumstats)
        ``H_y = ∂²loss/∂y²`` at the total sumstats.
    """

    params: jnp.ndarray
    fisher: jnp.ndarray
    jac: jnp.ndarray
    sumstats: jnp.ndarray
    sumstats_hessian: jnp.ndarray

    def covariance(self, jitter: float = 0.0):
        """Laplace covariance ``F⁻¹`` (see :func:`laplace_covariance`)."""
        return laplace_covariance(self.fisher, jitter=jitter)

    def stderr(self, jitter: float = 0.0):
        """Per-parameter 1σ Laplace uncertainties
        (``sqrt(diag(F⁻¹))``)."""
        return jnp.sqrt(jnp.diagonal(self.covariance(jitter=jitter)))

    def diagnostics(self) -> dict:
        """Conditioning report (see :func:`fisher_diagnostics`)."""
        return fisher_diagnostics(self.fisher)


def fisher_information(model, params, randkey=None, mode: str = "fwd"
                       ) -> FisherResult:
    """Distributed Gauss–Newton Fisher information ``Jᵀ H_y J``.

    One data pass for ``(y, J)`` (resident SPMD program or streamed
    chunk accumulation — O(|y|·|p|) communication either way), then an
    O(|y|²) replicated Hessian of the loss-from-sumstats on the host
    program.  Exact Fisher for Gaussian likelihoods; at an MLE whose
    sumstats are locally linear it matches ``jax.hessian`` of the full
    loss (tested to rtol 1e-4 in ``tests/test_inference.py``).

    Works for both :class:`~multigrad_tpu.core.model.OnePointModel`
    and :class:`~multigrad_tpu.data.streaming.StreamingOnePointModel`.
    For calibrated *absolute* uncertainties the model's loss must be a
    negative log-density (e.g. ``½ χ²``), not a rescaled proxy (an
    MSE's Fisher is the NLL's scaled by the same constant).
    """
    params = jnp.asarray(params)
    from ..core.group import OnePointGroup
    if isinstance(model, OnePointGroup):
        return _group_fisher_information(model, params,
                                         randkey=randkey, mode=mode)
    loss_model = _loss_model(model)
    y, jac = sumstats_jacobian(model, params, randkey=randkey, mode=mode)
    y = jnp.asarray(y)
    jac = jnp.asarray(jac).reshape(-1, params.shape[-1])

    kwargs = {} if randkey is None else {"randkey": init_randkey(randkey)}
    ss_aux = None
    if loss_model.sumstats_func_has_aux:
        # The jac program drops aux; one extra sumstats pass fetches
        # it (rare path — none of the shipped models use sumstats aux).
        ss_aux = model.calc_sumstats_from_params(params,
                                                 randkey=randkey)[1]
        if not hasattr(model, "streams") and loss_model.comm is not None:
            # The resident distributed program returns aux shard-
            # STACKED (leading comm.size axis), while the loss
            # contract is a per-shard view — and the loss is
            # replicated-consistent across shards by construction, so
            # any one shard's view is the right argument.  (Streaming
            # aux is already an additive total, matching its own
            # _loss_from_total convention.)
            ss_aux = jax.tree_util.tree_map(lambda a: a[0], ss_aux)

    def loss_of_y(y_flat):
        args = (y_flat.reshape(y.shape), ss_aux) \
            if loss_model.sumstats_func_has_aux \
            else (y_flat.reshape(y.shape),)
        out = loss_model.calc_loss_from_sumstats(*args, **kwargs)
        return out[0] if loss_model.loss_func_has_aux else out

    hess_y = jax.jit(jax.hessian(loss_of_y))(y.ravel())
    fisher = jac.T @ hess_y @ jac
    fisher = 0.5 * (fisher + fisher.T)     # exact symmetry
    return FisherResult(params=params, fisher=fisher, jac=jac,
                        sumstats=y, sumstats_hessian=hess_y)


def _group_fisher_information(group, params, randkey=None,
                              mode: str = "fwd") -> FisherResult:
    """Joint Fisher of an :class:`~multigrad_tpu.core.group
    .OnePointGroup`: the group loss is the SUM of member losses and
    each member's loss reads only its own sumstats, so the joint
    Gauss–Newton Fisher is the sum of member Fishers — every member's
    Jacobian already differentiates w.r.t. the JOINT parameter vector
    (``param_view`` members gather their slice in-graph, so the
    gather's Jacobian lands the columns in the right joint slots).
    The factors are returned stacked: ``jac`` is the members'
    Jacobians vstacked, ``sumstats_hessian`` their block-diagonal
    composition, preserving ``fisher == jac.T @ H_y @ jac``.
    """
    members = [fisher_information(m, params, randkey=randkey,
                                  mode=mode)
               for m in group.models]
    fisher = sum(m.fisher for m in members)
    jac = jnp.vstack([m.jac for m in members])
    sumstats = jnp.concatenate(
        [jnp.ravel(m.sumstats) for m in members])
    sizes = [m.sumstats_hessian.shape[0] for m in members]
    hess = jnp.zeros((sum(sizes), sum(sizes)),
                     dtype=members[0].sumstats_hessian.dtype)
    off = 0
    for m, n in zip(members, sizes):
        hess = hess.at[off:off + n, off:off + n].set(
            m.sumstats_hessian)
        off += n
    return FisherResult(params=params, fisher=fisher, jac=jac,
                        sumstats=sumstats, sumstats_hessian=hess)


def laplace_covariance(fisher, jitter: float = 0.0):
    """Laplace posterior covariance ``F⁻¹`` via Cholesky.

    ``jitter`` (added to the diagonal, scaled by the mean diagonal)
    regularizes a singular/near-singular Fisher; a non-positive-
    definite matrix falls back to the Moore–Penrose pseudoinverse with
    a warning — unidentifiable directions then get zero (not
    infinite) variance, so check :func:`fisher_diagnostics` before
    trusting per-parameter errors.
    """
    fisher = jnp.asarray(fisher)
    ndim = fisher.shape[0]
    mat = fisher
    if jitter:
        scale = jnp.mean(jnp.abs(jnp.diagonal(fisher))) + 1e-30
        mat = fisher + jitter * scale * jnp.eye(ndim, dtype=fisher.dtype)
    chol = jnp.linalg.cholesky(mat)
    if bool(jnp.any(~jnp.isfinite(chol))):
        warnings.warn(
            "Fisher matrix is not positive definite; falling back to "
            "pseudoinverse — some directions are unidentifiable (see "
            "fisher_diagnostics)", RuntimeWarning, stacklevel=2)
        return jnp.linalg.pinv(mat)
    eye = jnp.eye(ndim, dtype=fisher.dtype)
    inv_chol = jax.scipy.linalg.solve_triangular(chol, eye, lower=True)
    return inv_chol.T @ inv_chol


def fisher_diagnostics(fisher) -> dict:
    """Conditioning report for a Fisher matrix.

    Returns a plain dict (host numpy scalars):

    * ``eigvals`` — ascending eigenvalue spectrum;
    * ``condition_number`` — λ_max/λ_min (inf when singular);
    * ``n_unidentifiable`` — eigenvalues below
      ``ndim · eps · λ_max`` (numerically-null directions: parameter
      combinations the data does not constrain);
    * ``identifiable`` — True when no such direction exists.
    """
    fisher = np.asarray(fisher)
    eigvals = np.linalg.eigvalsh(fisher)
    lam_max = float(eigvals[-1]) if eigvals.size else 0.0
    tol = fisher.shape[0] * np.finfo(fisher.dtype).eps * abs(lam_max)
    n_null = int(np.sum(eigvals <= tol))
    lam_min = float(eigvals[0]) if eigvals.size else 0.0
    cond = float("inf") if lam_min <= tol \
        else float(lam_max / lam_min)
    return {
        "eigvals": eigvals,
        "condition_number": cond,
        "n_unidentifiable": n_null,
        "identifiable": n_null == 0,
    }
