"""Declarative SLOs over the QoS classes, evaluated live.

The observability half of the QoS subsystem
(:mod:`multigrad_tpu.serve.qos`): an :class:`Slo` states a latency
objective declaratively — *p95 < 2 s for class interactive* — and a
:class:`SloMonitor` evaluates it continuously from the latencies the
scheduler (or fleet router) feeds it:

* every served fit lands one observation in a **per-class latency
  histogram** (``multigrad_qos_fit_latency_seconds{priority_class=}``
  in the live registry, trace id as the exemplar) plus an exact
  in-process sample buffer, so :meth:`SloMonitor.evaluate` returns
  true quantiles even with no registry attached (bench, demos);
* declared objectives export as gauges
  (``multigrad_qos_slo_threshold_seconds`` /
  ``multigrad_qos_slo_quantile``) the moment the monitor is built,
  so ``LiveServer /status`` can judge a class's health from the
  registry alone — :meth:`~multigrad_tpu.telemetry.live.LiveSink
  .qos_summary` recomputes *measured vs declared* on every scrape;
* :meth:`evaluate` refreshes ``multigrad_qos_p50/p95/p99_seconds``
  and the ``multigrad_qos_slo_ok`` verdict gauges, and its return
  value is the dict ``bench.py qos_mixed_load`` flattens into the
  dossier ``telemetry.regress`` gates — a scheduling change that
  trades a protected class's tail for aggregate throughput fails CI.

The monitor buffers at most :attr:`SloMonitor.MAX_SAMPLES` latencies
per class (deterministic decimation: every other sample is dropped
when the buffer doubles), bounding memory in a long-running service
while keeping the empirical distribution's shape.
"""
from __future__ import annotations

import collections
import re
from dataclasses import dataclass
from typing import Optional

from .._lockdep import make_lock

__all__ = ["Slo", "SloMonitor", "parse_slo"]

_SLO_RE = re.compile(
    r"^\s*p(\d{1,2}(?:\.\d+)?)\s*<\s*([0-9.]+)\s*s?\s+for\s+"
    r"(?:class\s+)?(\S+)\s*$", re.IGNORECASE)


@dataclass(frozen=True)
class Slo:
    """One declarative latency objective: the ``quantile`` of class
    ``priority_class``'s end-to-end fit latency must stay under
    ``threshold_s`` seconds.

    ``budget`` is the allowed-violation fraction backing the PR-20
    error-budget engine (:class:`~multigrad_tpu.telemetry.budget
    .SloBudget`); it defaults to ``1 - quantile`` — a p95 objective
    tolerates 5 % violating requests — so every pre-budget ``Slo``
    keeps its meaning unchanged."""

    priority_class: str
    threshold_s: float
    quantile: float = 0.95
    budget: Optional[float] = None

    def __post_init__(self):
        if not isinstance(self.priority_class, str) \
                or not self.priority_class:
            raise TypeError("Slo.priority_class must be a non-empty "
                            f"str, got {self.priority_class!r}")
        object.__setattr__(self, "threshold_s",
                           float(self.threshold_s))
        object.__setattr__(self, "quantile", float(self.quantile))
        if self.threshold_s <= 0:
            raise ValueError("Slo.threshold_s must be positive")
        if not (0.0 < self.quantile < 1.0):
            raise ValueError("Slo.quantile must be in (0, 1), got "
                             f"{self.quantile}")
        budget = self.budget
        if budget is None:
            budget = round(1.0 - self.quantile, 6)
        object.__setattr__(self, "budget", float(budget))
        if not (0.0 < self.budget <= 1.0):
            raise ValueError("Slo.budget must be in (0, 1], got "
                             f"{self.budget}")

    def describe(self) -> str:
        q = self.quantile * 100
        qs = f"{q:g}"
        return (f"p{qs} < {self.threshold_s:g} s for class "
                f"{self.priority_class!r}")


def parse_slo(text: str) -> Slo:
    """Parse the declarative string form — ``"p95 < 2 s for
    interactive"`` (``class`` keyword and the ``s`` unit optional) —
    into an :class:`Slo`."""
    m = _SLO_RE.match(text)
    if m is None:
        raise ValueError(
            f"cannot parse SLO {text!r}; expected the form "
            "'p95 < 2.0 s for <class>'")
    return Slo(priority_class=m.group(3),
               threshold_s=float(m.group(2)),
               quantile=float(m.group(1)) / 100.0)


def _quantile(sorted_vals, q: float) -> Optional[float]:
    """Exact linear-interpolated quantile of a sorted sample."""
    n = len(sorted_vals)
    if not n:
        return None
    if n == 1:
        return float(sorted_vals[0])
    pos = q * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return float(sorted_vals[lo] * (1 - frac)
                 + sorted_vals[hi] * frac)


class SloMonitor:
    """Live per-class latency bookkeeping + SLO verdicts.

    Parameters
    ----------
    metrics : LiveMetrics, optional
        Registry the per-class histograms and SLO gauges export
        into (``multigrad_qos_*``); ``None`` keeps the monitor
        fully in-process (bench / demo use).
    slos : iterable of Slo | str
        Declared objectives — :class:`Slo` instances or their
        declarative string form (:func:`parse_slo`).  At most one
        per class.  Classes without a declared SLO are still
        observed (histograms, quantiles), just never judged.
    budgets : bool
        Grow a :class:`~multigrad_tpu.telemetry.budget.SloBudget`
        error-budget ledger per declared SLO (the
        ``multigrad_slo_budget_*`` gauges, burn rates, exhaustion
        ETA).  On by default; the rollup-overhead bench's baseline
        leg turns it off for a fair A/B.
    """

    MAX_SAMPLES = 8192

    def __init__(self, metrics=None, slos=(),
                 prefix: str = "multigrad_qos",
                 budgets: bool = True):
        self.metrics = metrics
        self.prefix = prefix
        self.slos: dict = {}
        for s in (slos or ()):
            if isinstance(s, str):
                s = parse_slo(s)
            if not isinstance(s, Slo):
                raise TypeError(f"slos entries must be Slo or str, "
                                f"got {type(s).__name__}")
            if s.priority_class in self.slos:
                raise ValueError("duplicate SLO for class "
                                 f"{s.priority_class!r}")
            self.slos[s.priority_class] = s
        self._lock = make_lock("serve.slo.SloMonitor._lock")
        # Error-budget ledgers, one per declared SLO.  Built (and
        # fed) OUTSIDE the monitor lock: a ledger exports gauges into
        # the registry, and registry work under the monitor lock
        # would be a gratuitous lock-order edge.
        self.budgets: dict = {}
        if budgets:
            from ..telemetry.budget import SloBudget
            for s in self.slos.values():
                self.budgets[s.priority_class] = SloBudget(
                    s.priority_class, s.threshold_s,
                    budget=s.budget, live=metrics)
        self._samples: dict = {}            # class -> [e2e_s, ...]
        self._shed_by_class: collections.Counter = \
            collections.Counter()
        self._shed_by_tenant: collections.Counter = \
            collections.Counter()
        # Thresholds export immediately: /status judges a class from
        # the registry alone, so the declaration must be visible
        # before the first observation arrives.
        if metrics is not None:
            for s in self.slos.values():
                labels = {"priority_class": s.priority_class}
                metrics.set(f"{prefix}_slo_threshold_seconds",
                            s.threshold_s, labels=labels,
                            help="declared per-class latency SLO "
                                 "threshold")
                metrics.set(f"{prefix}_slo_quantile", s.quantile,
                            labels=labels,
                            help="quantile the class's SLO is "
                                 "declared over")

    # -- write side ---------------------------------------------------------
    def observe(self, priority_class: str, tenant: str, e2e_s: float,
                trace_id: Optional[str] = None):
        """One served fit: its end-to-end latency joins the class's
        sample buffer and (when a registry is attached) the
        per-class histogram, with the trace id as the exemplar."""
        e2e_s = float(e2e_s)
        with self._lock:
            buf = self._samples.setdefault(priority_class, [])
            buf.append(e2e_s)
            if len(buf) > self.MAX_SAMPLES:
                # Deterministic decimation: halve by dropping every
                # other sample — keeps the distribution's shape,
                # bounds memory, stays reproducible (no RNG).
                del buf[::2]
        m = self.metrics
        if m is not None:
            m.observe(f"{self.prefix}_fit_latency_seconds", e2e_s,
                      labels={"priority_class": priority_class},
                      exemplar=trace_id,
                      help="end-to-end served fit latency by "
                           "priority class")
            m.inc(f"{self.prefix}_fits_total",
                  labels={"priority_class": priority_class,
                          "tenant": tenant},
                  help="served fits by priority class and tenant")
        ledger = self.budgets.get(priority_class)
        if ledger is not None:
            ledger.observe(e2e_s, trace_id=trace_id)

    def record_shed(self, priority_class: str, tenant: str):
        """One class-aware shed (queue eviction or fleet-wide
        reject) against this class/tenant."""
        with self._lock:
            self._shed_by_class[priority_class] += 1
            self._shed_by_tenant[tenant] += 1
        m = self.metrics
        if m is not None:
            m.inc(f"{self.prefix}_shed_total",
                  labels={"priority_class": priority_class},
                  help="requests shed, by priority class")
            m.inc(f"{self.prefix}_shed_tenant_total",
                  labels={"tenant": tenant},
                  help="requests shed, by tenant")
        ledger = self.budgets.get(priority_class)
        if ledger is not None:
            # A shed request never met its objective: it burns
            # budget exactly like a late one.
            ledger.record_shed()

    # -- read side ----------------------------------------------------------
    def evaluate(self) -> dict:
        """Per-class health: ``{class: {count, p50_s, p95_s, p99_s,
        max_s, shed, slo?}}`` where ``slo`` (present for declared
        classes) carries ``{target, quantile, threshold_s,
        measured_s, ok}`` — ``ok`` is ``None`` until the class has
        data.  Refreshes the ``multigrad_qos_p*_seconds`` and
        ``multigrad_qos_slo_ok`` gauges when a registry is
        attached."""
        with self._lock:
            samples = {c: sorted(v)
                       for c, v in self._samples.items()}
            shed = dict(self._shed_by_class)
        out: dict = {}
        for cls in sorted(set(samples) | set(self.slos)):
            vals = samples.get(cls, [])
            entry = {
                "count": len(vals),
                "p50_s": _quantile(vals, 0.50),
                "p95_s": _quantile(vals, 0.95),
                "p99_s": _quantile(vals, 0.99),
                "max_s": vals[-1] if vals else None,
                "shed": shed.get(cls, 0),
            }
            slo = self.slos.get(cls)
            if slo is not None:
                measured = _quantile(vals, slo.quantile)
                entry["slo"] = {
                    "target": slo.describe(),
                    "quantile": slo.quantile,
                    "threshold_s": slo.threshold_s,
                    "measured_s": measured,
                    "ok": (None if measured is None
                           else bool(measured <= slo.threshold_s)),
                }
            ledger = self.budgets.get(cls)
            if ledger is not None:
                snap = ledger.snapshot()
                entry["budget"] = {
                    k: snap[k] for k in
                    ("budget", "remaining_frac", "burn_rate",
                     "fast_burning", "exhaustion_eta_s",
                     "violations")}
            out[cls] = entry
        m = self.metrics
        if m is not None:
            for cls, entry in out.items():
                labels = {"priority_class": cls}
                for name, key in (("p50", "p50_s"), ("p95", "p95_s"),
                                  ("p99", "p99_s")):
                    if entry[key] is not None:
                        m.set(f"{self.prefix}_{name}_seconds",
                              entry[key], labels=labels,
                              help=f"measured {name} end-to-end fit "
                                   "latency by priority class")
                verdict = entry.get("slo", {}).get("ok")
                if verdict is not None:
                    m.set(f"{self.prefix}_slo_ok",
                          1.0 if verdict else 0.0, labels=labels,
                          help="1 when the class's measured "
                               "quantile meets its declared SLO")
        return out

    def ok(self) -> bool:
        """True when every declared SLO with data is met (classes
        with no observations yet don't fail the verdict)."""
        return all(e["slo"]["ok"] is not False
                   for e in self.evaluate().values() if "slo" in e)

    def snapshot(self) -> dict:
        """JSON-able monitor state for ``/status`` style surfaces:
        per-class health plus the tenant-level shed counters."""
        with self._lock:
            shed_tenant = dict(self._shed_by_tenant)
        return {"classes": self.evaluate(),
                "shed_by_tenant": shed_tenant}
