"""Preemption-resilient fit-fleet: a router over N worker processes.

PR 10's :class:`~multigrad_tpu.serve.scheduler.FitScheduler` is
single-process end to end — one queue, one mesh, one dispatcher — and
a killed process loses every in-flight request.  This module is the
horizontal dimension: a :class:`FleetRouter` front-end that shards
incoming :class:`~multigrad_tpu.serve.queue.FitConfig` traffic across
N **worker processes** (spawned subprocesses running
``python -m multigrad_tpu.serve.worker``, each its own jax runtime
and :class:`FitScheduler`), with the failure semantics spot-TPU
serving actually needs:

* **Config-affinity routing** — requests sharing a config land on the
  worker whose bucket programs are already compiled (rendezvous
  hashing over ``(config, ndim)``, so a worker death remaps only its
  own keys).  The persistent on-disk XLA compile cache
  (:func:`~multigrad_tpu.serve.compile_cache.enable_compile_cache`)
  is shared by every worker, making a warm cache a *fleet-wide*
  asset: a request stolen or re-enqueued onto a different worker
  recompiles from a disk read, not from XLA.
* **Heartbeat health tracking** — every worker streams heartbeats
  (queue depth, in-flight count, scheduler counters); heartbeat loss
  or an unexpected process exit declares the worker lost.
* **Preemption-resilient draining** — a SIGTERM'd worker announces
  ``draining``, serves everything it already queued
  (``FitScheduler.close(drain=True)``), and exits 0; the router
  routes around it meanwhile.  A SIGKILL'd worker's in-flight
  requests are detected by heartbeat/connection loss and
  **re-enqueued on a surviving worker**, preserving the original
  wall-clock deadline and the consumed poison retry, with the full
  requeue history carried on the
  :class:`~multigrad_tpu.serve.queue.FitFuture` (``.requeues``) and a
  ``worker_lost`` postmortem bundle dumped through the existing
  flight-recorder machinery.  Requests that exhaust ``max_requeues``
  (or find no survivor) resolve with the typed
  :class:`WorkerLostError` — never silently lost, never hung.
* **Load shedding / work stealing** — a worker whose queue saturates
  rejects the request (``QueueFullError`` worker-side becomes a
  ``reject`` message); the router steals the request onto the next
  live worker, and only when *every* live worker pushed back does
  the caller see the typed :class:`FleetSaturatedError`.  Optionally
  (``shed_inflight=``) the router sheds *proactively*, routing away
  from a worker whose router-known in-flight load exceeds the least
  loaded worker's by the threshold.
* **Bounded retry-with-backoff** on worker RPC failures: a failed
  send is retried with exponential backoff before the worker is
  declared lost and the request re-enqueued.

Observability: fleet gauges (``multigrad_fleet_*``) land in the
``live=`` registry, per-worker telemetry JSONL streams are wired as
``rank_paths`` of a :class:`~multigrad_tpu.telemetry.LiveServer` so
the existing ``/fleet`` endpoint (:mod:`~multigrad_tpu.telemetry
.aggregate`) serves the cross-worker view, and the router logs
``fleet_worker`` / ``fleet_requeue`` records into ``telemetry=``.
**Distributed request tracing** (on by default, ``trace=``): a
W3C-style trace context minted per request at :meth:`FleetRouter
.submit` rides every wire hop, each stage records a span into its
process's trace JSONL (router: ``route``/``rpc_send``/``requeue``/
``result_return``; worker scheduler: ``queue_wait``/
``bucket_coalesce``/``dispatch``/``adam_segments``/``finalize``),
end-to-end latency histograms with p50/p95/p99 and exemplar trace
ids land in ``/status``, per-worker RPC round-trip time is sampled
into the ``multigrad_fleet_rpc_rtt`` gauge, and ``python -m
multigrad_tpu.telemetry.trace`` renders any request's merged
waterfall from the files alone — a chaos-killed request shows one
explicit ``requeue`` hop per worker generation it crossed.

The chaos-injection harness proving all of this lives in
:mod:`.chaos`; ``examples/fleet_chaos_demo.py`` runs the
kill-a-worker scenario end to end and CI greps its ``FLEET OK``
receipt.
"""
from __future__ import annotations

import collections
import dataclasses
import hashlib
import itertools
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from ..telemetry.tracing import Tracer
from .._lockdep import make_lock
from .compile_cache import DEFAULT_BUCKETS
from .qos import class_rank, make_tag, request_tag
from .queue import (FitCancelled, FitConfig, FitDeadlineExceeded,
                    FitFailed, FitFuture, QueueFullError)
from .wire import (JsonlChannel, config_to_wire, qos_to_wire,
                   resources_from_wire, result_from_wire,
                   rollup_from_wire, shed_from_wire)

__all__ = ["FleetRouter", "WorkerHandle", "WorkerLostError",
           "FleetSaturatedError"]


class WorkerLostError(RuntimeError):
    """A request's worker died and the fleet could not finish it —
    requeues exhausted, or no surviving worker to re-enqueue on.
    ``requeues`` carries the request's full migration history (the
    same entries as ``FitFuture.requeues``), each with the lost
    worker, the reason, and the ``worker_lost`` postmortem bundle
    path when one was dumped."""

    def __init__(self, message: str, request_id=None, requeues=None):
        self.request_id = request_id
        self.requeues = list(requeues or ())
        super().__init__(message)


class FleetSaturatedError(QueueFullError):
    """Admission-reject: every live worker's queue pushed back.  The
    fleet-level analog of :class:`~multigrad_tpu.serve.queue
    .QueueFullError` — raised onto the future only after reroute
    (work stealing) was attempted on every live worker.

    With QoS-aware workers the error carries *why*: ``reason`` is
    ``"tenant_quota"`` when the rejects said "YOU are over quota"
    (vs the default ``"queue_full"``, "the fleet is busy"), and
    ``shed_by_class`` / ``shed_by_tenant`` snapshot the fleet's
    cumulative shed counters at reject time — an operator can tell
    from the exception alone whether the fix is "raise the tenant's
    quota" or "add workers".  All attributes default benign, so a
    pre-QoS fleet raises the same error it always did."""

    def __init__(self, message: str, reason: str = "queue_full",
                 shed_by_class=None, shed_by_tenant=None):
        self.reason = reason
        self.shed_by_class = dict(shed_by_class or {})
        self.shed_by_tenant = dict(shed_by_tenant or {})
        super().__init__(message)


@dataclass
class FleetRequest:
    """Router-side bookkeeping for one fleet fit request."""

    id: str
    guess: np.ndarray
    config: FitConfig
    future: FitFuture
    deadline_t: Optional[float] = None     # absolute wall clock
    submitted_t: float = field(default_factory=time.time)
    worker: Optional[str] = None           # current home
    poison_retried: bool = False           # consumed its one retry
    rejected_by: set = field(default_factory=set)
    # Distributed tracing: the context minted at submit, the
    # router-side hop-latency accumulator (route / rpc_send /
    # result_return / requeue seconds, merged with the worker-side
    # hops onto FitResult.hops), the wall clock of the latest
    # dispatch send (a requeue span covers [last dispatch, requeue]
    # — the whole lost attempt INCLUDING the heartbeat-timeout
    # detection window, which no live process can span), and the
    # one-root latch.
    trace: Optional[object] = None
    hops: dict = field(default_factory=dict)
    last_dispatch_t: Optional[float] = None
    root_recorded: bool = False
    # QoS tag (qos.QosTag | None).  Deliberately NOT part of `key`:
    # the batchability identity stays (config, ndim), so same-config
    # fits from different tenants share one affinity home — and one
    # bucket — instead of fragmenting the compile cache per tenant.
    qos: Optional[object] = None

    @property
    def key(self) -> str:
        """Affinity key: the batchability identity — the same
        (config, ndim) pair the scheduler's queue groups buckets by,
        rendered through the frozen dataclass repr so a future
        FitConfig field joins the routing key automatically."""
        return repr((self.config, int(self.guess.shape[0])))


class WorkerHandle:
    """One fleet worker: process + channel + health/load state.

    ``state`` walks ``up → draining → dead`` (or straight to
    ``dead`` on SIGKILL/heartbeat loss).  ``inflight`` maps request
    ids to :class:`FleetRequest`\\ s currently homed on this worker —
    the set the router re-enqueues when the worker is lost.
    """

    def __init__(self, worker_id: str, proc=None, chan=None,
                 telemetry_path: Optional[str] = None,
                 log_path: Optional[str] = None,
                 live_port: Optional[int] = None,
                 trace_path: Optional[str] = None):
        self.id = worker_id
        self.proc = proc
        self.chan = chan
        self.telemetry_path = telemetry_path
        self.log_path = log_path
        self.live_port = live_port
        self.trace_path = trace_path
        self.state = "up"
        self.last_heartbeat = time.time()
        self.rpc_rtt_s: Optional[float] = None
        self._rtt_logged_t = 0.0
        self.queue_depth = 0
        self.saturated_until = 0.0
        self.inflight: dict = {}
        self.sched_stats: dict = {}
        # Live resource view (latest heartbeat snapshot) plus a small
        # ring of recent snapshots: a SIGKILL'd worker cannot dump
        # its own resource ring, so the router's copy of its last
        # heartbeats IS the ring its worker_lost postmortem captures.
        self.resources: Optional[dict] = None
        self.resource_ring: collections.deque = \
            collections.deque(maxlen=32)
        self.drained = threading.Event()

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def routable(self) -> bool:
        return self.state == "up"

    def send(self, msg: dict):
        if self.chan is None:
            raise OSError(f"worker {self.id} has no channel")
        self.chan.send(msg)

    def load(self) -> int:
        """Router-known load: requests homed here and unresolved.
        Known synchronously (unlike heartbeat queue depth, which lags
        by one interval), so burst routing can balance on it."""
        return len(self.inflight)


class FleetRouter:
    """Config-affinity router over N fit-fleet worker processes.

    Parameters
    ----------
    n_workers : int
        Worker processes to spawn (0 is allowed: tests register
        handles manually).
    model, model_kwargs :
        The worker model spec, resolved by ``multigrad_tpu.serve
        .worker`` — the builtin ``"smf"`` (``model_kwargs`` may carry
        ``num_halos``) or a ``"module:factory"`` path whose factory
        receives ``model_kwargs``.
    base_dir : str, optional
        Fleet working directory (default: a fresh temp dir): worker
        telemetry JSONLs, worker logs, postmortem bundles and — when
        ``compile_cache="auto"`` — the shared on-disk XLA compile
        cache all land here.
    buckets, max_pending, batch_window_s, retry_poisoned :
        Forwarded to each worker's :class:`~multigrad_tpu.serve
        .scheduler.FitScheduler`.
    devices, platform :
        Each worker's jax runtime: ``XLA_FLAGS=--xla_force_host_
        platform_device_count=<devices>`` and
        ``JAX_PLATFORMS=<platform>`` are set in the worker
        environment (they must be set before the worker imports jax,
        which is why the router owns them).
    compile_cache : str | None
        Shared persistent XLA compile-cache directory — the
        fleet-wide warm asset.  ``"auto"`` (default) puts it under
        ``base_dir``; ``None`` disables persistence.
    telemetry : MetricsLogger, optional
        Router-side ``fleet_worker`` / ``fleet_requeue`` records.
    live : LiveServer | LiveSink | LiveMetrics, optional
        Fleet gauges (``multigrad_fleet_*``).  A ``LiveServer`` whose
        ``rank_paths`` is unset additionally gets the workers'
        telemetry paths, so its ``/fleet`` endpoint serves the
        cross-worker aggregate view.
    heartbeat_s / heartbeat_timeout_s :
        Worker heartbeat period and the age beyond which a worker is
        declared lost.
    max_requeues : int
        How many times one request may be re-enqueued off dead
        workers before it resolves with :class:`WorkerLostError`.
    rpc_retries / rpc_backoff_s :
        Bounded exponential backoff for a failed worker send before
        the worker is declared lost.
    shed_inflight : int, optional
        Proactive load shedding: route away from the affinity home
        when its router-known in-flight load exceeds the least
        loaded live worker's by at least this many requests
        (``None`` disables; reject-driven stealing still applies).
    trace : bool | Tracer
        Distributed request tracing (default on).  ``True`` writes
        the router's spans to ``<base_dir>/router.trace.jsonl`` and
        spawns every worker with its own ``<worker>.trace.jsonl``;
        a :class:`~multigrad_tpu.telemetry.tracing.Tracer` instance
        substitutes for the router's own sink.  A trace context is
        minted per request at :meth:`submit` and propagated on the
        wire, so each request's full hop journey — across requeues
        and worker generations — merges into one waterfall
        (``python -m multigrad_tpu.telemetry.trace`` over
        :attr:`trace_paths`).  ``False`` disables tracing.
    worker_live_port : int, optional
        Base port for each worker's own :class:`~multigrad_tpu
        .telemetry.LiveServer`.  All workers get the SAME base —
        the ``EADDRINUSE`` bind-retry probes forward, and each
        worker's ``/status`` reports the port it actually bound.
    chaos : bool
        Spawn workers with ``--chaos`` so the
        :class:`~multigrad_tpu.serve.chaos.ChaosController` can
        inject protocol-level faults (queue-full rejects, stalls).
    qos : bool
        Multi-tenant QoS (default off): spawn every worker with
        ``--qos`` (weighted-fair dequeue, class-aware shed,
        deadline-aware packing; see :mod:`~multigrad_tpu.serve.qos`)
        and propagate each request's tag on the wire.  Off, tags
        still ride :meth:`submit` for telemetry but workers dequeue
        FIFO.
    tenant_quota : int, optional
        Per-tenant queued-request cap forwarded to each worker
        (requires ``qos=True``); an over-quota submit rejects with
        reason ``"tenant_quota"`` — which the router treats as "this
        tenant is over", NOT as fleet saturation (the worker is not
        marked saturated, other tenants keep routing to it).
    slo : SloMonitor | iterable of Slo | str, optional
        Router-side SLO monitor (see :mod:`~multigrad_tpu.serve
        .slo`): every served fit's end-to-end latency is observed
        per priority class, declared objectives export as
        ``multigrad_qos_*`` gauges into ``live=``, and
        ``router.slo.evaluate()`` judges them.  Iterables/strings
        are declarative objectives (``"p95 < 2 s for
        interactive"``); ``qos=True`` with no ``slo`` still attaches
        a bare monitor (observation without judgment).
    """

    #: Minimum seconds between ``trace_rtt`` JSONL samples per
    #: worker (the RTT gauge still refreshes on every pong).
    RTT_LOG_INTERVAL_S = 10.0

    def __init__(self, n_workers: int = 2, *,
                 model: str = "smf",
                 model_kwargs: Optional[dict] = None,
                 base_dir: Optional[str] = None,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 max_pending: int = 1024,
                 batch_window_s: float = 0.05,
                 retry_poisoned: bool = True,
                 devices: int = 1,
                 platform: str = "cpu",
                 compile_cache: Optional[str] = "auto",
                 telemetry=None, live=None,
                 heartbeat_s: float = 0.25,
                 heartbeat_timeout_s: float = 2.0,
                 max_requeues: int = 2,
                 rpc_retries: int = 3,
                 rpc_backoff_s: float = 0.05,
                 shed_inflight: Optional[int] = None,
                 saturate_cooldown_s: float = 0.5,
                 trace=True,
                 worker_live_port: Optional[int] = None,
                 chaos: bool = False,
                 qos: bool = False,
                 tenant_quota: Optional[int] = None,
                 slo=None,
                 spawn_timeout_s: float = 240.0,
                 worker_args: Optional[Sequence[str]] = None,
                 env: Optional[dict] = None):
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="mgt_fleet_")
        os.makedirs(self.base_dir, exist_ok=True)
        self.model = model
        self.model_kwargs = dict(model_kwargs or {})
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.max_pending = int(max_pending)
        self.batch_window_s = float(batch_window_s)
        self.retry_poisoned = bool(retry_poisoned)
        self.devices = int(devices)
        self.platform = platform
        self.compile_cache = (os.path.join(self.base_dir, "xla_cache")
                              if compile_cache == "auto"
                              else compile_cache)
        self.telemetry = telemetry
        self._metrics = getattr(live, "metrics", live)
        from ..telemetry.live import LatencyObserver
        self._latency = LatencyObserver(self._metrics,
                                        "multigrad_fleet",
                                        "fleet fit")
        self.heartbeat_s = float(heartbeat_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.max_requeues = int(max_requeues)
        self.rpc_retries = int(rpc_retries)
        self.rpc_backoff_s = float(rpc_backoff_s)
        self.shed_inflight = shed_inflight
        self.saturate_cooldown_s = float(saturate_cooldown_s)
        self.worker_live_port = worker_live_port
        self.chaos_enabled = bool(chaos)
        self.qos_enabled = bool(qos)
        self.tenant_quota = tenant_quota
        from .slo import SloMonitor
        if isinstance(slo, SloMonitor):
            self.slo = slo
        elif slo is not None:
            self.slo = SloMonitor(self._metrics, slo)
        elif self.qos_enabled:
            self.slo = SloMonitor(self._metrics, ())
        else:
            self.slo = None
        # Fleet-wide shed accounting, accumulated from QoS-aware
        # workers' reject messages (wire `shed` field) under _lock.
        self._shed_by_class: dict = {}
        self._shed_by_tenant: dict = {}
        # Fleet-level history plane (PR 20): every worker heartbeat's
        # compact rollup delta merges here, so windowed fleet rates
        # and queue-wait trends survive a SIGKILL'd worker — the
        # worker's own store dies with it, the merged history does
        # not.  Also a sink on the router's record stream and a
        # scraper of its registry, so router-side fit_summary /
        # resource_sample records land in the same windows.
        from ..telemetry.rollup import RollupStore
        self.rollup = RollupStore()
        if telemetry is not None:
            telemetry.add_sink(self.rollup)
        if self._metrics is not None:
            self.rollup.attach_live(self._metrics)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.worker_args = list(worker_args or ())
        self._env = env

        self._owns_tracer = False
        if trace is True:
            self._tracer = Tracer(
                os.path.join(self.base_dir, "router.trace.jsonl"),
                service="router")
            self._owns_tracer = True
        elif trace:
            self._tracer = trace
        else:
            self._tracer = None

        from ..telemetry.flight import FlightRecorder
        self._recorder = FlightRecorder(
            dump_dir=os.path.join(self.base_dir, "postmortems"),
            trip_on_stall=False, divergence_spike=None)

        # The router claims futures (FitFuture._set_running takes
        # the future's own lock) inside its registry critical
        # section — an ordering the AST cannot derive through the
        # dynamic `req.future` dispatch, hence declared.
        self._lock = make_lock(
            "serve.fleet.FleetRouter._lock",
            may_precede=("serve.queue.FitFuture._lock",))
        self._ids = itertools.count()
        self._requests: dict = {}
        # Sticky config homes: key -> worker id of the last dispatch.
        # Affinity must survive a steal — when load shedding (or a
        # reject) moves a config off its hash home, the config's
        # LATER traffic follows it, so one compiled program still
        # serves the whole stream instead of every batch window
        # being paid twice on two half-groups.
        self._key_home: dict = {}
        self._stats: dict = {}
        self._first_submit_t: Optional[float] = None
        self._last_completed_t: Optional[float] = None
        self._closing = False
        self.workers: list = []
        self._reader_threads: list = []

        for i in range(int(n_workers)):
            self.workers.append(self._spawn(f"w{i}"))
        # Wire the /fleet plane: the per-worker telemetry JSONLs are
        # exactly the "per-rank files" aggregate.py merges.
        paths = [w.telemetry_path for w in self.workers
                 if w.telemetry_path]
        if live is not None and paths \
                and getattr(live, "rank_paths", "absent") is None:
            live.rank_paths = paths
        self._monitor_stop = threading.Event()
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name="mgt-fleet-monitor")
        self._monitor.start()
        self._refresh_gauges()

    # ------------------------------------------------------------------ #
    # worker lifecycle
    # ------------------------------------------------------------------ #
    def _worker_env(self) -> dict:
        env = dict(os.environ if self._env is None else self._env)
        env["JAX_PLATFORMS"] = self.platform
        env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                            f"{self.devices}")
        # The workers must import the same multigrad_tpu the router
        # runs — prepend its repo root so a source checkout works
        # without installation (harmless when pip-installed).
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep \
            + env.get("PYTHONPATH", "")
        return env

    def _spawn(self, worker_id: str) -> WorkerHandle:
        telemetry_path = os.path.join(self.base_dir,
                                      f"{worker_id}.jsonl")
        log_path = os.path.join(self.base_dir, f"{worker_id}.log")
        trace_path = (os.path.join(self.base_dir,
                                   f"{worker_id}.trace.jsonl")
                      if self._tracer is not None else None)
        cmd = [sys.executable, "-m", "multigrad_tpu.serve.worker",
               "--worker-id", worker_id,
               "--rank", str(len(self.workers)), "--port", "0",
               "--model", self.model,
               "--model-kwargs", json.dumps(self.model_kwargs),
               "--buckets", ",".join(str(b) for b in self.buckets),
               "--max-pending", str(self.max_pending),
               "--batch-window-s", str(self.batch_window_s),
               "--heartbeat-s", str(self.heartbeat_s),
               "--telemetry", telemetry_path,
               "--flight-dir",
               os.path.join(self.base_dir, "postmortems")]
        if trace_path is not None:
            cmd += ["--trace", trace_path]
        if not self.retry_poisoned:
            cmd.append("--no-retry-poisoned")
        if self.compile_cache:
            cmd += ["--compile-cache", self.compile_cache]
        if self.worker_live_port is not None:
            cmd += ["--live-port", str(self.worker_live_port)]
        if self.chaos_enabled:
            cmd.append("--chaos")
        if self.qos_enabled:
            cmd.append("--qos")
            if self.tenant_quota is not None:
                cmd += ["--tenant-quota", str(self.tenant_quota)]
        cmd += self.worker_args
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=self._worker_env())

        ready: dict = {}
        ready_evt = threading.Event()

        def _drain_stdout():
            # All worker output lands in a per-worker log; the READY
            # handshake is parsed on the way through.
            with open(log_path, "w") as log:
                for line in proc.stdout:
                    log.write(line)
                    log.flush()
                    if line.startswith("FLEET-WORKER-READY "):
                        try:
                            ready.update(json.loads(
                                line.split(" ", 1)[1]))
                        except ValueError:
                            pass
                        ready_evt.set()
            ready_evt.set()       # EOF: unblock the spawn wait too

        threading.Thread(target=_drain_stdout, daemon=True,
                         name=f"mgt-fleet-{worker_id}-log").start()
        if not ready_evt.wait(self.spawn_timeout_s) or "port" not in ready:
            proc.kill()
            tail = ""
            try:
                with open(log_path) as f:
                    tail = f.read()[-2000:]
            except OSError:
                pass
            raise RuntimeError(
                f"fleet worker {worker_id} failed to start within "
                f"{self.spawn_timeout_s}s (rc={proc.poll()}):\n{tail}")
        import socket as _socket
        sock = _socket.create_connection(
            ("127.0.0.1", int(ready["port"])), timeout=10)
        handle = WorkerHandle(
            worker_id, proc=proc, chan=JsonlChannel(sock),
            telemetry_path=telemetry_path, log_path=log_path,
            live_port=ready.get("live_port"),
            trace_path=trace_path)
        t = threading.Thread(target=self._reader, args=(handle,),
                             daemon=True,
                             name=f"mgt-fleet-{worker_id}-reader")
        t.start()
        self._reader_threads.append(t)
        self._log_event("fleet_worker", worker=worker_id,
                        state="up", pid=proc.pid,
                        live_port=ready.get("live_port"))
        return handle

    # ------------------------------------------------------------------ #
    # submit side
    # ------------------------------------------------------------------ #
    def submit(self, guess, nsteps: int = 100,
               learning_rate: float = 0.01, param_bounds=None,
               randkey=None, const_randkey: bool = False,
               config: Optional[FitConfig] = None,
               deadline_s: Optional[float] = None,
               trace=None, qos=None, tenant: Optional[str] = None,
               priority_class: Optional[str] = None,
               slo_deadline_s: Optional[float] = None) -> FitFuture:
        """Queue one fit on the fleet; returns its
        :class:`~multigrad_tpu.serve.queue.FitFuture`.

        Same surface as :meth:`FitScheduler.submit
        <multigrad_tpu.serve.scheduler.FitScheduler.submit>` minus
        the queue-blocking knobs (fleet backpressure is reroute →
        typed :class:`FleetSaturatedError`).  ``deadline_s`` is
        converted to an absolute wall-clock deadline once, here — a
        requeue after a worker death does NOT reset it.

        With tracing on (the default) this is the **mint point** of
        the request's trace: a fresh W3C-style context is created
        here, propagated on every wire hop, and closed by the root
        ``request`` span when the future settles — the returned
        future carries the id as ``.trace_id``.  ``trace`` overrides
        the mint with a caller-supplied
        :class:`~multigrad_tpu.telemetry.tracing.TraceContext` — the
        job-DAG runner (:mod:`multigrad_tpu.serve.jobs`) passes a
        child of its stage span, so every per-fit ``request`` span
        parents into the job's single waterfall instead of starting
        a trace of its own.

        ``qos`` (a :class:`~multigrad_tpu.serve.qos.QosTag`) — or
        the convenience kwargs ``tenant`` / ``priority_class`` /
        ``slo_deadline_s`` — tags the request for multi-tenant
        scheduling; the tag rides the wire to the worker (honored
        under ``qos=True``, ignored by pre-QoS workers) and an SLO
        deadline doubles as the request deadline when ``deadline_s``
        is unset.
        """
        if self._closing:
            raise RuntimeError("fleet router is closed")
        tag = make_tag(qos, tenant, priority_class, slo_deadline_s)
        if deadline_s is None and tag is not None \
                and tag.slo_deadline_s is not None:
            deadline_s = tag.slo_deadline_s
        if config is None:
            config = FitConfig(
                nsteps=nsteps, learning_rate=learning_rate,
                param_bounds=param_bounds, randkey=randkey,
                const_randkey=const_randkey)
        guess = np.asarray(guess, dtype=float)
        from .scheduler import FitScheduler
        FitScheduler._validate(guess, config)
        rid = f"r{next(self._ids)}"
        ctx = trace
        if ctx is None:
            ctx = self._tracer.new_trace() \
                if self._tracer is not None else None
        future = FitFuture(rid)
        if ctx is not None:
            future.trace_id = ctx.trace_id
        req = FleetRequest(
            id=rid, guess=guess, config=config,
            future=future, trace=ctx, qos=tag,
            deadline_t=(time.time() + float(deadline_s)
                        if deadline_s is not None else None))
        with self._lock:
            self._requests[rid] = req
            self._count_locked("submitted")
            if self._first_submit_t is None:
                self._first_submit_t = req.submitted_t
        self._dispatch(req)
        return req.future

    # ------------------------------------------------------------------ #
    # routing
    # ------------------------------------------------------------------ #
    def _affinity_order(self, key: str) -> list:
        """Rendezvous (highest-random-weight) order of ALL workers
        for one affinity key: deterministic, and a worker's death
        remaps only the keys it owned."""
        def weight(w):
            return hashlib.md5(
                f"{key}|{w.id}".encode()).hexdigest()
        return sorted(self.workers, key=weight, reverse=True)

    def _route(self, req: FleetRequest, exclude=frozenset()
               ) -> Optional[WorkerHandle]:
        now = time.time()
        order = [w for w in self._affinity_order(req.key)
                 if w.routable() and w.id not in exclude]
        if not order:
            return None
        with self._lock:
            sticky = self._key_home.get(req.key)
        pick = next((w for w in order if w.id == sticky), None)
        if pick is None:
            # New (or orphaned) key: hash home first; skip recently-
            # saturated workers when a fresh one exists (reject-
            # driven stealing sets the flag).
            candidates = [w for w in order
                          if w.saturated_until <= now] or order
            pick = candidates[0]
            if self.shed_inflight is not None and len(candidates) > 1:
                # Proactive shed — only at key-assignment time, so a
                # config's burst is never split across two workers'
                # batch windows: abandon the hash home when it is
                # this much deeper than the lightest live worker.
                lightest = min(candidates, key=WorkerHandle.load)
                if pick.load() - lightest.load() >= self.shed_inflight:
                    pick = lightest
        with self._lock:
            self._key_home[req.key] = pick.id
        return pick

    def _dispatch(self, req: FleetRequest, exclude=frozenset()):
        if req.future.done():
            return            # cancelled (or settled) while pending
        t_route = time.time()
        worker = self._route(req, exclude)
        if worker is None:
            self._settle_lost(
                req, "no live fleet worker available")
            return
        with self._lock:
            if worker.state != "up":
                # Lost between route and claim: try again without it.
                pass
            else:
                req.worker = worker.id
                req.future._set_running()
                worker.inflight[req.id] = req
        if req.worker != worker.id:
            self._dispatch(req, exclude | {worker.id})
            return
        self._trace_hop(req, "route", t_route, worker=worker.id)
        msg = {"op": "submit", "rid": req.id,
               "guess": req.guess.tolist(),
               "config": config_to_wire(req.config),
               "deadline_t": req.deadline_t,
               "retried": req.poison_retried,
               "submitted_t": req.submitted_t}
        if req.trace is not None:
            msg["trace"] = req.trace.to_wire()
        if req.qos is not None:
            # Key stays off untagged messages entirely: an untagged
            # router's traffic is byte-identical to the pre-QoS
            # protocol.
            msg["qos"] = qos_to_wire(req.qos)
        # lock-ok: unlocked-shared-write single-owner field: only the thread that just claimed the request under _lock (it is in exactly one worker's inflight map) reaches this write; readers (_requeue) run only after popping the claim back
        req.last_dispatch_t = time.time()
        self._send_with_retry(worker, msg, req)

    def _send_with_retry(self, worker: WorkerHandle, msg: dict,
                         req: FleetRequest):
        """Bounded retry-with-backoff on RPC failures, then declare
        the worker lost and re-enqueue the request elsewhere."""
        t0 = time.time()
        n_attempts = 0
        for attempt in range(self.rpc_retries):
            n_attempts = attempt + 1
            try:
                worker.send(msg)
                # The span covers backoff sleeps of earlier failed
                # attempts — rpc_send time as the tenant experienced
                # it, not just the final successful write.
                self._trace_hop(req, "rpc_send", t0,
                                worker=worker.id,
                                attempts=n_attempts)
                return
            except OSError:
                if worker.state != "up":
                    break
                time.sleep(self.rpc_backoff_s * (2 ** attempt))
        # n_attempts is the sends actually tried — the loop breaks
        # early on a known-down worker, and an operator reading the
        # failed span must not conclude the whole backoff ladder ran.
        self._trace_hop(req, "rpc_send", t0, ok=False,
                        worker=worker.id,
                        attempts=n_attempts)
        # Claim the request back BEFORE declaring the worker lost —
        # and only requeue on a successful claim: a concurrent
        # _worker_lost (reader EOF, monitor) may have emptied the
        # inflight map and requeued this request already, and a
        # second requeue here would double-count the migration (and
        # could spuriously exhaust the fleet's exclude set).
        with self._lock:
            claimed = worker.inflight.pop(req.id, None)
        self._worker_lost(worker, "rpc send failure")
        if claimed is not None:
            self._requeue(req, f"rpc to worker {worker.id} failed",
                          bundle=None)

    # ------------------------------------------------------------------ #
    # worker responses (reader threads)
    # ------------------------------------------------------------------ #
    def _reader(self, handle: WorkerHandle):
        # The broad backstop is the reader's settlement contract: a
        # malformed message (or a handler bug) must kill neither the
        # thread nor the worker's inflight futures silently — the
        # disconnect path requeues or settles every one of them.
        try:
            self._reader_loop(handle)
        except Exception as err:
            self._log_event("fleet_reader_error", worker=handle.id,
                            error=repr(err))
        finally:
            self._on_disconnect(handle)

    def _reader_loop(self, handle: WorkerHandle):
        for msg in handle.chan:
            op = msg.get("op")
            if op == "result":
                self._on_result(handle, msg)
            elif op == "error":
                self._on_error(handle, msg)
            elif op == "reject":
                self._on_reject(handle, msg)
            elif op == "heartbeat":
                handle.last_heartbeat = time.time()
                handle.queue_depth = int(msg.get("queue_depth", 0))
                handle.sched_stats = msg.get("stats", {})
                # Optional resource snapshot (mixed-version fleet):
                # a legacy heartbeat decodes to None and leaves the
                # view unpopulated; a decorated one from a NEWER
                # worker is read known-keys-only.
                res = resources_from_wire(msg.get("resources"))
                if res is not None:
                    handle.resources = res
                    handle.resource_ring.append(res)
                    self._refresh_resource_gauges(handle, res)
                # Optional rollup delta (same mixed-version rules:
                # legacy heartbeat -> None -> no history, never
                # fabricated zeros).  Merged fleet-level; the
                # contribution outlives the worker.
                roll = rollup_from_wire(msg.get("rollup"))
                if roll is not None:
                    self.rollup.merge_delta(roll, worker=handle.id)
            elif op == "pong":
                handle.last_heartbeat = time.time()
                self._on_pong(handle, msg)
            elif op == "poison_retry":
                self._on_poison_retry(handle, msg)
            elif op == "draining":
                self._on_draining(handle,
                                  msg.get("reason", "draining"))
            elif op == "drained":
                handle.drained.set()

    def _pop_inflight(self, handle: WorkerHandle, rid
                      ) -> Optional[FleetRequest]:
        with self._lock:
            return handle.inflight.pop(rid, None)

    def _forget(self, req: FleetRequest):
        """Drop a terminally-settled request from the registry — a
        long-lived router must not pin every guess/trajectory ever
        served until shutdown."""
        with self._lock:
            self._requests.pop(req.id, None)

    def _on_result(self, handle: WorkerHandle, msg: dict):
        req = self._pop_inflight(handle, msg.get("rid"))
        if req is None or req.future.done():
            return        # late duplicate from a written-off worker
        done_t = time.time()
        sent_t = msg.get("sent_t")
        if isinstance(sent_t, (int, float)):
            self._trace_hop(req, "result_return",
                            min(sent_t, done_t), done_t,
                            worker=handle.id)
        result = result_from_wire(msg["result"], req.id,
                                  worker=handle.id)
        # The delivered hop vector is worker hops (queue_wait,
        # bucket_coalesce, dispatch, adam_segments, finalize — from
        # the wire) + router hops (route, rpc_send, result_return,
        # requeue) — the full per-request latency breakdown.
        result = dataclasses.replace(
            result,
            trace_id=(req.trace.trace_id if req.trace is not None
                      else result.trace_id),
            hops={**(result.hops or {}), **req.hops})
        # Counters, trace root, and latency histograms all land
        # BEFORE the future resolves (the scheduler's convention): a
        # caller that wakes on the last result and reads .stats or
        # /status must see the completion — and the observation —
        # that produced it.
        with self._lock:
            self._count_locked("completed")
            self._last_completed_t = done_t
        self._fits_counter("ok")
        self._trace_root(req, "ok", done_t, worker=handle.id)
        self._observe_latency(req, done_t - req.submitted_t,
                              result.hops)
        if self.slo is not None:
            tag = request_tag(req)
            self.slo.observe(tag.priority_class, tag.tenant,
                             done_t - req.submitted_t,
                             trace_id=result.trace_id)
        req.future._set_result(result)
        self._forget(req)
        self._refresh_gauges()

    def _on_error(self, handle: WorkerHandle, msg: dict):
        req = self._pop_inflight(handle, msg.get("rid"))
        if req is None or req.future.done():
            return
        if msg.get("retried"):
            req.poison_retried = True
        # Trace root BEFORE the future resolves (the convention
        # everywhere a request settles): the caller waking on this
        # error may immediately merge the trace files for triage and
        # must find a complete, rooted trace.
        self._trace_root(req, msg.get("etype", "error"),
                         worker=handle.id,
                         bundle=msg.get("bundle_path"))
        with self._lock:
            self._count_locked("failed")
        self._fits_counter("failed")
        req.future._set_exception(self._exception_from_wire(msg, req))
        self._forget(req)
        self._refresh_gauges()

    @staticmethod
    def _exception_from_wire(msg: dict, req: FleetRequest
                             ) -> BaseException:
        etype = msg.get("etype", "RuntimeError")
        message = msg.get("message", "")
        if etype == "FitFailed":
            return FitFailed(message, req.id,
                             bundle_path=msg.get("bundle_path"))
        if etype == "FitDeadlineExceeded":
            return FitDeadlineExceeded(message)
        if etype == "FitCancelled":
            return FitCancelled(message)
        if etype in ("ValueError", "TypeError"):
            return {"ValueError": ValueError,
                    "TypeError": TypeError}[etype](message)
        return RuntimeError(f"{etype}: {message}")

    def _on_reject(self, handle: WorkerHandle, msg: dict):
        """Load shed: the worker's queue is full (or it is draining).
        Steal the request onto the next live worker; admission-reject
        with the typed error only when everyone pushed back.

        QoS-aware workers say *why*: reason ``"tenant_quota"`` means
        "this TENANT is over its per-worker cap" — a per-tenant
        verdict, not fleet saturation — so the worker is NOT marked
        saturated (other tenants keep routing to it), though this
        request still moves on (a different worker has a different
        quota ledger).  The reject's cumulative ``shed`` counters
        fold into the router's fleet-wide accounting either way."""
        req = self._pop_inflight(handle, msg.get("rid"))
        if req is None or req.future.done():
            return
        reason = msg.get("reason", "queue_full")
        shed = shed_from_wire(msg.get("shed"))
        with self._lock:
            # Worker counters are CUMULATIVE: replace, don't add.
            for side, dst in (("by_class", self._shed_by_class),
                              ("by_tenant", self._shed_by_tenant)):
                dst.setdefault(handle.id, {})
                dst[handle.id] = shed[side] or dst[handle.id]
        if reason != "tenant_quota":
            handle.saturated_until = \
                time.time() + self.saturate_cooldown_s
        req.rejected_by.add(handle.id)
        with self._lock:
            self._count_locked("rejected")
        self._inc_counter("multigrad_fleet_rejects_total",
                          help="worker queue-full rejects",
                          labels={"worker": handle.id})
        remaining = [w for w in self.workers if w.routable()
                     and w.id not in req.rejected_by]
        if not remaining:
            self._trace_root(req, "shed")
            if self.slo is not None:
                tag = request_tag(req)
                self.slo.record_shed(tag.priority_class, tag.tenant)
            by_class, by_tenant = self.shed_counts()
            with self._lock:
                self._count_locked("shed")
            self._fits_counter("shed")
            req.future._set_exception(FleetSaturatedError(
                f"every live fleet worker rejected request {req.id} "
                f"(reason: {reason})", reason=reason,
                shed_by_class=by_class, shed_by_tenant=by_tenant))
            self._forget(req)
            return
        self._dispatch(req, exclude=req.rejected_by)

    def shed_counts(self) -> tuple:
        """Fleet-wide shed accounting summed over workers:
        ``(by_class, by_tenant)`` dicts from the cumulative counters
        the QoS-aware workers report on their reject messages."""
        by_class: dict = {}
        by_tenant: dict = {}
        with self._lock:
            for per_worker, dst in ((self._shed_by_class, by_class),
                                    (self._shed_by_tenant, by_tenant)):
                for counts in per_worker.values():
                    for k, v in counts.items():
                        dst[k] = dst.get(k, 0) + int(v)
        return by_class, by_tenant

    def _on_pong(self, handle: WorkerHandle, msg: dict):
        """RPC round-trip sample: the monitor's ping carried its
        send time, the worker echoed it back.  This is the fleet's
        link-latency floor — the health plane knew liveness but not
        how long a hop actually takes, and it is also the wall-clock
        noise floor to read cross-process trace offsets against.
        An old worker's pong has no ``t0``: skip, don't crash
        (mixed-version fleet)."""
        t0 = msg.get("t0")
        if not isinstance(t0, (int, float)):
            return
        now = time.time()
        rtt = max(0.0, now - t0)
        handle.rpc_rtt_s = rtt
        if self._metrics is not None:
            self._metrics.set(
                "multigrad_fleet_rpc_rtt", rtt,
                help="per-worker heartbeat-RPC round-trip seconds",
                labels={"worker": handle.id})
        # The gauge refreshes on every pong; the JSONL noise-floor
        # sample is throttled per worker — the monitor pings up to
        # 4x/s and an unthrottled log would grow the trace file by
        # megabytes/hour on a long-lived router, dwarfing the
        # request spans it exists to annotate.
        if self._tracer is not None \
                and now - handle._rtt_logged_t \
                >= self.RTT_LOG_INTERVAL_S:
            handle._rtt_logged_t = now
            self._tracer.log("trace_rtt", worker=handle.id,
                             rtt_s=round(rtt, 6))

    def _on_poison_retry(self, handle: WorkerHandle, msg: dict):
        with self._lock:
            req = self._requests.get(msg.get("rid"))
        if req is not None:
            req.poison_retried = True

    def _on_draining(self, handle: WorkerHandle, reason: str):
        with self._lock:
            if handle.state == "up":
                handle.state = "draining"
        self._log_event("fleet_worker", worker=handle.id,
                        state="draining", reason=reason)
        self._refresh_gauges()

    def _on_disconnect(self, handle: WorkerHandle):
        if self._closing:
            # Shutdown owns the cleanup — but the drain wait blocks
            # on inflight, so a worker dying mid-close must still
            # release its entries (close() settles their futures).
            with self._lock:
                handle.state = "dead"
                handle.inflight.clear()
            return
        if handle.state == "dead":
            return
        if handle.state == "draining":
            self._worker_drained(handle)
        else:
            self._worker_lost(handle, "connection closed")

    # ------------------------------------------------------------------ #
    # death / drain / requeue
    # ------------------------------------------------------------------ #
    def _worker_lost(self, handle: WorkerHandle, reason: str):
        """Declare a worker lost and re-enqueue its in-flight
        requests on survivors — the preemption-resilience core."""
        with self._lock:
            if handle.state == "dead":
                return
            handle.state = "dead"
            inflight = list(handle.inflight.values())
            handle.inflight.clear()
            self._count_locked("worker_deaths")
        if handle.proc is not None and handle.proc.poll() is None:
            handle.proc.kill()
        bundle = self._recorder.dump(
            "worker_lost", worker=handle.id, cause=reason,
            pid=handle.pid,
            inflight=[r.id for r in inflight],
            # Bundle -> trace navigation: every stranded request's
            # trace id (the reverse link is the requeue span's
            # `bundle` attribute).
            trace_ids=[r.trace.trace_id for r in inflight
                       if r.trace is not None],
            last_heartbeat_age_s=round(
                time.time() - handle.last_heartbeat, 3),
            sched_stats=handle.sched_stats,
            # The dead worker's last known resource history (its
            # heartbeat snapshots — it cannot dump its own ring
            # after a SIGKILL).
            resources=list(handle.resource_ring))
        self._log_event("fleet_worker", worker=handle.id,
                        state="dead", reason=reason,
                        inflight=len(inflight),
                        postmortem_bundle=bundle)
        self._inc_counter("multigrad_fleet_worker_deaths_total",
                          help="workers declared lost")
        # Class-aware recovery order: the survivors' queues may be
        # tight, so the stranded requests most worth saving go first
        # — highest priority class, then nearest deadline, then
        # oldest submit (FIFO among equals; a pre-QoS fleet's
        # untagged requests all tie and keep the old order).
        def _rescue_key(r):
            tag = request_tag(r)
            return (-class_rank(tag.priority_class),
                    r.deadline_t is None,
                    r.deadline_t if r.deadline_t is not None else 0.0,
                    r.submitted_t)
        for req in sorted(inflight, key=_rescue_key):
            self._requeue(req, f"worker {handle.id} lost ({reason})",
                          bundle)
        self._refresh_gauges()

    def _worker_drained(self, handle: WorkerHandle):
        with self._lock:
            if handle.state == "dead":
                return
            handle.state = "dead"
            leftovers = list(handle.inflight.values())
            handle.inflight.clear()
            self._count_locked("drained")
        self._log_event("fleet_worker", worker=handle.id,
                        state="drained", leftovers=len(leftovers))
        # A clean drain answered everything it had; anything left
        # (drain cut short) migrates like a death would.
        for req in leftovers:
            self._requeue(req,
                          f"worker {handle.id} exited mid-drain",
                          None)
        self._refresh_gauges()

    def _requeue(self, req: FleetRequest, reason: str,
                 bundle: Optional[str]):
        """Re-enqueue one request off a lost worker.

        The contract (tests/test_fleet.py pins each clause): the
        requeue history lands on the future; a cancelled future stays
        cancelled; the ORIGINAL wall-clock deadline still applies (a
        requeue never resets it); the consumed poison retry is
        forwarded so it cannot double-fire; and after
        ``max_requeues`` migrations the request resolves with the
        typed :class:`WorkerLostError` instead of bouncing forever.

        Each migration is one explicit ``requeue`` trace span naming
        both worker generations (``from_worker``/``to_worker``) and
        the ``worker_lost`` bundle.  The span STARTS at the lost
        attempt's dispatch time: everything the dead worker did (and
        the heartbeat-timeout window where nothing ran anywhere) is
        accounted to the requeue hop, so a chaos-killed request's
        waterfall still sums to its end-to-end latency.
        """
        fut = req.future
        from_worker = req.worker
        hop_t0 = req.last_dispatch_t or time.time()
        entry = {"t": time.time(), "worker": req.worker,
                 "reason": reason, "bundle": bundle}
        fut.requeues.append(entry)
        self._log_event("fleet_requeue", request=req.id,
                        worker=req.worker, reason=reason,
                        n_requeues=len(fut.requeues), bundle=bundle)
        self._inc_counter("multigrad_fleet_requeues_total",
                          help="requests re-enqueued off lost workers")
        with self._lock:
            self._count_locked("requeued")

        def _requeue_span(to_worker, outcome, t_end=None,
                          count_hop=True):
            if self._tracer is None or req.trace is None:
                return
            t_end = time.time() if t_end is None else t_end
            self._tracer.record(
                req.trace.child(), "requeue", hop_t0, t_end,
                from_worker=from_worker, to_worker=to_worker,
                reason=reason, bundle=bundle, outcome=outcome,
                n_requeues=len(fut.requeues))
            if count_hop:
                req.hops["requeue"] = round(
                    req.hops.get("requeue", 0.0)
                    + max(0.0, t_end - hop_t0), 6)

        fut._requeued()
        if fut.done() or fut.cancelled():
            _requeue_span(None, "already_settled")
            self._forget(req)
            return
        if req.deadline_t is not None and time.time() > req.deadline_t:
            _requeue_span(None, "expired")
            self._trace_root(req, "expired")
            with self._lock:
                self._count_locked("expired")
            self._fits_counter("expired")
            fut._set_exception(FitDeadlineExceeded(
                f"request {req.id} deadline passed before requeue "
                f"(after {len(fut.requeues)} migration(s))"))
            self._forget(req)
            return
        if len(fut.requeues) > self.max_requeues:
            _requeue_span(None, "max_requeues")
            self._settle_lost(
                req, f"request {req.id} requeued "
                     f"{len(fut.requeues)} times (max "
                     f"{self.max_requeues}); giving up")
            return
        # lock-ok: unlocked-shared-write single-owner field: a request is requeued by exactly one thread at a time (it was popped from the dead worker's inflight map under _lock before this path runs)
        req.rejected_by = {req.worker} if req.worker else set()
        # The hop seconds land on req.hops BEFORE the redispatch: a
        # cached fit on the survivor can answer (and _on_result
        # merge the hop vector into FitResult) before this thread
        # resumes.  The span itself is written after, so its
        # to_worker/outcome reflect what _dispatch actually did —
        # the request may have settled as lost or been cancelled in
        # there, and 'redispatched' must not be a lie in the trace.
        hop_end = time.time()
        if self._tracer is not None and req.trace is not None:
            req.hops["requeue"] = round(
                req.hops.get("requeue", 0.0)
                + max(0.0, hop_end - hop_t0), 6)
        self._dispatch(req, exclude=req.rejected_by)
        if fut.done():
            _requeue_span(None, "not_redispatched", t_end=hop_end,
                          count_hop=False)
        else:
            _requeue_span(req.worker, "redispatched",
                          t_end=hop_end, count_hop=False)

    def _settle_lost(self, req: FleetRequest, message: str):
        self._trace_root(req, "lost")
        with self._lock:
            self._count_locked("lost")
        self._fits_counter("lost")
        req.future._set_exception(WorkerLostError(
            message, req.id, req.future.requeues))
        self._forget(req)

    # ------------------------------------------------------------------ #
    # health monitor
    # ------------------------------------------------------------------ #
    def _monitor_loop(self):
        interval = max(0.02, min(self.heartbeat_timeout_s / 4,
                                 0.25))
        while not self._monitor_stop.wait(interval):
            # Per-iteration backstop: the monitor's loss paths
            # (_worker_lost -> _requeue) settle futures, so one bad
            # tick must not kill the thread and leave every later
            # loss undetected — log and keep monitoring.
            try:
                self._monitor_tick()
            except Exception as err:
                self._log_event("fleet_monitor_error",
                                error=repr(err))

    def _monitor_tick(self):
        now = time.time()
        for w in list(self.workers):
            if w.state == "up" and w.chan is not None:
                # RPC RTT probe: the pong echoes t0 back (see
                # _on_pong).  Send failures are the reader/
                # monitor loss paths' problem, not the probe's.
                try:
                    w.send({"op": "ping", "t0": now})
                except OSError:
                    pass
            if w.state == "up":
                if w.proc is not None \
                        and w.proc.poll() is not None:
                    self._worker_lost(
                        w, "process exited "
                           f"rc={w.proc.returncode}")
                elif now - w.last_heartbeat \
                        > self.heartbeat_timeout_s:
                    self._worker_lost(
                        w, "heartbeat lost "
                           f"({now - w.last_heartbeat:.2f}s)")
            elif w.state == "draining" and w.proc is not None \
                    and w.proc.poll() is not None:
                self._worker_drained(w)
        self._refresh_gauges()

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #
    def close(self, drain: bool = True,
              timeout: Optional[float] = 60.0):
        """Shut the fleet down.  ``drain=True`` asks every live
        worker to serve what it holds (the SIGTERM path, over the
        protocol), waits for in-flight requests to settle, then
        reaps the processes; ``drain=False`` reaps immediately.
        Futures still unresolved afterwards get
        :class:`~multigrad_tpu.serve.queue.FitCancelled`."""
        if self._closing:
            return
        self._closing = True
        self._monitor_stop.set()
        if drain:
            for w in self.workers:
                if w.routable():
                    try:
                        w.send({"op": "drain"})
                    except OSError:
                        pass
            deadline = None if timeout is None \
                else time.time() + timeout
            while deadline is None or time.time() < deadline:
                with self._lock:
                    if not any(w.inflight for w in self.workers):
                        break
                time.sleep(0.02)
        for w in self.workers:
            if w.chan is not None:
                w.chan.close()
            if w.proc is not None:
                w.proc.terminate()
        for w in self.workers:
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    w.proc.kill()
        # Same lock as every other .state transition: the monitor
        # and reader threads are still draining their final
        # callbacks at this point, and an unlocked write here raced
        # their _worker_lost / _worker_drained state machine.
        with self._lock:
            for w in self.workers:
                w.state = "dead"
        with self._lock:
            leftovers = [r for r in self._requests.values()
                         if not r.future.done()]
        for req in leftovers:
            self._trace_root(req, "cancelled")
            req.future._set_exception(FitCancelled(
                f"request {req.id} cancelled by fleet shutdown"))
        if self._owns_tracer and self._tracer is not None:
            self._tracer.close()
        self.rollup.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(drain=True)
        return False

    # ------------------------------------------------------------------ #
    # observability
    # ------------------------------------------------------------------ #
    @property
    def trace_paths(self) -> list:
        """Every per-process trace JSONL of this fleet (router +
        workers) — the argument list for ``python -m multigrad_tpu
        .telemetry.trace`` / :func:`~multigrad_tpu.telemetry
        .aggregate.merge_traces`."""
        paths = []
        if self._tracer is not None and self._tracer.path:
            paths.append(self._tracer.path)
        paths += [w.trace_path for w in self.workers
                  if getattr(w, "trace_path", None)]
        return paths

    def _trace_hop(self, req: FleetRequest, name: str,
                   t_start: float, t_end: Optional[float] = None,
                   ok: bool = True, **attrs):
        """Record one router-side hop span under the request's trace
        and accumulate its seconds into the request's hop vector
        (delivered on ``FitResult.hops``)."""
        if self._tracer is None or req.trace is None:
            return
        t_end = time.time() if t_end is None else t_end
        self._tracer.record(req.trace.child(), name, t_start, t_end,
                            ok=ok, **attrs)
        req.hops[name] = round(
            req.hops.get(name, 0.0) + max(0.0, t_end - t_start), 6)

    def _trace_root(self, req: FleetRequest, outcome: str,
                    t_end: Optional[float] = None, **attrs):
        """Close the request's trace with its root span (first
        settle wins — e.g. an error then a shutdown sweep must not
        record two roots)."""
        if self._tracer is None or req.trace is None:
            return
        with self._lock:
            if req.root_recorded:
                return
            req.root_recorded = True
        if req.config.job_id is not None:
            attrs.setdefault("job_id", req.config.job_id)
        if req.config.stage is not None:
            attrs.setdefault("stage", req.config.stage)
        self._tracer.record(req.trace, "request", req.submitted_t,
                            t_end, outcome=outcome, request=req.id,
                            requeues=len(req.future.requeues),
                            **attrs)

    def _observe_latency(self, req: FleetRequest, e2e_s: float,
                         hops: Optional[dict]):
        """Feed the fleet latency histograms (p50/p95/p99 in
        ``/status``) with the trace id as the exemplar; the
        :class:`~multigrad_tpu.telemetry.live.LatencyObserver` keeps
        the slowest-fit gauge pointing at its offending trace
        (thread-safe — one reader thread per worker observes)."""
        self._latency.observe(
            e2e_s, hops,
            req.trace.trace_id if req.trace is not None else None)

    def _count_locked(self, key: str):
        self._stats[key] = self._stats.get(key, 0) + 1

    def _log_event(self, event: str, **fields):
        if self.telemetry is not None:
            try:
                self.telemetry.log(event, **fields)
            except Exception:
                pass

    def _inc_counter(self, name: str, help=None, labels=None):
        if self._metrics is not None:
            self._metrics.inc(name, help=help, labels=labels)

    def _fits_counter(self, outcome: str):
        if self._metrics is not None:
            self._metrics.inc("multigrad_fleet_fits_total",
                              help="fleet fit requests, by outcome",
                              labels={"outcome": outcome})

    def _refresh_gauges(self):
        if self._metrics is None:
            return
        alive = sum(w.state == "up" for w in self.workers)
        self._metrics.set("multigrad_fleet_workers_alive",
                          float(alive),
                          help="fleet workers currently routable")
        self._metrics.set(
            "multigrad_fleet_inflight",
            float(sum(len(w.inflight) for w in self.workers)),
            help="requests dispatched and unresolved, fleet-wide")
        for w in self.workers:
            self._metrics.set(
                "multigrad_fleet_worker_up",
                1.0 if w.state == "up" else 0.0,
                help="per-worker liveness",
                labels={"worker": w.id})
            self._metrics.set(
                "multigrad_fleet_worker_queue_depth",
                float(w.queue_depth),
                help="per-worker scheduler queue depth "
                     "(last heartbeat)",
                labels={"worker": w.id})
        rate = self.fits_per_hour()
        if rate is not None:
            self._metrics.set("multigrad_fleet_fits_per_hour", rate,
                              help="aggregate served-fit rate")

    def _refresh_resource_gauges(self, handle: WorkerHandle,
                                 res: dict):
        """Per-worker resource gauges from a heartbeat snapshot —
        the fleet-wide utilization view in the router's registry
        (one labelled series per worker, refreshed at heartbeat
        cadence)."""
        if self._metrics is None:
            return
        labels = {"worker": handle.id}
        for gauge, key, help_ in (
                ("multigrad_fleet_worker_busy_frac", "busy_frac",
                 "per-worker dispatch duty cycle (last heartbeat)"),
                ("multigrad_fleet_worker_rss_bytes", "rss_bytes",
                 "per-worker host RSS (last heartbeat)"),
                ("multigrad_fleet_worker_device_bytes_in_use",
                 "device_bytes_in_use",
                 "per-worker device memory in use (last heartbeat)"),
                ("multigrad_fleet_worker_device_peak_bytes",
                 "device_peak_bytes",
                 "per-worker device memory high-water "
                 "(last heartbeat)"),
                ("multigrad_fleet_worker_compile_seconds_total",
                 "compile_s_total",
                 "per-worker cumulative program-build seconds "
                 "(last heartbeat)")):
            v = res.get(key)
            if v is not None:
                self._metrics.set(gauge, float(v), help=help_,
                                  labels=labels)

    def fits_per_hour(self) -> Optional[float]:
        """Aggregate fleet throughput: completions per hour from the
        first submission to the latest completion."""
        with self._lock:
            n = self._stats.get("completed", 0)
            if (not n or self._first_submit_t is None
                    or self._last_completed_t is None):
                return None
            span = self._last_completed_t - self._first_submit_t
        if span <= 0:
            return None
        return n / span * 3600.0

    @property
    def stats(self) -> dict:
        """Aggregate counters (submitted / completed / failed /
        requeued / rejected / shed / lost / expired / worker_deaths /
        drained) plus a per-worker health snapshot."""
        now = time.time()
        with self._lock:
            out = dict(self._stats)
            out["workers"] = {
                w.id: {"state": w.state,
                       "inflight": len(w.inflight),
                       "queue_depth": w.queue_depth,
                       "heartbeat_age_s": round(
                           now - w.last_heartbeat, 3),
                       "rpc_rtt_s": (round(w.rpc_rtt_s, 6)
                                     if w.rpc_rtt_s is not None
                                     else None),
                       "live_port": w.live_port,
                       "resources": (dict(w.resources)
                                     if w.resources is not None
                                     else None)}
                for w in self.workers}
        out["workers_alive"] = sum(
            1 for w in self.workers if w.state == "up")
        # Fleet-wide utilization: mean duty cycle over live monitored
        # workers and summed memory — the router-side aggregate the
        # autoscaler reads next to per-worker detail.
        fracs = [w.resources.get("busy_frac") for w in self.workers
                 if w.state == "up" and w.resources is not None
                 and w.resources.get("busy_frac") is not None]
        out["fleet_busy_frac"] = (
            round(sum(fracs) / len(fracs), 4) if fracs else None)
        rss = [w.resources.get("rss_bytes") for w in self.workers
               if w.state == "up" and w.resources is not None
               and w.resources.get("rss_bytes") is not None]
        out["fleet_rss_bytes"] = int(sum(rss)) if rss else None
        out["fits_per_hour"] = self.fits_per_hour()
        if self.qos_enabled or self.slo is not None:
            by_class, by_tenant = self.shed_counts()
            out["qos_shed"] = {"by_class": by_class,
                               "by_tenant": by_tenant}
        out["history"] = self.history()
        return out

    def history(self, window_s: float = 600.0) -> dict:
        """Windowed fleet history from the merged heartbeat rollups:
        trailing fit/shed counts and rate, device-busy seconds, and
        the queue-wait mean/max/trend over ``window_s``.  Values are
        ``None`` until heartbeat deltas have landed — a legacy
        (pre-rollup) fleet reports an empty history, never zeros."""
        from ..telemetry.rollup import (DEVICE_BUSY_S, FITS,
                                        QUEUE_WAIT_S, SHEDS)
        r = self.rollup

        def rnd(v, k=6):
            return None if v is None else round(v, k)

        return {
            "window_s": float(window_s),
            "fits": (int(v) if (v := r.delta(
                "fleet." + FITS, window_s)) is not None else None),
            "fits_per_s": rnd(r.rate("fleet." + FITS, window_s)),
            "sheds": (int(v) if (v := r.delta(
                "fleet." + SHEDS, window_s)) is not None else None),
            "device_busy_s": rnd(
                r.delta("fleet." + DEVICE_BUSY_S, window_s), 3),
            "queue_wait_mean_s": rnd(
                r.mean_over("fleet." + QUEUE_WAIT_S, window_s)),
            "queue_wait_max_s": rnd(
                r.max_over("fleet." + QUEUE_WAIT_S, window_s)),
            "queue_wait_trend": rnd(
                r.trend("fleet." + QUEUE_WAIT_S, window_s), 8),
        }
