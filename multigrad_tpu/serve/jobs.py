"""Job-DAG pipeline subsystem: joint posteriors as a service.

One :class:`Job` — a typed DAG of stages (:mod:`multigrad_tpu.serve
.stages`) — submitted to a :class:`JobRunner` runs a whole posterior
pipeline (Latin-hypercube scan → multi-start ensemble → Laplace →
HMC → predictive checks) through the existing serving planes instead
of caller-side orchestration around one-shot ``submit`` calls:

* **Dependency resolution** — stages whose dependencies have settled
  run concurrently (one ``mgt-job-*`` thread per ready stage);
  artifacts flow between stages as small JSON-able host-side dicts
  (the stage contract — never catalogs).
* **Fit fan-out** — fit-type stages push bursts through the runner's
  backend (:class:`~multigrad_tpu.serve.scheduler.FitScheduler` or
  :class:`~multigrad_tpu.serve.fleet.FleetRouter`); each stage's
  shared :class:`~multigrad_tpu.serve.queue.FitConfig` is stamped
  with ``job_id``/``stage``, so the burst coalesces into its own
  bucket family and keys its own fleet affinity.  Host-side stages
  (Laplace/HMC/predictive checks) run on the runner's local model;
  HMC rides the sharded-K program family when the mesh has a free
  replica axis.
* **Tracing** — the runner mints ONE trace per job; every stage
  attempt is a ``stage`` span under the job root, every fit's
  ``request`` span (and the scheduler/router hops under it) parents
  into its stage span, so ``python -m multigrad_tpu.telemetry.trace``
  renders the complete multi-stage DAG as a single waterfall.
* **Checkpoints** — with ``checkpoint_dir`` set, job state is written
  at every stage boundary (artifacts are JSON by contract, so the
  checkpoint is a plain file).  A crashed/killed runner re-submitted
  with the same ``job_id`` restores every completed stage — and keeps
  the same trace — so a lost worker costs a *stage*, not the job.
  (Within a stage, a fleet backend already migrates in-flight fits
  off a dead worker via its requeue machinery; the runner's
  ``max_stage_attempts`` re-runs the stage only when the backend
  gives up.)
* **Observability** — ``multigrad_job_*`` gauges feed ``/status``;
  one ``job_summary`` telemetry record per job (per-stage outcomes,
  latencies, fit counts) feeds the report CLI's ``job:`` section;
  predictive-check verdicts are their own ``predictive_check``
  records.

::

    job = Job(stages=(
        SweepStage("scan", n_points=32, param_bounds=BOUNDS),
        EnsembleStage("ensemble", deps=("scan",), n_starts=8,
                      param_bounds=BOUNDS),
        LaplaceStage("laplace", deps=("ensemble",)),
        HmcStage("hmc", deps=("ensemble", "laplace")),
        PredictiveCheckStage("check", deps=("hmc",)),
    ))
    future = JobRunner(router, model=joint_model,
                       checkpoint_dir=ckpt).submit(job)
    result = future.result()          # JobResult: per-stage outcomes
"""
from __future__ import annotations

import json
import os
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple, Union

from .._lockdep import make_condition, make_lock
from .stages import Stage, StageRuntime

__all__ = ["Job", "JobResult", "JobRunner", "JobFuture",
           "StageResult", "JobFailed"]


class JobFailed(RuntimeError):
    """The job runner itself died before settling the job (stage
    *failures* do not raise — they settle the
    :class:`JobFuture` with a :class:`JobResult` whose ``ok`` is
    False and per-stage outcomes tell the story)."""


@dataclass
class Job:
    """A typed DAG of stages, submitted as one unit.

    ``job_id`` names the job everywhere — config stamps, trace
    attributes, gauges, the checkpoint file — and is minted
    (``job-<hex>``) when not given.  Re-submitting a job with the
    same ``job_id`` to a runner with a ``checkpoint_dir`` resumes it:
    completed stages restore from the checkpoint.  Validation
    (unique names, known dependencies, acyclicity) happens here, at
    construction, so a malformed DAG fails its caller instead of a
    runner thread.
    """

    stages: Union[Tuple[Stage, ...], Stage]
    job_id: Optional[str] = None
    # Multi-tenant QoS: the whole job's fit traffic is tagged with
    # this tenant/class (see multigrad_tpu.serve.qos) — stages
    # propagate the tag on every backend.submit, so a QoS-enabled
    # fleet schedules the job's bursts under its tenant's fair share.
    tenant: Optional[str] = None
    priority_class: Optional[str] = None

    def __post_init__(self):
        if isinstance(self.stages, Stage):
            self.stages = (self.stages,)
        self.stages = tuple(self.stages)
        if not self.stages:
            raise ValueError("Job needs at least one stage")
        names = [s.name for s in self.stages]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate stage names: {names}")
        known = set(names)
        for s in self.stages:
            missing = [d for d in s.deps if d not in known]
            if missing:
                raise ValueError(
                    f"stage {s.name!r} depends on unknown stage(s) "
                    f"{missing}")
        self._toposort()            # raises on cycles
        if self.job_id is None:
            self.job_id = f"job-{secrets.token_hex(4)}"

    def _toposort(self) -> Tuple[Stage, ...]:
        by_name = {s.name: s for s in self.stages}
        done, order, visiting = set(), [], set()

        def visit(s):
            if s.name in done:
                return
            if s.name in visiting:
                raise ValueError(
                    f"stage dependency cycle through {s.name!r}")
            visiting.add(s.name)
            for d in s.deps:
                visit(by_name[d])
            visiting.discard(s.name)
            done.add(s.name)
            order.append(s)

        for s in self.stages:
            visit(s)
        return tuple(order)


@dataclass(frozen=True)
class StageResult:
    """One stage's outcome within a settled job."""

    name: str
    #: "ok" | "failed" | "skipped" (an upstream dependency failed) |
    #: "restored" (completed in a previous run, replayed from the
    #: job checkpoint).
    outcome: str
    artifact: Optional[dict] = None
    elapsed_s: float = 0.0
    attempts: int = 0
    error: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.outcome in ("ok", "restored")


@dataclass(frozen=True)
class JobResult:
    """A settled job: per-stage results plus the roll-up."""

    job_id: str
    ok: bool
    stages: Dict[str, StageResult]
    elapsed_s: float
    trace_id: Optional[str] = None

    def artifact(self, stage: str) -> Optional[dict]:
        result = self.stages.get(stage)
        return result.artifact if result is not None else None

    def outcomes(self) -> Dict[str, str]:
        return {name: r.outcome for name, r in self.stages.items()}


class JobFuture:
    """Await/poll handle for one submitted job.

    :meth:`result` blocks for the :class:`JobResult` (stage failures
    settle the future normally — check ``result.ok``); runner-level
    crashes surface as a raised :class:`JobFailed`.
    ``stage_results`` is live: stages appear as they settle, so a
    dashboard can render pipeline progress without waiting for the
    job.
    """

    def __init__(self, job_id: str):
        self.job_id = job_id
        #: The job's trace id (None with tracing off): the handle
        #: into `python -m multigrad_tpu.telemetry.trace`.
        self.trace_id: Optional[str] = None
        self._lock = make_lock("serve.jobs.JobFuture._lock")
        self._cond = make_condition("serve.jobs.JobFuture._cond",
                                    self._lock)
        self._stage_results: Dict[str, StageResult] = {}
        self._result: Optional[JobResult] = None
        self._exception: Optional[BaseException] = None

    @property
    def stage_results(self) -> Dict[str, StageResult]:
        with self._lock:
            return dict(self._stage_results)

    def done(self) -> bool:
        with self._lock:
            return self._result is not None \
                or self._exception is not None

    def result(self, timeout: Optional[float] = None) -> JobResult:
        with self._cond:
            if not self._cond.wait_for(
                    lambda: self._result is not None
                    or self._exception is not None,
                    timeout=timeout):
                raise TimeoutError(
                    f"job {self.job_id} not settled within "
                    f"{timeout}s")
            if self._exception is not None:
                raise self._exception
            return self._result

    def exception(self, timeout: Optional[float] = None
                  ) -> Optional[BaseException]:
        try:
            self.result(timeout=timeout)
        except TimeoutError:
            raise
        except BaseException as err:
            return err
        return None

    # -- runner side --------------------------------------------------------
    def _stage_settled(self, result: StageResult):
        with self._cond:
            self._stage_results[result.name] = result
            self._cond.notify_all()

    def _set_result(self, result: JobResult):
        with self._cond:
            # First-wins: a late duplicate settle (the runner's
            # crash backstop racing the normal completion path) must
            # not clobber the outcome a caller may already hold.
            if self._result is not None \
                    or self._exception is not None:
                return
            self._result = result
            self._cond.notify_all()

    def _set_exception(self, err: BaseException):
        with self._cond:
            if self._result is not None \
                    or self._exception is not None:
                return
            self._exception = err
            self._cond.notify_all()


class JobRunner:
    """Runs job DAGs over a fit backend.

    Parameters
    ----------
    backend :
        A :class:`~multigrad_tpu.serve.scheduler.FitScheduler` or
        :class:`~multigrad_tpu.serve.fleet.FleetRouter`; fit-type
        stages fan their bursts out through it.
    model : optional
        Local model (or fused :class:`~multigrad_tpu.core.group
        .OnePointGroup`) for the host-side stages (Laplace, HMC,
        predictive checks).  Defaults to the backend's own model
        when it holds one (a scheduler does; a fleet router only
        knows its workers' model *spec*, so pass the model
        explicitly to run host-side stages next to a fleet).
    telemetry, live, tracer : optional
        Default to the backend's planes, so job records, gauges and
        spans land in the same streams as the fits they wrap.
    checkpoint_dir : str, optional
        Directory for per-job stage-boundary checkpoints
        (``<job_id>.json``).  Unset disables checkpointing.
    max_stage_attempts : int
        In-run retries per stage (failure after the last attempt
        fails the stage; downstream stages are skipped).
    fit_timeout_s : float, optional
        Per-fit result timeout inside fan-out stages.
    """

    def __init__(self, backend, model=None, telemetry=None,
                 live=None, tracer=None,
                 checkpoint_dir: Optional[str] = None,
                 max_stage_attempts: int = 2,
                 fit_timeout_s: Optional[float] = None):
        self.backend = backend
        backend_model = getattr(backend, "model", None)
        if model is None and hasattr(backend_model,
                                     "batched_loss_and_grad_fn"):
            model = backend_model
        self.model = model
        self.telemetry = telemetry if telemetry is not None \
            else getattr(backend, "telemetry", None)
        self.tracer = tracer if tracer is not None else (
            getattr(backend, "tracer", None)
            or getattr(backend, "_tracer", None))
        metrics = getattr(live, "metrics", live)
        if metrics is None:
            metrics = getattr(backend, "_metrics", None)
        self._metrics = metrics
        self.checkpoint_dir = checkpoint_dir
        self.max_stage_attempts = max(1, int(max_stage_attempts))
        self.fit_timeout_s = fit_timeout_s
        # The fleet router closes every request span itself (its
        # root bookkeeping is first-settle-wins on the caller's
        # context); a scheduler given an upstream context records
        # hops only, so fan-out stages add the request span.
        from .fleet import FleetRouter
        self._backend_records_request_span = isinstance(
            backend, FleetRouter)
        self._lock = make_lock("serve.jobs.JobRunner._lock")
        # Guards every concurrent touch of a job's shared `results`
        # dict (fan-out waves run one thread per ready stage) and
        # serializes checkpoint publication, so each published
        # checkpoint reflects all stages settled before it.
        self._results_lock = make_lock(
            "serve.jobs.JobRunner._results_lock")
        self._active: Dict[str, JobFuture] = {}
        self._threads: Dict[str, threading.Thread] = {}

    # ------------------------------------------------------------------ #
    # submission
    # ------------------------------------------------------------------ #
    def submit(self, job: Job) -> JobFuture:
        """Launch `job` on its own runner thread; returns the
        :class:`JobFuture` immediately."""
        future = JobFuture(job.job_id)
        thread = threading.Thread(
            target=self._run_job, args=(job, future), daemon=True,
            name=f"mgt-job-{job.job_id}")
        with self._lock:
            if job.job_id in self._active:
                raise ValueError(
                    f"job {job.job_id!r} is already running")
            self._active[job.job_id] = future
            self._threads[job.job_id] = thread
            n_active = len(self._active)
        self._gauge("multigrad_job_active", n_active,
                    help="job DAGs currently executing")
        thread.start()
        return future

    def run(self, job: Job,
            timeout: Optional[float] = None) -> JobResult:
        """Submit and block: ``submit(job).result(timeout)``."""
        return self.submit(job).result(timeout=timeout)

    # ------------------------------------------------------------------ #
    # execution
    # ------------------------------------------------------------------ #
    def _run_job(self, job: Job, future: JobFuture):
        t0 = time.time()
        try:
            restored = self._restore_checkpoint(job)
            job_ctx = self._job_context(job, restored)
            if job_ctx is not None:
                future.trace_id = job_ctx.trace_id
            results: Dict[str, StageResult] = {}
            for name, entry in restored.get("stages", {}).items():
                if entry.get("outcome") in ("ok", "restored") \
                        and any(s.name == name for s in job.stages):
                    results[name] = StageResult(
                        name=name, outcome="restored",
                        artifact=entry.get("artifact"),
                        elapsed_s=float(entry.get("elapsed_s", 0.0)),
                        attempts=int(entry.get("attempts", 0)))
                    future._stage_settled(results[name])
            self._execute_dag(job, future, job_ctx, results)
            elapsed = time.time() - t0
            ok = all(r.ok for r in results.values())
            result = JobResult(
                job_id=job.job_id, ok=ok, stages=dict(results),
                elapsed_s=round(elapsed, 6),
                trace_id=(job_ctx.trace_id if job_ctx is not None
                          else None))
            # Root span and telemetry land BEFORE the future
            # resolves: a caller waking on result() must find a
            # complete trace and an accounted job.
            if self.tracer is not None and job_ctx is not None:
                self.tracer.record(
                    job_ctx, "job", t0, time.time(),
                    ok=ok, outcome="ok" if ok else "failed",
                    job_id=job.job_id, n_stages=len(job.stages))
            self._log_job_summary(job, result)
            self._count_job("ok" if ok else "failed")
            future._set_result(result)
        except BaseException as err:  # noqa: BLE001 — runner backstop
            self._count_job("crashed")
            future._set_exception(JobFailed(
                f"job {job.job_id} runner died: {err!r}"))
        finally:
            with self._lock:
                self._active.pop(job.job_id, None)
                self._threads.pop(job.job_id, None)
                n_active = len(self._active)
            self._gauge("multigrad_job_active", n_active,
                        help="job DAGs currently executing")

    def _execute_dag(self, job: Job, future: JobFuture, job_ctx,
                     results: Dict[str, StageResult]):
        pending = [s for s in job.stages if s.name not in results]
        while pending:
            ready, blocked = [], []
            for s in pending:
                if any(d in results and not results[d].ok
                       for d in s.deps):
                    results[s.name] = StageResult(
                        name=s.name, outcome="skipped",
                        error="upstream stage failed")
                    # Count-before-settle, as in _run_stage_guarded:
                    # a dashboard woken by the stage must see it
                    # already accounted.
                    self._count_stage(job, "skipped")
                    future._stage_settled(results[s.name])
                elif all(d in results for d in s.deps):
                    ready.append(s)
                else:
                    blocked.append(s)
            pending = blocked
            if not ready:
                if pending:
                    # Unreachable for a validated DAG (Job() proved
                    # acyclicity, and _run_stage_guarded guarantees
                    # every executed stage lands in `results`): fail
                    # loudly instead of spinning on `while pending`.
                    raise RuntimeError(
                        f"job {job.job_id}: no runnable stage among "
                        f"pending "
                        f"{[s.name for s in pending]} — DAG "
                        "invariant broken")
                continue
            if len(ready) == 1:
                self._run_stage_guarded(job, ready[0], job_ctx,
                                        results, future)
            else:
                # Independent ready stages genuinely overlap — each
                # on its own thread, writing a distinct results key
                # (inserts are serialized by _results_lock).
                threads = []
                for stage in ready:
                    t = threading.Thread(
                        target=self._run_stage_guarded,
                        args=(job, stage, job_ctx, results, future),
                        daemon=True,
                        name=f"mgt-job-{job.job_id}-{stage.name}")
                    threads.append(t)
                    t.start()
                for t in threads:
                    t.join()

    def _run_stage_guarded(self, job: Job, stage: Stage, job_ctx,
                           results: Dict[str, StageResult],
                           future: JobFuture) -> StageResult:
        """One stage, exception-proof end to end.

        Whatever escapes the stage machinery (a tracer sink, a
        metrics backend, an unwritable ``checkpoint_dir``) must still
        record a :class:`StageResult`: a worker thread dying without
        one would either spin the DAG loop forever (dependents never
        become ready) or let the job settle ``ok`` with the stage
        silently absent from its results.
        """
        try:
            result = self._run_stage(job, stage, job_ctx, results)
        except BaseException as err:  # noqa: BLE001 — thread backstop
            result = StageResult(
                name=stage.name, outcome="failed",
                attempts=self.max_stage_attempts, error=repr(err))
        with self._results_lock:
            results[stage.name] = result
        try:
            self._count_stage(job, result.outcome)
            future._stage_settled(result)
            if result.ok:
                self._write_checkpoint(job, job_ctx, results)
        except Exception as err:
            # Bookkeeping is best-effort: the stage outcome is
            # already recorded, so a checkpoint/metrics failure must
            # not kill the worker thread (it only means a resume
            # re-runs this stage).
            self._note_bookkeeping_error(job, stage, err)
        return result

    def _run_stage(self, job: Job, stage: Stage, job_ctx,
                   results: Dict[str, StageResult]) -> StageResult:
        with self._results_lock:
            # Sibling fan-out stages insert keys concurrently; an
            # unguarded comprehension can raise "dictionary changed
            # size during iteration".
            artifacts = {name: r.artifact
                         for name, r in results.items()
                         if r.ok and r.artifact is not None}
        last_error: Optional[BaseException] = None
        for attempt in range(1, self.max_stage_attempts + 1):
            stage_ctx = job_ctx.child() if job_ctx is not None \
                else None
            rt = StageRuntime(
                job_id=job.job_id, stage=stage.name,
                backend=self.backend, model=self.model,
                artifacts=artifacts, stage_ctx=stage_ctx,
                tracer=self.tracer, telemetry=self.telemetry,
                backend_records_request_span=(
                    self._backend_records_request_span),
                fit_timeout_s=self.fit_timeout_s,
                tenant=job.tenant,
                priority_class=job.priority_class)
            t0 = time.time()
            try:
                artifact = stage.run(rt)
            except BaseException as err:  # noqa: BLE001 — retried
                last_error = err
                if self.tracer is not None and stage_ctx is not None:
                    self.tracer.record(
                        stage_ctx, "stage", t0, time.time(),
                        ok=False, stage=stage.name,
                        job_id=job.job_id, attempt=attempt,
                        error=repr(err))
                continue
            elapsed = time.time() - t0
            if self.tracer is not None and stage_ctx is not None:
                self.tracer.record(
                    stage_ctx, "stage", t0, time.time(),
                    stage=stage.name, job_id=job.job_id,
                    attempt=attempt)
            return StageResult(
                name=stage.name, outcome="ok", artifact=artifact,
                elapsed_s=round(elapsed, 6), attempts=attempt)
        return StageResult(
            name=stage.name, outcome="failed",
            elapsed_s=0.0, attempts=self.max_stage_attempts,
            error=repr(last_error))

    # ------------------------------------------------------------------ #
    # tracing / checkpoints / observability
    # ------------------------------------------------------------------ #
    def _job_context(self, job: Job, restored: dict):
        if self.tracer is None:
            return None
        trace = restored.get("trace") or {}
        trace_id, span_id = trace.get("trace_id"), trace.get("span_id")
        if trace_id and span_id:
            # A resumed job continues its ORIGINAL trace: the root
            # span is only recorded at settle, so the final waterfall
            # is one complete tree across runner generations.
            from ..telemetry.tracing import TraceContext
            return TraceContext(trace_id, span_id, None)
        return self.tracer.new_trace()

    def _checkpoint_path(self, job: Job) -> Optional[str]:
        if self.checkpoint_dir is None:
            return None
        return os.path.join(self.checkpoint_dir,
                            f"{job.job_id}.json")

    def _restore_checkpoint(self, job: Job) -> dict:
        path = self._checkpoint_path(job)
        if path is None or not os.path.exists(path):
            return {}
        try:
            with open(path) as f:
                state = json.load(f)
        except (OSError, ValueError):
            # A torn checkpoint restores nothing — the job simply
            # re-runs from the top (atomic-rename writes make this
            # unreachable short of filesystem corruption).
            return {}
        if state.get("job_id") != job.job_id:
            return {}
        return state

    def _write_checkpoint(self, job: Job, job_ctx,
                          results: Dict[str, StageResult]):
        path = self._checkpoint_path(job)
        if path is None:
            return
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        # Snapshot AND publish under the results lock: concurrent
        # fan-out writers are serialized, so the tmp file is never
        # co-written and the LAST published checkpoint always
        # reflects every stage settled before it (each writer
        # inserts its result before writing, under the same lock).
        with self._results_lock:
            stages = {}
            for r in results.values():
                if r.ok:
                    stages[r.name] = {
                        "outcome": "ok", "artifact": r.artifact,
                        "elapsed_s": r.elapsed_s,
                        "attempts": r.attempts,
                    }
            state = {
                "job_id": job.job_id,
                "t": time.time(),
                "trace": ({"trace_id": job_ctx.trace_id,
                           "span_id": job_ctx.span_id}
                          if job_ctx is not None else None),
                "stages": stages,
            }
            tmp = (f"{path}.tmp-{os.getpid()}"
                   f"-{threading.get_ident()}")
            with open(tmp, "w") as f:
                json.dump(state, f)
            os.replace(tmp, path)  # atomic: a reader sees old or new

    def _note_bookkeeping_error(self, job: Job, stage: Stage, err):
        """Best-effort telemetry for non-fatal stage bookkeeping
        failures (checkpoint IO, metrics sinks)."""
        if self.telemetry is None:
            return
        try:
            self.telemetry.log(
                "job_bookkeeping_error", job_id=job.job_id,
                stage=stage.name, error=repr(err))
        except Exception:
            pass

    def _log_job_summary(self, job: Job, result: JobResult):
        if self.telemetry is None:
            return
        stages = []
        for s in job.stages:
            r = result.stages.get(s.name)
            if r is None:
                continue
            entry = {"stage": s.name, "outcome": r.outcome,
                     "elapsed_s": r.elapsed_s,
                     "attempts": r.attempts}
            if r.artifact and "n_fits" in r.artifact:
                entry["n_fits"] = r.artifact["n_fits"]
            if r.artifact and "verdicts" in r.artifact:
                entry["verdicts"] = r.artifact["verdicts"]
            if r.error:
                entry["error"] = r.error
            stages.append(entry)
        self.telemetry.log(
            "job_summary", job_id=result.job_id, ok=result.ok,
            elapsed_s=result.elapsed_s, trace_id=result.trace_id,
            n_stages=len(job.stages), stages=stages)

    def _gauge(self, name, value, help=None, labels=None):
        if self._metrics is not None:
            self._metrics.set(name, float(value), help=help,
                              labels=labels)

    def _count_job(self, outcome: str):
        if self._metrics is not None:
            self._metrics.inc("multigrad_jobs_total",
                              help="settled job DAGs, by outcome",
                              labels={"outcome": outcome})

    def _count_stage(self, job: Job, outcome: str):
        if self._metrics is not None:
            self._metrics.inc("multigrad_job_stages_total",
                              help="settled job stages, by outcome",
                              labels={"outcome": outcome})
