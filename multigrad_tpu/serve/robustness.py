"""Per-request fault isolation for bucketed fit dispatches.

A bucket dispatch runs K tenants' fits as one batched Adam scan.
Adam's update is elementwise along the batch axis, so a NaN/Inf in
one tenant's fit is *structurally contained* to its own row — the
batch-mates' trajectories are bitwise identical to what they would
have been in a clean batch (``tests/test_serve.py`` asserts exactly
that).  What remains for the serving layer is the per-request
bookkeeping this module provides:

* :func:`nonfinite_rows` — classify the finished batch: which rows
  came back poisoned (non-finite final parameters or loss)?
* :func:`request_postmortem` — dump a flight-recorder bundle for the
  failing request alone (the recorder's ring carries the serve
  telemetry records around the dispatch, the bundle detail carries
  the tenant's request id, guess, bucket and row), without tripping
  the recorder's fatal latch — batch-mates and later dispatches must
  keep flowing.
* :func:`split_expired` — deadline enforcement at dispatch time: a
  request whose deadline passed while it sat in the queue is resolved
  with :class:`~multigrad_tpu.serve.queue.FitDeadlineExceeded`
  instead of wasting a bucket row.

The retry policy (a poisoned request is re-enqueued ONCE at the head
of the queue, so its second attempt runs in a fresh bucket) and the
graceful drain live in :class:`~multigrad_tpu.serve.scheduler
.FitScheduler`, which composes these helpers.
"""
from __future__ import annotations

import time
from typing import Optional, Tuple

import numpy as np

from .queue import FitDeadlineExceeded, FitRequest

__all__ = ["nonfinite_rows", "request_postmortem", "split_expired"]


def nonfinite_rows(finals, losses) -> np.ndarray:
    """Boolean mask over batch rows: True = poisoned.

    A row is poisoned when its final parameters or its final loss are
    non-finite.  (An *infinite* loss with finite parameters is
    poisoned too: the tenant's objective is broken at the returned
    point, and handing it back as a "result" would just defer the
    failure to the caller.)
    """
    finals = np.asarray(finals)
    losses = np.asarray(losses)
    bad_params = ~np.all(np.isfinite(finals), axis=-1)
    return bad_params | ~np.isfinite(losses)


def request_postmortem(recorder, request: FitRequest, row: int,
                       bucket: int, final_params, final_loss,
                       resources=None) -> Optional[str]:
    """Dump a per-request postmortem bundle; returns its path.

    Uses :meth:`~multigrad_tpu.telemetry.flight.FlightRecorder.dump`
    directly — NOT :meth:`trip` — because a poisoned tenant must not
    latch the shared recorder into a fatal state that would poison
    every later dispatch.  ``None`` when the recorder is absent or
    the dump itself failed (the recorder swallows its own errors by
    contract: a postmortem must never add a second failure).
    """
    if recorder is None:
        return None
    params = np.asarray(final_params, dtype=float)
    trace = getattr(request, "trace", None)
    return recorder.dump(
        "non_finite_request",
        request_id=request.id,
        # Postmortems are navigable from either end: the bundle
        # names the trace, the trace's root span names the bundle.
        trace_id=(trace.trace_id if trace is not None else None),
        row=int(row),
        bucket=int(bucket),
        retried=bool(request.retried),
        guess=[float(g) for g in np.asarray(request.guess).ravel()],
        final_params=[float(p) for p in params.ravel()],
        final_loss=float(final_loss),
        nsteps=request.config.nsteps,
        learning_rate=request.config.learning_rate,
        # The consumed-resources context (the monitor's sample ring)
        # — was the device near its memory limit, was the process
        # busy-saturated — rides along when the caller monitors.
        resources=resources,
    )


def split_expired(requests, now: Optional[float] = None
                  ) -> Tuple[list, list]:
    """Partition a dispatch group into (live, expired) by deadline."""
    now = time.time() if now is None else now
    live, expired = [], []
    for r in requests:
        (expired if r.expired(now) else live).append(r)
    for r in expired:
        r.future._set_exception(FitDeadlineExceeded(
            f"request {r.id} deadline passed "
            f"{now - r.deadline:.3f} s before dispatch"))
    return live, expired
