"""Fit-request queue: the submit/await surface of the serving layer.

The multi-tenant front door of :mod:`multigrad_tpu.serve`: callers
build a :class:`FitConfig` (fit schedule + bounds — everything about a
fit *except* its initial guess), submit ``(guess, config)`` pairs, and
get back a :class:`FitFuture` to await, poll or cancel.  The queue
itself is a bounded thread-safe FIFO with admission control — a
structurally invalid request (wrong guess shape, guess outside its
bounds box) is rejected at ``submit`` time, and a full queue pushes
back instead of growing without bound (``block=False`` raises
:class:`QueueFullError` immediately; ``block=True`` waits up to
``timeout`` for the dispatcher to drain headroom).

Requests sharing a config — the same ``(nsteps, learning_rate,
bounds, randkey)`` — are *batchable*: the scheduler
(:mod:`.scheduler`) pops same-config groups off this queue and packs
them into one ``(K, ndim)`` bucket dispatch.  :meth:`FitQueue
.take_group` implements exactly that pop: the oldest pending request
plus every compatible request behind it, up to the bucket cap,
waiting a short batch window for a burst to coalesce.
"""
from __future__ import annotations

import collections
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Optional, Tuple

import numpy as np

from .._lockdep import make_condition, make_lock

__all__ = ["FitConfig", "FitRequest", "FitFuture", "FitResult",
           "FitQueue", "QueueFullError", "FitCancelled",
           "FitDeadlineExceeded", "FitFailed", "FitOOMError"]


class QueueFullError(RuntimeError):
    """Admission control pushed back: the queue is at ``max_pending``
    (and stayed there for the whole ``timeout``, when blocking)."""


class FitCancelled(RuntimeError):
    """The future was cancelled before its fit was dispatched."""


class FitDeadlineExceeded(TimeoutError):
    """The request's deadline passed before a bucket could serve it."""


class FitFailed(RuntimeError):
    """The fit produced a non-finite result (NaN/Inf parameters or
    loss).  ``bundle_path`` points at the per-request flight-recorder
    postmortem bundle; ``request_id`` names the tenant's request."""

    def __init__(self, message: str, request_id: int,
                 bundle_path: Optional[str] = None):
        self.request_id = request_id
        self.bundle_path = bundle_path
        at = f"; postmortem bundle: {bundle_path}" if bundle_path \
            else ""
        super().__init__(f"{message} (request {request_id}){at}")


class FitOOMError(FitFailed):
    """A bucket dispatch ran out of device memory.

    The typed, actionable form of the failure that used to land as a
    generic :class:`FitFailed`: the scheduler classifies a
    RESOURCE_EXHAUSTED / out-of-memory dispatch error, attaches the
    sharded-K memory-model estimate (``estimated_bytes``, from
    :func:`~multigrad_tpu.inference.ensemble_memory_model` —
    per-device optimizer + trajectory state for this bucket), and the
    message spells out the remedy: shard the K axis (build the model
    on :func:`~multigrad_tpu.parallel.ensemble_comm` and pass
    ``FitScheduler(k_sharded=True)``), or cap the ladder with
    ``k_budget_bytes``.  The same estimate rides in the postmortem
    bundle.
    """

    def __init__(self, message: str, request_id: int,
                 bundle_path: Optional[str] = None,
                 estimated_bytes: Optional[int] = None,
                 bucket: Optional[int] = None):
        self.estimated_bytes = estimated_bytes
        self.bucket = bucket
        super().__init__(message, request_id,
                         bundle_path=bundle_path)


def _normalize_bounds(param_bounds) -> Optional[tuple]:
    """Bounds as a hashable tuple of ``None | (low, high)`` floats —
    the form that can live inside a frozen, dict-keyable config."""
    if param_bounds is None:
        return None
    out = []
    for entry in param_bounds:
        if entry is None:
            out.append(None)
            continue
        low, high = entry
        out.append((float(low), float(high)))
    return tuple(out)


@dataclass(frozen=True)
class FitConfig:
    """Everything about a fit except its initial guess.

    Two requests are *batchable* iff their configs are equal: the
    scheduler packs them into one ``(K, ndim)`` parameter matrix
    driven by a single batched Adam scan, so every field here is part
    of the compiled program's identity (``nsteps`` and
    ``learning_rate`` join the segment-program cache key;
    ``param_bounds`` selects the bounded bijection; ``randkey``
    selects the keyed kernel and the per-step key chain, shared by
    all rows of a batch).

    ``param_bounds`` follows the ``run_adam`` convention — a sequence
    of ``None | (low, high)`` per parameter — normalized to a
    hashable tuple so configs can key dispatch groups.

    ``job_id``/``stage`` are optional pipeline metadata stamped by
    the job-DAG runner (:mod:`multigrad_tpu.serve.jobs`): free-form
    strings naming the owning job and stage.  Being config fields
    they join dispatch-group equality and the fleet's affinity key
    automatically — a stage's burst coalesces into its own bucket
    family and lands on one worker's compile cache — and they ride
    the wire protocol as ordinary known keys (older peers simply
    drop them; see :mod:`multigrad_tpu.serve.wire`).
    """

    nsteps: int = 100
    learning_rate: float = 0.01
    param_bounds: Optional[tuple] = None
    randkey: Optional[int] = None
    const_randkey: bool = False
    job_id: Optional[str] = None
    stage: Optional[str] = None

    def __post_init__(self):
        for field_name in ("job_id", "stage"):
            value = getattr(self, field_name)
            if value is not None and not isinstance(value, str):
                raise TypeError(
                    f"FitConfig.{field_name} must be a str or None, "
                    f"got {type(value).__name__}")
        object.__setattr__(self, "nsteps", int(self.nsteps))
        object.__setattr__(self, "learning_rate",
                           float(self.learning_rate))
        object.__setattr__(self, "param_bounds",
                           _normalize_bounds(self.param_bounds))
        if self.nsteps <= 0:
            raise ValueError(f"nsteps must be positive, got "
                             f"{self.nsteps}")
        if self.randkey is not None:
            # Configs key dispatch groups (hashed, compared with ==),
            # so the randkey must be a plain int seed — a PRNG key
            # ARRAY would make config equality raise inside the
            # dispatcher thread.  run_adam_scan builds the typed key
            # from the seed at dispatch.
            if not isinstance(self.randkey, (int, np.integer)) \
                    or isinstance(self.randkey, bool):
                raise TypeError(
                    "FitConfig.randkey must be an int seed (or "
                    f"None), got {type(self.randkey).__name__}")
            object.__setattr__(self, "randkey", int(self.randkey))
        if self.const_randkey and self.randkey is None:
            raise ValueError("Must pass randkey if const_randkey")

    @property
    def with_key(self) -> bool:
        return self.randkey is not None

    @property
    def bounded(self) -> bool:
        return self.param_bounds is not None

    def bounds_list(self) -> Optional[list]:
        """Bounds in the list form the optimizer entry points take."""
        return None if self.param_bounds is None \
            else list(self.param_bounds)


@dataclass(frozen=True)
class FitResult:
    """A served fit, as delivered by :meth:`FitFuture.result`.

    ``traj`` is this request's own ``(nsteps + 1, ndim)`` trajectory
    slice of the batched scan — bitwise identical to what a solo
    :func:`~multigrad_tpu.optim.adam.run_adam_scan` of the same guess
    would return (Adam's update is elementwise, so batch rows advance
    as independent fits).  ``worker`` names the fleet worker that
    served the fit when the request traveled through a
    :class:`~multigrad_tpu.serve.fleet.FleetRouter` (``None`` for
    in-process scheduling).
    """

    request_id: int
    params: np.ndarray
    loss: float
    traj: np.ndarray
    steps: int
    bucket: int
    wait_s: float
    fit_s: float
    retried: bool = False
    worker: Optional[str] = None
    # Distributed-tracing surface: the request's trace id (mint
    # point: FleetRouter.submit / FitScheduler.submit) and the
    # per-hop latency breakdown in seconds — scheduler hops
    # (queue_wait / bucket_coalesce / dispatch / adam_segments /
    # finalize) plus, for fleet-served fits, the router's hops
    # (route / rpc_send / result_return, and requeue time when the
    # request migrated off a lost worker).  ``wait_s``/``fit_s``
    # above are the coarse pre-tracing bookkeeping; ``hops`` is the
    # full vector the waterfall renders.
    trace_id: Optional[str] = None
    hops: Optional[dict] = None
    # Pipeline metadata echoed back from the request's FitConfig (see
    # FitConfig.job_id/.stage): lets a job runner — or any caller
    # multiplexing stages over one scheduler — attribute results
    # without a side table.
    job_id: Optional[str] = None
    stage: Optional[str] = None


class FitFuture:
    """Await/poll/cancel handle for one submitted fit request.

    The deliberately tiny subset of ``concurrent.futures.Future`` the
    serving layer needs: :meth:`result` blocks (with an optional
    caller-side timeout — independent of the request's *deadline*,
    which the scheduler enforces), :meth:`exception` fetches the
    error without raising, :meth:`cancel` withdraws a request that
    has not been picked up by a bucket yet.

    ``requeues`` is the request's requeue history: the fleet router
    appends one ``{"t", "worker", "reason", "bundle"}`` entry every
    time the request is moved off a lost/preempted worker, so a
    delivered result (or terminal error) carries the full migration
    story of the request that produced it.  Empty for requests that
    never left their first home.
    """

    def __init__(self, request_id: int):
        self.request_id = request_id
        self.requeues: list = []
        # The request's distributed-tracing id (None when tracing is
        # off): the caller-side handle into the merged waterfall —
        # `python -m multigrad_tpu.telemetry.trace --trace <id>`.
        self.trace_id: Optional[str] = None
        self._event = threading.Event()
        self._lock = make_lock("serve.queue.FitFuture._lock")
        self._result: Optional[FitResult] = None
        self._exception: Optional[BaseException] = None
        self._running = False
        self._cancelled = False

    # -- scheduler side -----------------------------------------------------
    def _set_running(self) -> bool:
        """Claim the request for a dispatch; False if already
        cancelled (the dispatcher skips it)."""
        with self._lock:
            if self._cancelled:
                return False
            self._running = True
            return True

    def _requeued(self):
        """Back to pending (the retry path re-enqueues the request)."""
        with self._lock:
            self._running = False

    def _set_result(self, result: FitResult):
        # First resolution wins (same contract as _set_exception): a
        # request requeued off a stalled-but-alive worker can complete
        # twice — once on the survivor, once when the original worker
        # wakes up — and the late duplicate must not clobber the
        # delivered result.
        with self._lock:
            if self._event.is_set():
                return
            self._result = result
        self._event.set()

    def _set_exception(self, exc: BaseException):
        with self._lock:
            if self._event.is_set():
                return
            self._exception = exc
        self._event.set()

    # -- caller side --------------------------------------------------------
    def cancel(self) -> bool:
        """Withdraw the request.  Only a still-pending request can be
        cancelled — once a bucket has claimed it (or it is done) this
        returns False.  A successful cancel resolves the future with
        :class:`FitCancelled`; the queue slot is reclaimed lazily at
        the dispatcher's next pass."""
        with self._lock:
            if self._running or self._event.is_set():
                return False
            self._cancelled = True
            self._exception = FitCancelled(
                f"request {self.request_id} cancelled")
        self._event.set()
        return True

    def cancelled(self) -> bool:
        return self._cancelled

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> FitResult:
        """Block until served; raises the fit's error
        (:class:`FitFailed` / :class:`FitDeadlineExceeded` /
        :class:`FitCancelled`) or ``TimeoutError`` if ``timeout``
        elapses first."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not served within "
                f"{timeout} s (still "
                f"{'running' if self._running else 'queued'})")
        if self._exception is not None:
            raise self._exception
        return self._result

    def exception(self, timeout: Optional[float] = None
                  ) -> Optional[BaseException]:
        """The fit's error (or None on success), without raising it."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not served within "
                f"{timeout} s")
        return self._exception


@dataclass
class FitRequest:
    """One queued fit: a guess, its config, and delivery bookkeeping."""

    id: int
    guess: np.ndarray
    config: FitConfig
    future: FitFuture
    deadline: Optional[float] = None      # absolute time.time()
    submitted_t: float = field(default_factory=time.time)
    retried: bool = False
    # Trace context (telemetry.tracing.TraceContext) propagated from
    # the request's origin; ``owns_trace`` marks contexts THIS
    # scheduler minted (single-process serving), i.e. the scheduler
    # also records the root `request` span at settle — a fleet
    # worker's scheduler must not, the router owns that root.
    trace: Optional[object] = None
    owns_trace: bool = False
    # QoS identity (serve.qos.QosTag): tenant / priority_class /
    # slo_deadline.  Carried on the REQUEST, deliberately not in the
    # config — the config is the batchability key, and same-config
    # fits from different tenants must still co-batch (duck-typed
    # object here so the queue stays import-free of the policy
    # module).  None schedules as the shared default tenant.
    qos: Optional[object] = None

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline is None:
            return False
        return (time.time() if now is None else now) > self.deadline


def _group_key(req: FitRequest) -> tuple:
    """Batchability key: the config AND the guess dimensionality.

    Unbounded configs carry no ndim of their own, and packing a
    stray 3-parameter guess into a 2-parameter bucket would fail the
    whole group at the stack step — the ndim in the key keeps a
    malformed request's failure its own."""
    return (req.config, int(req.guess.shape[0]))


class FitQueue:
    """Bounded thread-safe FIFO of :class:`FitRequest`\\ s.

    ``max_pending`` is the backpressure bound: :meth:`submit` beyond
    it raises :class:`QueueFullError` (immediately, or after
    ``timeout`` when ``block=True``).  Cancelled requests keep their
    slot until the dispatcher's next :meth:`take_group` purges them —
    the bound is on *tracked* requests, which is what admission
    control is protecting.  Expired requests do NOT keep theirs: both
    admission (a full queue) and :meth:`take_group` purge them,
    settling their futures :class:`FitDeadlineExceeded` — a backlog
    of dead deadlines must never block a live tenant's submit.

    ``qos`` (a :class:`~multigrad_tpu.serve.qos.QosPolicy`) replaces
    the FIFO dequeue with policy-driven scheduling: per-tenant
    deficit round-robin picks whose config home dequeues, EDF orders
    the group, per-tenant quotas reject before global queue-full,
    and a full queue sheds its lowest priority class to admit
    strictly-higher-class work.  ``None`` (the default) keeps the
    legacy FIFO behavior bit-for-bit.

    ``on_settle(request, kind)`` is called — outside the lock,
    before the future resolves — for every request the queue settles
    itself (``kind`` is ``"expired"`` or ``"shed"``): the
    scheduler's hook for trace roots and counters, preserving the
    root-before-resolve convention of every other settle path.
    """

    def __init__(self, max_pending: int = 1024, qos=None,
                 on_settle=None):
        self.max_pending = int(max_pending)
        if self.max_pending <= 0:
            raise ValueError("max_pending must be positive")
        self.qos = qos
        self._on_settle = on_settle
        self._lock = make_lock("serve.queue.FitQueue._lock")
        self._not_empty = make_condition(
            "serve.queue.FitQueue._not_empty", lock=self._lock)
        self._not_full = make_condition(
            "serve.queue.FitQueue._not_full", lock=self._lock)
        self._pending: collections.deque = collections.deque()
        self._ids = itertools.count()
        self._closed = False

    # -- producer side ------------------------------------------------------
    def next_id(self) -> int:
        return next(self._ids)

    def submit(self, request: FitRequest, block: bool = False,
               timeout: Optional[float] = None, front: bool = False,
               force: bool = False) -> FitFuture:
        """Enqueue; raises :class:`QueueFullError` on backpressure and
        ``RuntimeError`` once the queue is closed.  ``front`` puts the
        request at the head (the retry path: a poisoned request gets
        its fresh bucket before newer work); ``force`` bypasses the
        capacity check — ONLY for re-enqueues of already-admitted
        requests (their slot was released at take time, so forcing
        them back never grows the tracked-work bound past one request
        beyond ``max_pending``).

        With a QoS policy attached, admission is class- and
        tenant-aware: the tenant's quota is checked first
        (:class:`~multigrad_tpu.serve.qos.TenantQuotaError` — "you
        are over quota" — before any global queue-full verdict), a
        full queue first purges expired requests (settled
        :class:`FitDeadlineExceeded`), and failing that sheds its
        lowest-class queued request (settled
        :class:`~multigrad_tpu.serve.qos.FitShedError`) to admit
        strictly-higher-class work."""
        deadline = None if timeout is None else time.time() + timeout
        use_qos = self.qos is not None and self.qos.enabled
        while True:
            settle: list = []    # (request, kind, exc), resolved
            admitted = False     # outside the lock below
            with self._not_full:
                if self._closed:
                    raise RuntimeError(
                        "queue is closed (scheduler shutting down)")
                now = time.time()
                if use_qos and not force:
                    self.qos.check_quota(self._pending, request, now)
                if force or len(self._pending) < self.max_pending:
                    admitted = True
                else:
                    # Full queue: dead deadlines don't hold slots —
                    # purge-then-admit, so a queue full of expired
                    # requests still admits a live tenant.
                    popped = self._pop_expired(now)
                    if popped:
                        settle += [
                            (r, "expired", FitDeadlineExceeded(
                                f"request {r.id} deadline passed "
                                "while queued"))
                            for r in popped]
                        admitted = True
                    elif use_qos:
                        victim = self.qos.shed_victim(self._pending,
                                                      request)
                        if victim is not None:
                            self._pending = collections.deque(
                                r for r in self._pending
                                if r is not victim)
                            self.qos.record_shed(victim)
                            settle.append((
                                victim, "shed",
                                self.qos.shed_error(victim,
                                                    request)))
                            admitted = True
                    if not admitted:
                        if not block:
                            raise QueueFullError(
                                f"queue at max_pending="
                                f"{self.max_pending}")
                        remaining = None if deadline is None \
                            else deadline - time.time()
                        if remaining is not None and remaining <= 0:
                            raise QueueFullError(
                                f"queue still at max_pending="
                                f"{self.max_pending} after "
                                f"{timeout} s")
                        self._not_full.wait(remaining)
                if admitted:
                    if front:
                        self._pending.appendleft(request)
                    else:
                        self._pending.append(request)
                    self._not_empty.notify()
            self._settle(settle)
            if admitted:
                return request.future

    # -- consumer (dispatcher) side -----------------------------------------
    def take_group(self, max_n: int, window_s: float = 0.0,
                   timeout: Optional[float] = None
                   ) -> Tuple[list, list]:
        """Pop the oldest request plus every same-config request
        behind it, up to ``max_n``.

        Blocks up to ``timeout`` for the first request; once one is
        available, waits up to ``window_s`` more (the batch window)
        for a burst to coalesce into a fuller bucket — returning
        early the moment ``max_n`` compatible requests are pending.
        Cancelled requests are purged along the way.

        Returns ``(group, cancelled)``; ``group`` is empty on
        timeout.  FIFO order is preserved for requests left behind
        (other-config requests keep their positions).

        Expired requests are purged HERE — settled
        :class:`FitDeadlineExceeded` (after the ``on_settle`` hook)
        instead of occupying capacity until a dispatch notices.

        With a QoS policy the head is policy-chosen instead of
        FIFO: deficit round-robin over tenants picks the winner,
        EDF picks the winner's most urgent request, and the
        returned group is packing-ordered (winner first, then
        co-batched riders, each EDF-sorted) with the winner's
        deficit charged.  A head deadline tighter than the batch
        window collapses the window (see
        :meth:`~multigrad_tpu.serve.qos.QosPolicy
        .effective_window`).
        """
        expired: list = []
        use_qos = self.qos is not None and self.qos.enabled
        try:
            with self._not_empty:
                if not self._wait_for_pending(timeout):
                    return [], self._purge_cancelled()
                cancelled = self._purge_cancelled()
                now = time.time()
                expired += self._pop_expired(now)
                if not self._pending:
                    return [], cancelled
                # lock-ok: blocking-under-lock QosPolicy.select is pure in-memory DRR+EDF over the pending deque (no I/O, no other lock) — the policy's documented contract is that every mutator runs under this queue lock
                head = self.qos.select(self._pending, now) \
                    if use_qos else self._pending[0]
                key = _group_key(head)
                if use_qos:
                    window_s = self.qos.effective_window(
                        head, window_s, now)
                if window_s > 0:
                    batch_deadline = time.time() + window_s
                    while (self._count_matching(key) < max_n):
                        remaining = batch_deadline - time.time()
                        if remaining <= 0:
                            break
                        self._not_empty.wait(remaining)
                    cancelled += self._purge_cancelled()
                    expired += self._pop_expired()
                if use_qos:
                    matching = [r for r in self._pending
                                if _group_key(r) == key]
                    group = self.qos.order_group(matching)[:max_n]
                    taken = set(map(id, group))
                    keep = collections.deque(
                        r for r in self._pending
                        if id(r) not in taken)
                    self.qos.charge(group)
                else:
                    group, keep = [], collections.deque()
                    for req in self._pending:
                        if len(group) < max_n \
                                and _group_key(req) == key:
                            group.append(req)
                        else:
                            keep.append(req)
                self._pending = keep
                if group:      # cancelled purges notified already
                    self._not_full.notify_all()
                return group, cancelled
        finally:
            # Settled OUTSIDE the lock (root-before-resolve via the
            # on_settle hook, no user code under the queue lock).
            self._settle([(r, "expired", FitDeadlineExceeded(
                f"request {r.id} deadline passed while queued"))
                for r in expired])

    def _wait_for_pending(self, timeout: Optional[float]) -> bool:
        deadline = None if timeout is None else time.time() + timeout
        while not any(not r.future.cancelled() for r in self._pending):
            if self._closed and not self._pending:
                return False
            remaining = None if deadline is None \
                else deadline - time.time()
            if remaining is not None and remaining <= 0:
                return bool(self._pending)
            self._not_empty.wait(remaining)
        return True

    def _count_matching(self, key) -> int:
        return sum(1 for r in self._pending
                   if _group_key(r) == key
                   and not r.future.cancelled())

    def _purge_cancelled(self) -> list:
        purged = [r for r in self._pending if r.future.cancelled()]
        if purged:
            self._pending = collections.deque(
                r for r in self._pending if not r.future.cancelled())
            # Every purge frees backpressure headroom — wake blocked
            # producers HERE, so no take_group return path (e.g. the
            # everything-was-cancelled early return) can strand a
            # submit(block=True) caller on a now-empty queue.
            self._not_full.notify_all()
        return purged

    def _pop_expired(self, now: Optional[float] = None) -> list:
        """Remove (but do NOT settle) expired, uncancelled requests
        — called under the lock; the caller settles the returned
        requests outside it via :meth:`_settle`."""
        now = time.time() if now is None else now
        popped = [r for r in self._pending
                  if not r.future.cancelled() and r.expired(now)]
        if popped:
            dead = set(map(id, popped))
            self._pending = collections.deque(
                r for r in self._pending if id(r) not in dead)
            self._not_full.notify_all()
        return popped

    def _settle(self, items):
        """Resolve queue-settled requests — ``(request, kind, exc)``
        triples — outside the lock: the ``on_settle`` hook first
        (trace roots / counters; root-before-resolve), then the
        future.  Hook failures never strand a future unresolved."""
        for req, kind, exc in items:
            if self._on_settle is not None:
                try:
                    self._on_settle(req, kind)
                except Exception:
                    pass
            req.future._set_exception(exc)

    def qos_counts(self) -> dict:
        """Cumulative class-aware shed counters
        (``{"by_class": {...}, "by_tenant": {...}}``) — the payload
        tagged worker ``reject`` messages and
        :class:`~multigrad_tpu.serve.fleet.FleetSaturatedError`
        carry.  Empty without a policy."""
        with self._lock:
            if self.qos is None:
                return {"by_class": {}, "by_tenant": {}}
            return self.qos.shed_counts()

    # -- shared -------------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._pending)

    def empty(self) -> bool:
        return len(self) == 0

    def close(self):
        """Refuse new submissions (pending requests stay drainable)."""
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def drain_pending(self) -> list:
        """Pop everything (the non-graceful shutdown path)."""
        with self._lock:
            out = list(self._pending)
            self._pending.clear()
            self._not_full.notify_all()
        return out
