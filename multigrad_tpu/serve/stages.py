"""Typed stages of a posterior-pipeline job DAG.

A real posterior analysis is a *pipeline*, not a fit: Latin-hypercube
scan → multi-start ensemble → Laplace proposal → HMC refinement →
posterior-predictive checks.  This module defines each of those as a
typed :class:`Stage` a :class:`~multigrad_tpu.serve.jobs.Job` composes
into a DAG; the :class:`~multigrad_tpu.serve.jobs.JobRunner` resolves
dependencies and calls each ready stage's :meth:`Stage.run` with a
:class:`StageRuntime` handle.

Execution split — the MPMD-pipeline shape (PAPERS.md,
arXiv:2412.14374) over this repo's planes:

* **Fit fan-out stages** (:class:`SweepStage`, :class:`EnsembleStage`,
  :class:`FitStage`) ride the serving plane: one shared
  :class:`~multigrad_tpu.serve.queue.FitConfig` per stage (stamped
  with ``job_id``/``stage``, so the burst coalesces into its own
  bucket family and — through a fleet — keys its own worker
  affinity), submitted as a burst through the runner's backend
  (:class:`~multigrad_tpu.serve.scheduler.FitScheduler` or
  :class:`~multigrad_tpu.serve.fleet.FleetRouter`).
* **Host-side stages** (:class:`LaplaceStage`, :class:`HmcStage`,
  :class:`PredictiveCheckStage`) run on the runner's local model —
  HMC through the sharded-K path when the model's mesh has one
  (:func:`~multigrad_tpu.inference.ensemble
  .resolve_k_shard_topology`) — because their products are exactly
  the small host-side artifacts the pipeline flows between stages.

Artifact contract: every stage returns a **JSON-able dict** of small
host-side values — best-basin params, a Laplace covariance, HMC
diagnostics — never catalogs (the pjit-on-TPUv4 discipline of keeping
only O(|y|+|params|) crossing stage boundaries, arXiv:2204.06514).
JSON-ability is what makes stage-boundary checkpoints (and therefore
lost-worker recovery) trivial; consumers re-materialize arrays with
``np.asarray``.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Optional, Tuple

import numpy as np

from .queue import FitConfig

__all__ = ["Stage", "StageRuntime", "FitStage", "SweepStage",
           "EnsembleStage", "LaplaceStage", "HmcStage",
           "PredictiveCheckStage"]


def _tolist(x):
    return np.asarray(x, dtype=float).tolist()


@dataclass
class StageRuntime:
    """What a running stage may touch — handed to :meth:`Stage.run`
    by the job runner.

    Attributes
    ----------
    backend :
        The fit backend (``FitScheduler`` or ``FleetRouter``);
        :meth:`submit` / :meth:`run_fits` wrap it.
    model :
        The runner's local model (or fused
        :class:`~multigrad_tpu.core.group.OnePointGroup`) for
        host-side stages; ``None`` when the runner was built purely
        over a fleet without a local model.
    artifacts : dict
        Completed upstream stages' artifacts, by stage name.
    stage_ctx :
        This stage's span context within the job trace (``None``
        with tracing off); per-fit submits go out as its children.
    """

    job_id: str
    stage: str
    backend: Any = None
    model: Any = None
    artifacts: dict = field(default_factory=dict)
    stage_ctx: Any = None
    tracer: Any = None
    telemetry: Any = None
    #: True when the backend records each fit's ``request`` span
    #: itself on a caller-supplied context (the fleet router's
    #: first-settle-wins root); False means run_fits records them so
    #: scheduler hop spans still resolve to a parent.
    backend_records_request_span: bool = False
    fit_timeout_s: Optional[float] = None
    #: The owning job's QoS identity (multigrad_tpu.serve.qos):
    #: when set, every submit this stage fans out carries the tag —
    #: NOT part of the FitConfig, so same-config fits from different
    #: tenants still share a bucket.
    tenant: Optional[str] = None
    priority_class: Optional[str] = None

    def _qos_kwargs(self) -> dict:
        if self.tenant is None and self.priority_class is None:
            return {}
        from .qos import make_tag
        return {"qos": make_tag(None, self.tenant,
                                self.priority_class, None)}

    def config(self, **kwargs) -> FitConfig:
        """A stage-stamped :class:`FitConfig`: one per stage, so the
        whole burst shares a dispatch-group (and fleet-affinity)
        identity."""
        kwargs.setdefault("job_id", self.job_id)
        kwargs.setdefault("stage", self.stage)
        return FitConfig(**kwargs)

    def submit(self, guess, config: FitConfig):
        """Submit one fit, parented into this stage's trace span."""
        kwargs = self._qos_kwargs()
        if self.stage_ctx is not None:
            kwargs["trace"] = self.stage_ctx.child()
        return self.backend.submit(np.asarray(guess, dtype=float),
                                   config=config, **kwargs)

    def run_fits(self, guesses, config: FitConfig):
        """Fan a burst of fits out through the backend and gather.

        Submits every guess (the shared ``config`` makes the burst
        bucket-coalescible), blocks for all results, and — when the
        backend does not itself close caller-supplied contexts —
        records each fit's ``request`` span so the dispatch hops
        recorded under it resolve in the merged waterfall.

        Returns ``(params, losses)`` as ``(K, ndim)`` / ``(K,)``
        numpy arrays, in submit order.  Raises the first fit's
        exception on failure (the runner's stage-retry machinery
        owns recovery).
        """
        import time as _time
        pairs = []
        qos_kwargs = self._qos_kwargs()
        for guess in guesses:
            trace = self.stage_ctx.child() \
                if self.stage_ctx is not None else None
            t0 = _time.time()
            future = self.backend.submit(
                np.asarray(guess, dtype=float), config=config,
                **qos_kwargs,
                **({"trace": trace} if trace is not None else {}))
            pairs.append((future, trace, t0))
        params, losses = [], []
        first_error = None
        for future, trace, t0 in pairs:
            try:
                result = future.result(timeout=self.fit_timeout_s)
            except BaseException as err:
                if self.tracer is not None and trace is not None \
                        and not self.backend_records_request_span:
                    self.tracer.record(trace, "request", t0,
                                       ok=False, outcome="failed",
                                       job_id=self.job_id,
                                       stage=self.stage)
                if first_error is None:
                    first_error = err
                continue
            if self.tracer is not None and trace is not None \
                    and not self.backend_records_request_span:
                self.tracer.record(trace, "request", t0,
                                   outcome="ok", job_id=self.job_id,
                                   stage=self.stage,
                                   request=result.request_id)
            params.append(np.asarray(result.params, dtype=float))
            losses.append(float(result.loss))
        if first_error is not None:
            raise first_error
        return np.asarray(params), np.asarray(losses)

    def require_model(self, stage_kind: str):
        if self.model is None:
            raise ValueError(
                f"{stage_kind} runs host-side on the runner's local "
                "model; construct JobRunner(model=...) (a FleetRouter "
                "backend carries no model of its own)")
        return self.model

    def artifact(self, dep: str) -> dict:
        if dep not in self.artifacts:
            raise KeyError(
                f"stage {self.stage!r} needs upstream artifact "
                f"{dep!r}, have {sorted(self.artifacts)}")
        return self.artifacts[dep]


@dataclass
class Stage:
    """One node of a job DAG.

    ``name`` keys the stage's artifact, checkpoint entry, trace
    label, and ``FitConfig.stage`` stamp; ``deps`` are upstream stage
    names whose artifacts :meth:`run` may read.  Subclasses override
    :meth:`run` to return the stage's JSON-able artifact dict.
    """

    name: str
    deps: Tuple[str, ...] = ()

    def __post_init__(self):
        if not self.name or not isinstance(self.name, str):
            raise ValueError("stage name must be a non-empty string")
        self.deps = tuple(str(d) for d in self.deps)

    def run(self, rt: StageRuntime) -> dict:
        raise NotImplementedError

    def _upstream_best(self, rt: StageRuntime):
        """Best-basin params from the first dep exposing one (the
        standard artifact flow: sweep → ensemble → laplace/hmc)."""
        for dep in self.deps:
            art = rt.artifacts.get(dep) or {}
            if "best_params" in art:
                return np.asarray(art["best_params"], dtype=float)
        raise KeyError(
            f"stage {self.name!r}: no dependency artifact carries "
            f"'best_params' (deps: {self.deps})")


@dataclass
class FitStage(Stage):
    """Generic fit fan-out: one served fit per row of ``guesses``."""

    guesses: Any = None
    nsteps: int = 100
    learning_rate: float = 0.01
    param_bounds: Optional[tuple] = None
    randkey: Optional[int] = None

    def run(self, rt: StageRuntime) -> dict:
        guesses = np.atleast_2d(np.asarray(self.guesses, dtype=float))
        config = rt.config(
            nsteps=self.nsteps, learning_rate=self.learning_rate,
            param_bounds=self.param_bounds, randkey=self.randkey)
        params, losses = rt.run_fits(guesses, config)
        best = int(np.argmin(losses))
        return {"params": _tolist(params), "losses": _tolist(losses),
                "best_params": _tolist(params[best]),
                "best_loss": float(losses[best]),
                "n_fits": int(len(losses))}


@dataclass
class SweepStage(Stage):
    """Latin-hypercube scan: ``n_points`` short bounded fits over the
    prior box — the cheap basin-finding pass.  ``param_bounds`` is
    required (it IS the scan box)."""

    n_points: int = 16
    nsteps: int = 30
    learning_rate: float = 0.05
    param_bounds: Optional[tuple] = None
    seed: int = 0

    def run(self, rt: StageRuntime) -> dict:
        if self.param_bounds is None:
            raise ValueError(
                f"SweepStage {self.name!r} requires param_bounds "
                "(the scan box)")
        from ..utils.util import latin_hypercube_sampler
        low = np.asarray([b[0] for b in self.param_bounds], float)
        high = np.asarray([b[1] for b in self.param_bounds], float)
        inits = latin_hypercube_sampler(low, high, len(low),
                                        self.n_points, seed=self.seed)
        config = rt.config(
            nsteps=self.nsteps, learning_rate=self.learning_rate,
            param_bounds=self.param_bounds)
        params, losses = rt.run_fits(inits, config)
        best = int(np.argmin(losses))
        return {"params": _tolist(params), "losses": _tolist(losses),
                "best_params": _tolist(params[best]),
                "best_loss": float(losses[best]),
                "n_fits": int(len(losses))}


@dataclass
class EnsembleStage(Stage):
    """Multi-start refinement: long bounded fits from the upstream
    scan's ``n_starts`` best distinct basins (falling back to the
    single upstream best scattered by ``spread`` when the upstream
    artifact carries no per-start table)."""

    n_starts: int = 4
    nsteps: int = 200
    learning_rate: float = 0.01
    param_bounds: Optional[tuple] = None
    spread: float = 0.02
    seed: int = 0

    def _inits(self, rt: StageRuntime) -> np.ndarray:
        for dep in self.deps:
            art = rt.artifacts.get(dep) or {}
            if "params" in art and "losses" in art:
                params = np.asarray(art["params"], dtype=float)
                losses = np.asarray(art["losses"], dtype=float)
                order = np.argsort(losses)[:self.n_starts]
                inits = params[order]
                if len(inits) == self.n_starts:
                    return inits
        best = self._upstream_best(rt)
        rng = np.random.default_rng(self.seed)
        return best[None, :] + self.spread * rng.standard_normal(
            (self.n_starts, best.shape[0]))

    def run(self, rt: StageRuntime) -> dict:
        config = rt.config(
            nsteps=self.nsteps, learning_rate=self.learning_rate,
            param_bounds=self.param_bounds)
        params, losses = rt.run_fits(self._inits(rt), config)
        best = int(np.argmin(losses))
        return {"params": _tolist(params), "losses": _tolist(losses),
                "best_params": _tolist(params[best]),
                "best_loss": float(losses[best]),
                "n_fits": int(len(losses))}


@dataclass
class LaplaceStage(Stage):
    """Gauss–Newton Fisher + Laplace covariance at the upstream best
    basin — the O(ndim²) host-side proposal the HMC stage warms up
    from."""

    jitter: float = 1e-6
    randkey: Optional[int] = None

    def run(self, rt: StageRuntime) -> dict:
        model = rt.require_model("LaplaceStage")
        from ..inference.fisher import fisher_information
        best = self._upstream_best(rt)
        fisher = fisher_information(model, best,
                                    randkey=self.randkey)
        cov = np.asarray(fisher.covariance(jitter=self.jitter))
        stderr = np.asarray(fisher.stderr(jitter=self.jitter))
        return {"best_params": _tolist(best),
                "covariance": _tolist(cov),
                "stderr": _tolist(stderr),
                "fisher": _tolist(np.asarray(fisher.fisher))}


@dataclass
class HmcStage(Stage):
    """Multi-chain HMC refinement around the upstream basin, warmed
    by the Laplace proposal when one is upstream (chain inits
    scattered by the Laplace stderr; inverse mass set to the Laplace
    variances).  Runs host-side on the runner's local model —
    through the K-partitioned (sharded-K) program family whenever
    the model's mesh has a free replica axis."""

    num_samples: int = 300
    num_warmup: int = 200
    num_chains: int = 4
    num_leapfrog: int = 8
    step_size: float = 0.1
    target_accept: float = 0.8
    init_spread: float = 1.0
    randkey: int = 0
    keep_samples: bool = False
    k_sharded: Any = "auto"

    def _laplace(self, rt: StageRuntime) -> Optional[dict]:
        for dep in self.deps:
            art = rt.artifacts.get(dep) or {}
            if "stderr" in art:
                return art
        return None

    def run(self, rt: StageRuntime) -> dict:
        model = rt.require_model("HmcStage")
        from ..inference.ensemble import resolve_k_shard_topology
        from ..inference.hmc import run_hmc
        best = self._upstream_best(rt)
        laplace = self._laplace(rt)
        inv_mass = None
        init = best
        spread = 0.0
        if laplace is not None:
            stderr = np.asarray(laplace["stderr"], dtype=float)
            finite = np.isfinite(stderr) & (stderr > 0)
            stderr = np.where(finite, stderr, 1e-3)
            inv_mass = stderr ** 2
            rng = np.random.default_rng(self.randkey)
            init = best[None, :] + self.init_spread * stderr \
                * rng.standard_normal((self.num_chains,
                                       best.shape[0]))
        else:
            spread = self.init_spread * 1e-2
        k_sharded, _ = resolve_k_shard_topology(model, self.k_sharded)
        result = run_hmc(
            model, init, num_samples=self.num_samples,
            num_warmup=self.num_warmup, num_chains=self.num_chains,
            step_size=self.step_size, num_leapfrog=self.num_leapfrog,
            inv_mass=inv_mass, target_accept=self.target_accept,
            randkey=self.randkey, init_spread=spread,
            telemetry=rt.telemetry, k_sharded=k_sharded)
        samples = np.asarray(result.samples)
        flat = samples.reshape(-1, samples.shape[-1])
        artifact = {
            "best_params": _tolist(flat.mean(axis=0)),
            "posterior_mean": _tolist(flat.mean(axis=0)),
            "posterior_stderr": _tolist(flat.std(axis=0)),
            "rhat": _tolist(result.rhat),
            "ess": _tolist(result.ess),
            "accept_prob": _tolist(result.accept_prob),
            "divergences": _tolist(result.divergences),
            "num_chains": int(samples.shape[0]),
            "num_samples": int(samples.shape[1]),
            "k_sharded": bool(k_sharded),
        }
        if self.keep_samples:
            artifact["samples"] = _tolist(samples)
        else:
            # The predictive-check stage needs draws, not the whole
            # chain: a small thinned tail rides the artifact.
            keep = min(64, flat.shape[0])
            step = max(1, flat.shape[0] // keep)
            artifact["draws"] = _tolist(flat[::step][:keep])
        return artifact


@dataclass
class PredictiveCheckStage(Stage):
    """Posterior-predictive sanity gate: evaluate the joint loss over
    posterior draws (one batched program dispatch) and verdict the
    posterior against the basin it came from.  Verdicts land in the
    artifact AND as a ``predictive_check`` telemetry record, so
    ``/status`` and the report CLI surface a failed check without
    touching the artifact store."""

    max_draws: int = 64
    #: Fail the check when fewer than this fraction of draw losses
    #: are finite.
    min_finite_frac: float = 0.99
    #: Fail when the median draw loss exceeds the loss at the
    #: posterior mean by more than this many units of scale, where
    #: ``scale = max(|loss_at_mean|, 1)`` — a posterior that wandered
    #: off its basin.  A shifted excess rather than a ratio, so the
    #: threshold keeps its teeth for negative (log-likelihood-style)
    #: losses, where any negative median would make a ratio
    #: trivially small; tighten it well below 1 for such losses.
    max_median_excess: float = 50.0

    def _draws(self, rt: StageRuntime):
        for dep in self.deps:
            art = rt.artifacts.get(dep) or {}
            for key in ("draws", "samples"):
                if key in art:
                    draws = np.asarray(art[key], dtype=float)
                    draws = draws.reshape(-1, draws.shape[-1])
                    return draws[:self.max_draws], art
        raise KeyError(
            f"stage {self.name!r}: no dependency artifact carries "
            f"posterior 'draws'/'samples' (deps: {self.deps})")

    def run(self, rt: StageRuntime) -> dict:
        import jax.numpy as jnp
        model = rt.require_model("PredictiveCheckStage")
        draws, upstream = self._draws(rt)
        mean = np.asarray(
            upstream.get("posterior_mean",
                         upstream.get("best_params")), dtype=float)
        program = model.batched_loss_and_grad_fn(False)
        batch = jnp.asarray(np.vstack([mean[None, :], draws]))
        losses, _ = program(batch, model.aux_leaves(),
                            jnp.zeros(()))
        losses = np.asarray(losses, dtype=float)
        loss_at_mean = float(losses[0])
        draw_losses = losses[1:]
        finite = np.isfinite(draw_losses)
        finite_frac = float(np.mean(finite)) if draw_losses.size \
            else 0.0
        median = float(np.median(draw_losses[finite])) \
            if finite.any() else math.inf
        scale = max(abs(loss_at_mean), 1.0)
        median_excess = (median - loss_at_mean) / scale \
            if math.isfinite(median) else math.inf
        verdicts = {
            "finite": finite_frac >= self.min_finite_frac,
            "concentrated": median_excess <= self.max_median_excess,
        }
        ok = all(verdicts.values())
        artifact = {
            "ok": bool(ok),
            "verdicts": {k: bool(v) for k, v in verdicts.items()},
            "n_draws": int(draw_losses.size),
            "finite_frac": finite_frac,
            "loss_at_mean": loss_at_mean,
            "median_draw_loss": median,
            "median_excess": float(median_excess)
            if math.isfinite(median_excess) else None,
        }
        if rt.telemetry is not None:
            rt.telemetry.log(
                "predictive_check", job_id=rt.job_id,
                stage=rt.stage, **artifact)
        return artifact
