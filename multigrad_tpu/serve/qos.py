"""Multi-tenant QoS: priorities, weighted fairness, deadline packing.

The policy half of the serving layer's scheduling decisions.  The
FIFO :class:`~multigrad_tpu.serve.queue.FitQueue` treats every
request identically — one heavy tenant starves everyone, shedding
evicts whoever submitted last, and tail latency is an outcome, not a
policy.  This module turns each of those decisions into an explicit,
testable policy object:

* :class:`QosTag` — who a request belongs to (``tenant``), how much
  it matters (``priority_class``), and how soon it is useful
  (``slo_deadline_s``).  The tag rides ON THE REQUEST, deliberately
  NOT inside :class:`~multigrad_tpu.serve.queue.FitConfig`: the
  config is the batchability key (and the fleet's affinity key), so
  same-config fits from *different tenants still co-batch into one
  bucket* — the paper's core economics (a marginal bucket row is
  nearly free) is exactly why multi-tenancy works here, and putting
  the tenant in the key would shatter buckets per tenant and
  multiply retraces for zero isolation gain.
* :class:`QosPolicy` — the scheduling policy the queue consults:
  **deficit round-robin** over tenants (weighted fair shares;
  a tenant submitting 10x faster gets its fair share, not 10x),
  then **EDF** (earliest deadline first) within the winning
  tenant's config home, per-tenant admission quotas
  (:class:`TenantQuotaError` — "YOU are over quota" — rejects
  before the global queue-full), and class-aware shedding: a full
  queue sheds the *lowest* priority class with the most slack
  (:class:`FitShedError`) to admit strictly-higher-class work.

Deadline-aware bucket packing is the composition of three existing
mechanisms with the EDF dequeue order: ``buckets="auto"`` resolves
the bucket ladder from the autotuner's *measured fits/hour* (PR 12),
``k_budget_bytes`` caps it with the sharded-K memory model (PR 14),
and the queue hands the scheduler each group EDF-ordered — so when
a group splits across dispatches, the earliest deadlines ride the
first bucket, and a head-of-line request whose deadline is tighter
than the batch window collapses the window to zero
(:meth:`QosPolicy.effective_window`) instead of idling its slack
away waiting for stragglers to coalesce.

Concurrency contract: a :class:`QosPolicy` instance is owned by
exactly one :class:`~multigrad_tpu.serve.queue.FitQueue`; every
mutating method (``select`` / ``charge`` / ``check_quota`` /
``shed_victim`` / ``record_shed``) is called *inside* that queue's
``_lock`` critical sections, so the policy carries no lock of its
own — the queue's lock is the policy's lock.  Read shed counters
through :meth:`FitQueue.qos_counts`, which takes the queue lock.
"""
from __future__ import annotations

import collections
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from .queue import QueueFullError

__all__ = ["PRIORITY_CLASSES", "DEFAULT_TENANT", "DEFAULT_CLASS",
           "QosTag", "QosPolicy", "TenantQuotaError", "FitShedError",
           "class_rank", "request_tag", "make_tag", "edf_key",
           "edf_sorted", "deadlines_met", "jain_fairness"]

#: Built-in priority classes, ranked low → high.  Free-form class
#: names are allowed (a newer peer may send one this build has never
#: heard of); unknown classes rank lowest — a scheduler must never
#: give work it cannot identify precedence over work it can.
PRIORITY_CLASSES = ("batch", "standard", "interactive")

DEFAULT_TENANT = "default"
DEFAULT_CLASS = "standard"


def class_rank(priority_class: str,
               order: Tuple[str, ...] = PRIORITY_CLASSES) -> int:
    """Rank of a priority class in ``order`` (0 = lowest, shed
    first).  Unknown classes rank 0."""
    try:
        return order.index(priority_class)
    except ValueError:
        return 0


@dataclass(frozen=True)
class QosTag:
    """Per-request QoS identity: tenant, priority class, and an
    optional relative deadline.

    ``slo_deadline_s`` is the request's *useful-by* horizon in
    seconds from submit: it becomes the request's absolute deadline
    when the caller gave none, and it is the key EDF packs buckets
    by.  The tag is frozen and hashable but is **not** part of the
    batchability key — see the module docstring for why.
    """

    tenant: str = DEFAULT_TENANT
    priority_class: str = DEFAULT_CLASS
    slo_deadline_s: Optional[float] = None

    def __post_init__(self):
        for name in ("tenant", "priority_class"):
            value = getattr(self, name)
            if not isinstance(value, str) or not value:
                raise TypeError(
                    f"QosTag.{name} must be a non-empty str, "
                    f"got {value!r}")
        if self.slo_deadline_s is not None:
            object.__setattr__(self, "slo_deadline_s",
                               float(self.slo_deadline_s))
            if self.slo_deadline_s <= 0:
                raise ValueError(
                    f"QosTag.slo_deadline_s must be positive, got "
                    f"{self.slo_deadline_s}")


#: The identity every untagged request schedules as.
DEFAULT_TAG = QosTag()


def request_tag(req) -> QosTag:
    """The request's :class:`QosTag` (the default tag for untagged
    requests — legacy callers schedule as one shared tenant)."""
    tag = getattr(req, "qos", None)
    return tag if tag is not None else DEFAULT_TAG


def make_tag(qos=None, tenant: Optional[str] = None,
             priority_class: Optional[str] = None,
             slo_deadline_s: Optional[float] = None
             ) -> Optional[QosTag]:
    """Coerce the submit-surface QoS kwargs into one tag.

    ``qos`` (a prebuilt :class:`QosTag`) wins; otherwise a tag is
    built from the piecewise fields; all-defaults returns ``None``
    so untagged requests stay untagged (and off the wire)."""
    if qos is not None:
        if not isinstance(qos, QosTag):
            raise TypeError(
                f"qos must be a QosTag, got {type(qos).__name__}")
        return qos
    if tenant is None and priority_class is None \
            and slo_deadline_s is None:
        return None
    return QosTag(
        tenant=DEFAULT_TENANT if tenant is None else tenant,
        priority_class=(DEFAULT_CLASS if priority_class is None
                        else priority_class),
        slo_deadline_s=slo_deadline_s)


class TenantQuotaError(QueueFullError):
    """Per-tenant admission quota pushed back — "YOU are over quota",
    distinct from "the queue is full": the queue may have plenty of
    headroom for *other* tenants.  A subclass of
    :class:`~multigrad_tpu.serve.queue.QueueFullError` so existing
    backpressure handlers keep working unmodified."""

    def __init__(self, tenant: str, queued: int, quota: int):
        self.tenant = tenant
        self.queued = int(queued)
        self.quota = int(quota)
        super().__init__(
            f"tenant {tenant!r} is at its per-tenant quota "
            f"({queued}/{quota} queued); the queue itself has "
            "headroom — this is tenant admission control, not "
            "fleet saturation")


class FitShedError(QueueFullError):
    """A queued request was shed from a full queue to admit
    strictly-higher-class work (class-aware load shedding).  The
    shed request's future resolves with this; the error names both
    sides of the trade."""

    def __init__(self, request_id: int, tenant: str,
                 priority_class: str, shed_for: str):
        self.request_id = int(request_id)
        self.tenant = tenant
        self.priority_class = priority_class
        self.shed_for = shed_for
        super().__init__(
            f"request {request_id} (class {priority_class!r}, "
            f"tenant {tenant!r}) shed from a full queue to admit "
            f"{shed_for!r}-class work")


def edf_key(req, order: Tuple[str, ...] = PRIORITY_CLASSES) -> tuple:
    """Earliest-deadline-first sort key: finite deadlines first
    (ascending), then higher class, then FIFO.  Deadline-less
    requests sort after every deadlined one — they have infinite
    slack by definition."""
    deadline = getattr(req, "deadline", None)
    tag = request_tag(req)
    return (deadline is None,
            0.0 if deadline is None else float(deadline),
            -class_rank(tag.priority_class, order),
            req.submitted_t, req.id)


def edf_sorted(requests, order: Tuple[str, ...] = PRIORITY_CLASSES
               ) -> list:
    """Requests in EDF order (stable)."""
    return sorted(requests, key=lambda r: edf_key(r, order))


def deadlines_met(requests, service_s: float, batch: int = 1,
                  now: float = 0.0) -> int:
    """How many deadlines a serving order meets: serve ``requests``
    in the given order, ``batch`` at a time, each dispatch costing
    ``service_s`` seconds — count the requests whose (absolute)
    deadline is ``None`` or ≥ their completion time.  The pure
    simulation the EDF-packing test and the fairness bench share."""
    met = 0
    for i, req in enumerate(requests):
        done_t = now + (i // max(1, int(batch)) + 1) * float(service_s)
        deadline = getattr(req, "deadline", None)
        if deadline is None or done_t <= deadline:
            met += 1
    return met


def jain_fairness(values) -> float:
    """Jain's fairness index over per-tenant allocations:
    ``(Σx)² / (n·Σx²)`` — 1.0 is perfectly fair, ``1/n`` is one
    tenant taking everything.  Empty or all-zero input is vacuously
    fair (1.0)."""
    vals = [float(v) for v in values]
    if not vals:
        return 1.0
    denom = len(vals) * sum(v * v for v in vals)
    if denom == 0:
        return 1.0
    total = sum(vals)
    return (total * total) / denom


@dataclass
class QosPolicy:
    """The scheduling policy a :class:`~multigrad_tpu.serve.queue
    .FitQueue` consults when QoS is on.

    Parameters
    ----------
    class_order : tuple of str
        Priority classes, lowest first (shed order).  Unknown
        classes rank with the lowest.
    weights : dict tenant → float
        Fair-share weights for deficit round-robin; a weight-2
        tenant gets twice the dequeue credit per round of a
        weight-1 tenant.  ``default_weight`` covers tenants not
        listed.
    tenant_quota : int, optional
        Max *live* (non-expired, non-cancelled) queued requests per
        tenant; a submit past it raises :class:`TenantQuotaError`
        before the global queue-full check.
    quantum : float
        DRR credit granted per ring visit, scaled by the tenant's
        weight.  Request cost is 1.0.
    coalesce_cost : float
        What a non-winning tenant is charged for a row that rode the
        winner's bucket.  Less than 1.0 on purpose: a co-batched row
        is nearly free in device time (the paper's marginal-cost
        identity), so it must not cost a full turn — but it is not
        fully free either, or a heavy tenant could ride every bucket
        for nothing.  Deficits are clamped at one quantum of debt so
        co-batching can defer, never starve, a tenant's own turn.
    """

    enabled: bool = True
    class_order: Tuple[str, ...] = PRIORITY_CLASSES
    weights: Dict[str, float] = field(default_factory=dict)
    default_weight: float = 1.0
    tenant_quota: Optional[int] = None
    quantum: float = 2.0
    coalesce_cost: float = 0.25

    def __post_init__(self):
        self.class_order = tuple(str(c) for c in self.class_order)
        if self.tenant_quota is not None:
            self.tenant_quota = int(self.tenant_quota)
            if self.tenant_quota <= 0:
                raise ValueError("tenant_quota must be positive")
        # DRR + shed state — guarded by the owning FitQueue._lock
        # (see the module docstring's concurrency contract).
        self._ring: collections.deque = collections.deque()
        self._known: set = set()
        self._deficits: Dict[str, float] = {}
        self._last_winner: Optional[str] = None
        self._shed_by_class: collections.Counter = \
            collections.Counter()
        self._shed_by_tenant: collections.Counter = \
            collections.Counter()

    # -- identity helpers ---------------------------------------------------
    def weight(self, tenant: str) -> float:
        return float(self.weights.get(tenant, self.default_weight))

    def rank(self, priority_class: str) -> int:
        return class_rank(priority_class, self.class_order)

    # -- admission side (under the queue lock) ------------------------------
    def check_quota(self, pending, request, now: float):
        """Raise :class:`TenantQuotaError` when the request's tenant
        is at its quota of live queued requests.  Expired and
        cancelled requests do not count — a backlog of dead work
        must not lock a live tenant out (the admission-purge
        satellite's quota-side twin)."""
        if self.tenant_quota is None:
            return
        tenant = request_tag(request).tenant
        queued = sum(
            1 for r in pending
            if request_tag(r).tenant == tenant
            and not r.future.cancelled() and not r.expired(now))
        if queued >= self.tenant_quota:
            raise TenantQuotaError(tenant, queued, self.tenant_quota)

    def shed_victim(self, pending, incoming):
        """The queued request class-aware shedding evicts for
        ``incoming``: lowest priority class strictly below the
        incoming request's, ties broken toward the most slack
        (no deadline beats a far deadline beats a near one, then
        newest submit).  ``None`` when nothing queued ranks below
        the incoming class — equal classes never shed each other."""
        inc_rank = self.rank(request_tag(incoming).priority_class)
        victim = best = None
        for r in pending:
            if r.future.cancelled():
                continue
            rank = self.rank(request_tag(r).priority_class)
            if rank >= inc_rank:
                continue
            slack = (0, 0.0) if r.deadline is None \
                else (1, -float(r.deadline))
            key = (rank, slack, -r.submitted_t, -r.id)
            if best is None or key < best:
                victim, best = r, key
        return victim

    def record_shed(self, victim):
        tag = request_tag(victim)
        self._shed_by_class[tag.priority_class] += 1
        self._shed_by_tenant[tag.tenant] += 1

    def shed_error(self, victim, incoming) -> FitShedError:
        vtag = request_tag(victim)
        return FitShedError(victim.id, vtag.tenant,
                            vtag.priority_class,
                            request_tag(incoming).priority_class)

    def shed_counts(self) -> dict:
        """``{"by_class": {...}, "by_tenant": {...}}`` cumulative
        shed counters (read through :meth:`FitQueue.qos_counts`,
        which holds the queue lock)."""
        return {"by_class": dict(self._shed_by_class),
                "by_tenant": dict(self._shed_by_tenant)}

    # -- dequeue side (under the queue lock) --------------------------------
    def select(self, pending, now: float):
        """The request whose config home dequeues next: deficit
        round-robin picks the winning tenant, EDF picks the winner's
        most urgent request."""
        by_tenant: dict = {}
        for r in pending:
            if r.future.cancelled():
                continue
            by_tenant.setdefault(request_tag(r).tenant, []).append(r)
        if not by_tenant:
            return pending[0]
        winner = self._drr_pick(list(by_tenant))
        self._last_winner = winner
        return min(by_tenant[winner],
                   key=lambda r: edf_key(r, self.class_order))

    def _drr_pick(self, tenants) -> str:
        """Deficit round-robin: visit tenants in ring order, each
        visit granting ``quantum × weight`` credit (capped at one
        quantum — idle tenants must not bank unbounded credit);
        first active tenant whose deficit covers one request wins."""
        active = set(tenants)
        for t in tenants:
            if t not in self._known:
                self._known.add(t)
                self._ring.append(t)
                self._deficits.setdefault(t, 0.0)
        for _ in range(2 * len(self._ring)):
            t = self._ring[0]
            self._ring.rotate(-1)
            if t not in active:
                continue
            if self._deficits[t] >= 1.0:
                return t
            credit = self.quantum * self.weight(t)
            self._deficits[t] = min(self._deficits[t] + credit,
                                    max(1.0, credit))
            if self._deficits[t] >= 1.0:
                return t
        # Degenerate (all weights ≈ 0): serve somebody rather than
        # spin — the first active tenant in submit order.
        return tenants[0]

    def charge(self, group):
        """Debit the dequeued group's tenants: the winner pays full
        fare, co-batched riders pay ``coalesce_cost``.  Debt is
        clamped at one quantum so riding buckets defers a tenant's
        next turn, never starves it."""
        winner = self._last_winner
        for r in group:
            t = request_tag(r).tenant
            cost = 1.0 if (winner is None or t == winner) \
                else self.coalesce_cost
            cap = max(1.0, self.quantum * self.weight(t))
            self._deficits[t] = max(
                self._deficits.get(t, 0.0) - cost, -cap)

    def order_group(self, group) -> list:
        """Bucket packing order for a dequeued config home: the
        winning tenant's rows first (its turn), then co-batched
        riders — each side EDF-ordered, so when a group splits
        across dispatches the tightest deadlines ride the first
        bucket."""
        winner = self._last_winner
        return sorted(group, key=lambda r: (
            (0 if request_tag(r).tenant == winner else 1,)
            + edf_key(r, self.class_order)))

    def effective_window(self, head, window_s: float,
                         now: float) -> float:
        """Deadline-aware batch window: a head request whose slack
        is inside ~2 windows dispatches immediately — waiting for a
        fuller bucket would spend the very slack the deadline
        protects."""
        if window_s <= 0 or head.deadline is None:
            return window_s
        if head.deadline - now < 2.0 * window_s:
            return 0.0
        return window_s
