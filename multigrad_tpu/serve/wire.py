"""Fleet wire protocol: newline-delimited JSON over a local socket.

The router (:class:`~multigrad_tpu.serve.fleet.FleetRouter`) and its
worker processes (:mod:`~multigrad_tpu.serve.worker`) speak a tiny
asynchronous message protocol — one JSON object per line over one
persistent TCP connection per worker.  Requests and responses are
correlated by the router-assigned request id (``rid``); nothing in
the protocol blocks, so a worker can stream heartbeats while fits are
in flight and the router can keep submitting while results drain.

Router → worker ops:

``submit``
    ``{rid, guess, config, deadline_t, retried, submitted_t,
    trace}`` — one fit request.  ``deadline_t`` is an *absolute*
    wall-clock epoch so a request re-enqueued after a worker death
    keeps its original deadline; ``retried`` forwards the request's
    consumed poison retry so a re-enqueue cannot double-fire it;
    ``trace`` carries the request's W3C-style trace context
    (``{"traceparent": ...}``, see :mod:`~multigrad_tpu.telemetry
    .tracing`) so the worker's hop spans join the router-minted
    trace; ``qos`` (optional) carries the request's QoS tag
    (``{tenant, priority_class, slo_deadline_s}``, see
    :mod:`~multigrad_tpu.serve.qos`) — absent for untagged
    requests, ignored by pre-QoS workers.
``drain``
    Graceful preemption: serve everything queued, then exit (the
    protocol twin of SIGTERM).
``ping`` / ``stop`` / ``chaos``
    Liveness probe / hard shutdown / fault injection (the latter only
    honored by workers launched with ``--chaos``).  ``ping`` may
    carry ``t0`` (sender wall clock); the ``pong`` echoes it back,
    which is how the router measures per-worker RPC round-trip time
    (the ``multigrad_fleet_rpc_rtt`` gauge).

Worker → router ops:

``result`` / ``error`` / ``reject``
    Per-request terminal responses (``reject`` is the load-shed
    signal: the worker's queue is full, route elsewhere).  A
    QoS-aware worker's reject additionally carries ``reason``
    (``"queue_full"`` vs ``"tenant_quota"`` — "the fleet is busy"
    vs "YOU are over quota"), the rejected tenant, and ``shed``
    (cumulative per-class/per-tenant shed counters) — all optional
    keys an untagged router simply ignores.
``heartbeat``
    Periodic liveness + load report (``queue_depth``, ``inflight``,
    scheduler counters).  A monitored worker additionally carries
    ``resources`` — the compact :class:`~multigrad_tpu.telemetry
    .ResourceMonitor` snapshot (RSS, device memory, ``busy_frac``,
    compile accounting) feeding the router's fleet-wide utilization
    view; optional both ways (a legacy heartbeat decodes with the
    field ``None``, a decorated one is ignored by a legacy router).
    A history-keeping worker also carries ``rollup`` — the compact
    since-last-heartbeat slice of its windowed rollup store (fit /
    shed / device-busy counters, queue-wait count/sum/max) the
    router merges into a fleet-level history that survives the
    worker; optional both ways with the same legacy semantics (no
    key → no history, never fabricated zeros).  Heartbeat loss is
    how the router detects a SIGKILL'd or wedged worker.
``poison_retry``
    The worker's scheduler consumed a request's one poison retry —
    recorded by the router so a later requeue forwards
    ``retried=True``.
``draining`` / ``drained``
    Preemption notices bracketing a graceful drain.

**Forward compatibility is a protocol invariant**: every handler on
both sides MUST ignore unknown message keys, unknown config fields,
and unknown ops — trace fields (and whatever comes next) roll out
across a *mixed-version* fleet, where a decorated router talks to an
undecorated worker and vice versa.  The codecs below read known
keys explicitly (``d.get(...)`` with defaults) and never splat a
wire dict into a constructor; ``tests/test_tracing.py`` pins the
contract by sending decorated messages at undecorated handlers.

Everything here is stdlib + numpy; jax never enters the wire layer.
"""
from __future__ import annotations

import json
import socket
from typing import Optional

import numpy as np

from .._lockdep import make_lock
from .queue import FitConfig, FitResult

__all__ = ["JsonlChannel", "config_to_wire", "config_from_wire",
           "qos_to_wire", "qos_from_wire", "shed_to_wire",
           "shed_from_wire", "result_to_wire", "result_from_wire",
           "resources_to_wire", "resources_from_wire",
           "rollup_to_wire", "rollup_from_wire"]


class JsonlChannel:
    """Thread-safe newline-JSON message channel over a socket.

    ``send`` may be called from any thread (one writer lock
    serializes lines); ``recv``/iteration is single-consumer.
    Iteration ends cleanly on EOF or a closed socket — the reader
    loop's "peer went away" signal.
    """

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._rfile = sock.makefile("rb")
        self._wlock = make_lock("serve.wire.JsonlChannel._wlock")

    def send(self, msg: dict):
        data = (json.dumps(msg, separators=(",", ":")) + "\n").encode()
        with self._wlock:
            # lock-ok: blocking-under-lock the lock EXISTS to serialize whole lines onto the socket; no other lock is ever taken under it (leaf in the lock graph), so a slow peer delays only other writers of the same channel
            self._sock.sendall(data)

    def recv(self) -> Optional[dict]:
        """Next message, or ``None`` on EOF."""
        line = self._rfile.readline()
        if not line:
            return None
        return json.loads(line)

    def __iter__(self):
        while True:
            try:
                msg = self.recv()
            except (OSError, ValueError):
                return
            if msg is None:
                return
            yield msg

    def close(self):
        for fn in (self._rfile.close,
                   lambda: self._sock.shutdown(socket.SHUT_RDWR),
                   self._sock.close):
            try:
                fn()
            except OSError:
                pass


# ------------------------------------------------------------------ #
# codecs
# ------------------------------------------------------------------ #
def config_to_wire(config: FitConfig) -> dict:
    return {
        "nsteps": config.nsteps,
        "learning_rate": config.learning_rate,
        "param_bounds": (None if config.param_bounds is None
                         else [None if b is None else list(b)
                               for b in config.param_bounds]),
        "randkey": config.randkey,
        "const_randkey": config.const_randkey,
        "job_id": config.job_id,
        "stage": config.stage,
    }


def config_from_wire(d: dict) -> FitConfig:
    # FitConfig.__post_init__ re-normalizes bounds lists to tuples,
    # so the JSON round trip lands on an == / hash-equal config — the
    # property worker-side bucket grouping depends on.
    #
    # Known keys are read EXPLICITLY (never FitConfig(**d)): a newer
    # router decorating the config with fields this worker predates
    # must not crash admission — the unknown fields are simply not
    # part of this version's batchability identity.
    return FitConfig(
        nsteps=d["nsteps"], learning_rate=d["learning_rate"],
        param_bounds=d.get("param_bounds"),
        randkey=d.get("randkey"),
        const_randkey=bool(d.get("const_randkey", False)),
        job_id=d.get("job_id"), stage=d.get("stage"))


def qos_to_wire(tag) -> Optional[dict]:
    """A request's :class:`~multigrad_tpu.serve.qos.QosTag` as a
    wire dict (``None`` for untagged requests — the key stays off
    the message entirely, so an untagged router's traffic is
    byte-identical to the pre-QoS protocol)."""
    if tag is None:
        return None
    return {
        "tenant": tag.tenant,
        "priority_class": tag.priority_class,
        "slo_deadline_s": tag.slo_deadline_s,
    }


def qos_from_wire(d) -> Optional["QosTag"]:
    """Decode a submit message's ``qos`` field.  Known keys are read
    EXPLICITLY with defaults (never ``QosTag(**d)``): a newer router
    decorating the tag with fields this worker predates must not
    crash admission — and an untagged message (``None`` / ``{}``,
    an older router) decodes to ``None``, scheduling as the default
    tenant."""
    if not d:
        return None
    from .qos import DEFAULT_CLASS, DEFAULT_TENANT, QosTag
    slo_deadline = d.get("slo_deadline_s")
    return QosTag(
        tenant=str(d.get("tenant", DEFAULT_TENANT)),
        priority_class=str(d.get("priority_class", DEFAULT_CLASS)),
        slo_deadline_s=(None if slo_deadline is None
                        else float(slo_deadline)))


def shed_to_wire(counts) -> dict:
    """Per-class / per-tenant shed counters for a worker ``reject``
    message (JSON-safe copies)."""
    counts = counts or {}
    return {
        "by_class": {str(k): int(v) for k, v in
                     (counts.get("by_class") or {}).items()},
        "by_tenant": {str(k): int(v) for k, v in
                      (counts.get("by_tenant") or {}).items()},
    }


def shed_from_wire(d) -> dict:
    """Decode a ``reject`` message's ``shed`` field.  Tolerant of
    untagged workers (missing / partial dicts decode to empty
    counters) — the router's shed accounting must survive a
    mixed-version fleet."""
    if not isinstance(d, dict):
        return {"by_class": {}, "by_tenant": {}}
    out = {}
    for side in ("by_class", "by_tenant"):
        sub = d.get(side)
        out[side] = ({str(k): int(v) for k, v in sub.items()}
                     if isinstance(sub, dict) else {})
    return out


# The compact resource snapshot a heartbeat carries: every field
# numeric-or-None.  Int fields and float fields are coerced on decode
# so the router's arithmetic (headroom, fleet aggregation) never
# meets a string a buggy or future worker put on the wire.
_RESOURCE_INT_KEYS = ("rss_bytes", "device_bytes_in_use",
                      "device_peak_bytes", "device_bytes_limit",
                      "compile_count", "compile_hits",
                      "compile_misses")
_RESOURCE_FLOAT_KEYS = ("t", "uptime_s", "busy_frac", "busy_s_total",
                        "compile_s_total")


def resources_to_wire(snap) -> Optional[dict]:
    """A :meth:`~multigrad_tpu.telemetry.ResourceMonitor.snapshot`
    as a heartbeat field (``None`` before the first sample or for an
    unmonitored worker — the key stays off the message entirely, so
    an unmonitored worker's heartbeat is byte-identical to the
    pre-resources protocol)."""
    if not isinstance(snap, dict):
        return None
    out = {}
    for key in _RESOURCE_INT_KEYS:
        v = snap.get(key)
        out[key] = int(v) if isinstance(v, (int, float)) else None
    for key in _RESOURCE_FLOAT_KEYS:
        v = snap.get(key)
        out[key] = float(v) if isinstance(v, (int, float)) else None
    return out


def resources_from_wire(d) -> Optional[dict]:
    """Decode a heartbeat's ``resources`` field.  Known keys are read
    EXPLICITLY with ``None`` defaults (never splatted): a newer
    worker decorating the snapshot with fields this router predates
    must not crash the reader — and a legacy heartbeat (no
    ``resources`` key) decodes to ``None``, leaving the handle's
    fleet view unpopulated rather than zeroed."""
    if not isinstance(d, dict):
        return None
    out = {}
    for key in _RESOURCE_INT_KEYS:
        v = d.get(key)
        out[key] = int(v) if isinstance(v, (int, float)) else None
    for key in _RESOURCE_FLOAT_KEYS:
        v = d.get(key)
        out[key] = float(v) if isinstance(v, (int, float)) else None
    return out


# The compact rollup delta a heartbeat carries (PR 20): the
# since-last-heartbeat slice of the worker's history plane
# (:meth:`~multigrad_tpu.telemetry.rollup.RollupStore.take_delta`).
# Same known-keys discipline as the resource snapshot: every field
# numeric-or-None, coerced on decode, never splatted.
_ROLLUP_INT_KEYS = ("fits", "sheds", "queue_wait_count")
_ROLLUP_FLOAT_KEYS = ("t", "span_s", "device_busy_s",
                      "queue_wait_sum_s", "queue_wait_max_s")


def rollup_to_wire(delta) -> Optional[dict]:
    """A :meth:`~multigrad_tpu.telemetry.rollup.RollupStore
    .take_delta` dict as a heartbeat field (``None`` for an idle
    interval or a history-less worker — the key stays off the
    message entirely, so such a heartbeat is byte-identical to the
    pre-rollup protocol a legacy router expects)."""
    if not isinstance(delta, dict):
        return None
    out = {}
    for key in _ROLLUP_INT_KEYS:
        v = delta.get(key)
        out[key] = int(v) if isinstance(v, (int, float)) else None
    for key in _ROLLUP_FLOAT_KEYS:
        v = delta.get(key)
        out[key] = float(v) if isinstance(v, (int, float)) else None
    return out


def rollup_from_wire(d) -> Optional[dict]:
    """Decode a heartbeat's ``rollup`` field.  Known keys read
    EXPLICITLY with ``None`` defaults (never splatted): a newer
    worker's extra fields are dropped, a legacy heartbeat (no
    ``rollup`` key) decodes to ``None`` — no history, never
    fabricated zeros — and string-typed values coerce to ``None`` so
    the router's merge arithmetic never meets a str."""
    if not isinstance(d, dict):
        return None
    out = {}
    for key in _ROLLUP_INT_KEYS:
        v = d.get(key)
        out[key] = int(v) if isinstance(v, (int, float)) else None
    for key in _ROLLUP_FLOAT_KEYS:
        v = d.get(key)
        out[key] = float(v) if isinstance(v, (int, float)) else None
    return out


def result_to_wire(result: FitResult) -> dict:
    return {
        "params": np.asarray(result.params).tolist(),
        "loss": float(result.loss),
        "traj": np.asarray(result.traj).tolist(),
        "steps": int(result.steps),
        "bucket": int(result.bucket),
        "wait_s": float(result.wait_s),
        "fit_s": float(result.fit_s),
        "retried": bool(result.retried),
        "trace_id": result.trace_id,
        "hops": result.hops,
        "job_id": result.job_id,
        "stage": result.stage,
    }


def result_from_wire(d: dict, request_id, worker: Optional[str] = None
                     ) -> FitResult:
    # Trace fields are optional on the way in — an undecorated
    # (pre-tracing) worker's result still decodes; the router fills
    # in what it knows from its own side of the trace.
    hops = d.get("hops")
    return FitResult(
        request_id=request_id,
        params=np.asarray(d["params"], dtype=float),
        loss=float(d["loss"]),
        traj=np.asarray(d["traj"], dtype=float),
        steps=int(d["steps"]), bucket=int(d["bucket"]),
        wait_s=float(d["wait_s"]), fit_s=float(d["fit_s"]),
        retried=bool(d.get("retried", False)), worker=worker,
        trace_id=d.get("trace_id"),
        hops=dict(hops) if isinstance(hops, dict) else None,
        job_id=d.get("job_id"), stage=d.get("stage"))
