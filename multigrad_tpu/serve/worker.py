"""Fit-fleet worker: one scheduler process behind the fleet router.

``python -m multigrad_tpu.serve.worker`` runs one
:class:`~multigrad_tpu.serve.scheduler.FitScheduler` (its own jax
runtime, its own mesh) behind the wire protocol of
:mod:`~multigrad_tpu.serve.wire`: it prints a
``FLEET-WORKER-READY {json}`` handshake with its port, accepts ONE
router connection, and from then on serves ``submit`` ops, streams
heartbeats, and answers with ``result`` / ``error`` / ``reject``
messages.

Lifecycle contract (the preemption story):

* **SIGTERM** (or the ``drain`` op) — graceful preemption: announce
  ``draining`` (so the router routes around this worker), serve
  everything already queued via ``FitScheduler.close(drain=True)``,
  deliver the responses, announce ``drained``, exit 0.
* **SIGKILL** — nothing runs here, by definition; the router detects
  heartbeat/connection loss and re-enqueues this worker's in-flight
  requests elsewhere.
* A full local queue (``QueueFullError``) becomes a ``reject``
  message — the router's work-stealing signal, never a dropped
  request.
* A consumed poison retry is reported upstream (``poison_retry``)
  so a re-enqueued request cannot double-fire it, and incoming
  ``retried=True`` submits are marked accordingly.

Environment note: ``JAX_PLATFORMS`` / ``XLA_FLAGS`` must be set
**before launch** — the ``-m`` form imports the package (and with it
jax) before ``main`` runs, so in-process configuration is too late.
:class:`~multigrad_tpu.serve.fleet.FleetRouter` sets both from its
``platform=`` / ``devices=`` arguments.

With ``--chaos``, the worker honors fault-injection ops from the
:class:`~multigrad_tpu.serve.chaos.ChaosController`: forced
queue-full rejects, submit-path stalls, and heartbeat pauses —
deterministic handles on the failure modes the fleet must survive.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import threading
import time

__all__ = ["build_model", "main"]


def build_model(name: str, kwargs: dict):
    """Resolve a worker model spec.

    ``"smf"`` builds the stock SMF model (``num_halos`` in
    ``kwargs``, sharded over this process's mesh when it has more
    than one device).  Any ``"module:factory"`` path imports and
    calls ``factory(**kwargs)`` — the hook for serving custom
    models without touching this file.
    """
    if ":" in name:
        import importlib
        module, fn = name.split(":", 1)
        return getattr(importlib.import_module(module), fn)(**kwargs)
    if name == "smf":
        import jax

        import multigrad_tpu as mgt
        from multigrad_tpu.models.smf import SMFModel, make_smf_data
        comm = mgt.global_comm() if len(jax.devices()) > 1 else None
        n = int(kwargs.get("num_halos", 2000))
        return SMFModel(aux_data=make_smf_data(n, comm=comm),
                        comm=comm)
    raise ValueError(f"unknown worker model spec {name!r} "
                     "(builtin: 'smf'; or 'module:factory')")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m multigrad_tpu.serve.worker",
        description="One fit-fleet scheduler worker (spawned by "
                    "FleetRouter; see module docstring for the "
                    "env-var caveat when launching by hand).")
    ap.add_argument("--worker-id", default="w0")
    ap.add_argument("--rank", type=int, default=0,
                    help="fleet rank stamped on telemetry records — "
                         "each worker is its own jax runtime "
                         "(process_index 0), so without this the "
                         "cross-worker /fleet aggregation could not "
                         "tell the streams apart")
    ap.add_argument("--port", type=int, default=0,
                    help="router-facing TCP port (0 = pick free)")
    ap.add_argument("--model", default="smf",
                    help="'smf' or 'module:factory'")
    ap.add_argument("--model-kwargs", default="{}",
                    help="JSON kwargs for the model factory")
    ap.add_argument("--buckets", default="auto",
                    help="comma list of bucket sizes, or 'auto' "
                         "(default): resolve the measured fits/hour "
                         "ladder from the shared tuning table — "
                         "workers sharing the compile cache share "
                         "the table, so the fleet boots tuned "
                         "(hardcoded defaults on a cold table)")
    ap.add_argument("--tuning-table", default=None,
                    help="tuning-table path for --buckets auto "
                         "(default: beside the compile cache; "
                         "MGT_TUNING_TABLE overrides)")
    ap.add_argument("--max-pending", type=int, default=1024)
    ap.add_argument("--batch-window-s", type=float, default=0.05)
    ap.add_argument("--heartbeat-s", type=float, default=0.25)
    ap.add_argument("--telemetry", default=None,
                    help="per-worker JSONL record stream (the "
                         "router wires these into /fleet)")
    ap.add_argument("--trace", default=None,
                    help="per-worker trace-span JSONL: this "
                         "worker's hops (queue_wait, dispatch, "
                         "adam_segments, ...) recorded under the "
                         "router-minted trace contexts arriving on "
                         "submit messages; merged by trace_id with "
                         "the router's file "
                         "(python -m multigrad_tpu.telemetry.trace)")
    ap.add_argument("--flight-dir", default=None,
                    help="postmortem bundle directory")
    ap.add_argument("--compile-cache", default=None,
                    help="shared persistent XLA compile-cache dir "
                         "(the fleet-wide warm asset)")
    ap.add_argument("--live-port", type=int, default=None,
                    help="base port for this worker's LiveServer; "
                         "EADDRINUSE probes forward, so every "
                         "worker on a host can share the base")
    ap.add_argument("--no-retry-poisoned", action="store_true")
    ap.add_argument("--qos", action="store_true",
                    help="enable the QoS policy (weighted-fair "
                         "dequeue, class-aware shed, deadline-aware "
                         "packing); submit messages' qos tags are "
                         "honored instead of ignored")
    ap.add_argument("--tenant-quota", type=int, default=None,
                    help="max queued requests per tenant (requires "
                         "--qos); over-quota submits are rejected "
                         "with reason 'tenant_quota' so the router "
                         "can tell 'YOU are over quota' from 'the "
                         "fleet is busy'")
    ap.add_argument("--chaos", action="store_true",
                    help="honor chaos-injection ops (tests/demos)")
    args = ap.parse_args(argv)

    from multigrad_tpu.serve import (FitScheduler, QueueFullError,
                                     enable_compile_cache)
    from multigrad_tpu.serve.qos import (QosPolicy, TenantQuotaError)
    from multigrad_tpu.serve.wire import (JsonlChannel,
                                          config_from_wire,
                                          qos_from_wire,
                                          resources_to_wire,
                                          result_to_wire,
                                          rollup_to_wire,
                                          shed_to_wire)
    from multigrad_tpu.telemetry import JsonlSink, MetricsLogger
    from multigrad_tpu.telemetry.tracing import TraceContext, Tracer

    from multigrad_tpu._lockdep import make_lock, maybe_dump

    state = {"draining": False}
    chaos = {"reject_queue_full": 0, "stall_until": 0.0,
             "heartbeat_pause_until": 0.0}
    inflight: dict = {}              # wire rid -> local FitFuture
    local_to_rid: dict = {}          # scheduler id -> wire rid
    retried_rids: set = set()
    lock = make_lock("serve.worker.main.lock")
    chan_box: dict = {}
    logger = None
    live = None
    sched = None
    tracer = (Tracer(args.trace,
                     service=f"worker:{args.worker_id}")
              if args.trace else None)

    def _send(msg):
        chan = chan_box.get("chan")
        if chan is None:
            return
        try:
            chan.send(msg)
        except OSError:
            pass

    def _shutdown(code: int):
        try:
            if logger is not None:
                logger.close()
            if tracer is not None:
                tracer.close()
            if live is not None:
                live.stop()
            # os._exit skips atexit: flush the lockdep shadow's
            # edges/violations dump (MGT_LOCKDEP_DUMP) explicitly
            # so the chaos suite's cross-check sees this worker.
            maybe_dump()
        finally:
            # Daemon threads (scheduler, waiters, heartbeat) die
            # with the process; flushing happened above.
            os._exit(code)

    def _compact_stats() -> dict:
        if sched is None:
            return {}
        s = sched.stats
        return {k: s.get(k, 0) for k in
                ("submitted", "completed", "failed", "expired",
                 "cancelled", "retried", "dispatches")}

    def begin_drain(reason: str):
        if state["draining"]:
            return
        state["draining"] = True
        _send({"op": "draining", "worker": args.worker_id,
               "reason": reason})

        def _finish():
            # Serve everything already queued, wait for the waiter
            # threads to deliver every response, then exit 0.
            if sched is not None:
                sched.close(drain=True)
            deadline = time.time() + 120
            while inflight and time.time() < deadline:
                time.sleep(0.02)
            _send({"op": "drained", "worker": args.worker_id,
                   "stats": _compact_stats()})
            _shutdown(0)

        threading.Thread(target=_finish, daemon=True,
                         name="mgt-worker-drain").start()

    # Install the preemption handler FIRST — before the model build,
    # the compile-cache wiring or the socket exist.  On a loaded
    # host the gap between this worker's READY handshake and its
    # next timeslice can be long, and a SIGTERM landing in that gap
    # must drain (or cleanly exit), never hit the default
    # terminate-without-goodbye disposition.
    signal.signal(signal.SIGTERM,
                  lambda *a: begin_drain("sigterm"))

    if args.compile_cache:
        enable_compile_cache(args.compile_cache)
    model = build_model(args.model, json.loads(args.model_kwargs))

    if args.telemetry:
        os.makedirs(os.path.dirname(os.path.abspath(args.telemetry)),
                    exist_ok=True)
        logger = MetricsLogger(
            JsonlSink(args.telemetry),
            run_config={"fleet_worker": args.worker_id},
            run_extra={"process_index": args.rank})
    if args.live_port is not None:
        from multigrad_tpu.telemetry import LiveServer
        live = LiveServer(port=args.live_port)

    def on_poison_retry(request):
        with lock:
            rid = local_to_rid.get(request.id)
            if rid is not None:
                retried_rids.add(rid)
        if rid is not None:
            _send({"op": "poison_retry", "rid": rid})

    qos_policy = (QosPolicy(tenant_quota=args.tenant_quota)
                  if args.qos else None)
    sched = FitScheduler(
        model,
        buckets=("auto" if args.buckets.strip() == "auto"
                 else tuple(int(b) for b in args.buckets.split(","))),
        tuning_table=args.tuning_table,
        max_pending=args.max_pending,
        batch_window_s=args.batch_window_s,
        telemetry=logger, live=live, flight_dir=args.flight_dir,
        retry_poisoned=not args.no_retry_poisoned,
        on_poison_retry=on_poison_retry, tracer=tracer,
        qos=qos_policy)

    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", args.port))
    srv.listen(1)
    print("FLEET-WORKER-READY " + json.dumps({
        "id": args.worker_id, "pid": os.getpid(),
        "port": srv.getsockname()[1],
        "live_port": live.port if live is not None else None,
    }), flush=True)
    conn, _ = srv.accept()
    chan = chan_box["chan"] = JsonlChannel(conn)

    def waiter(rid: str, fut):
        exc = fut.exception(timeout=None)
        with lock:
            retried = rid in retried_rids
        # Send BEFORE dropping the in-flight entry: the drain path
        # exits the process the moment `inflight` empties, and a
        # response popped-but-unsent would be lost with it.
        if exc is None:
            # sent_t anchors the router's result_return span (same
            # host today; across hosts it inherits clock skew, read
            # against the rpc_rtt floor).
            _send({"op": "result", "rid": rid,
                   "result": result_to_wire(fut.result(timeout=0)),
                   "sent_t": time.time()})
        else:
            _send({"op": "error", "rid": rid,
                   "etype": type(exc).__name__,
                   "message": str(exc),
                   "bundle_path": getattr(exc, "bundle_path", None),
                   "retried": retried})
        with lock:
            inflight.pop(rid, None)
            local_to_rid.pop(fut.request_id, None)
            retried_rids.discard(rid)

    def handle_submit(msg):
        rid = msg["rid"]
        if state["draining"]:
            _send({"op": "reject", "rid": rid, "reason": "draining"})
            return
        if chaos["reject_queue_full"] > 0:
            chaos["reject_queue_full"] -= 1
            _send({"op": "reject", "rid": rid,
                   "reason": "queue_full"})
            return
        stall = chaos["stall_until"] - time.time()
        if stall > 0:
            # Slow-worker injection: the submit path wedges (the
            # reader thread sleeps, so EVERY later op queues behind
            # it) while heartbeats keep flowing from their own
            # thread — the "alive but useless" failure mode.
            time.sleep(stall)
        deadline_s = None
        if msg.get("deadline_t") is not None:
            deadline_s = msg["deadline_t"] - time.time()
            if deadline_s <= 0:
                _send({"op": "error", "rid": rid,
                       "etype": "FitDeadlineExceeded",
                       "message": f"request {rid} deadline passed "
                                  "before worker admission"})
                return
        retried = bool(msg.get("retried"))
        # Trace context + origin timestamp are optional wire fields
        # (mixed-version fleet): absent or malformed, the fit is
        # served untraced with a worker-local arrival time.
        trace_ctx = TraceContext.from_wire(msg.get("trace") or {})
        submitted_t = msg.get("submitted_t")
        if not isinstance(submitted_t, (int, float)):
            submitted_t = None
        # QoS tag: optional wire field (mixed-version fleet). A
        # pre-QoS router's submits decode to None and schedule as
        # the default tenant; with --qos off the tag still rides the
        # request (telemetry) but the queue dequeues FIFO.
        qos_tag = qos_from_wire(msg.get("qos"))
        try:
            fut = sched.submit(msg["guess"],
                               config=config_from_wire(msg["config"]),
                               deadline_s=deadline_s,
                               retried=retried, trace=trace_ctx,
                               submitted_t=submitted_t,
                               qos=qos_tag)
        except TenantQuotaError as e:
            # Per-tenant quota: "YOU are over quota", not "the fleet
            # is busy" — the router must NOT mark this worker
            # saturated or steal elsewhere on the tenant's behalf.
            _send({"op": "reject", "rid": rid,
                   "reason": "tenant_quota", "tenant": e.tenant,
                   "shed": shed_to_wire(sched.queue.qos_counts())})
            return
        except QueueFullError:
            shed = (shed_to_wire(sched.queue.qos_counts())
                    if qos_policy is not None else None)
            _send({"op": "reject", "rid": rid,
                   "reason": "queue_full",
                   **({"shed": shed} if shed is not None else {})})
            return
        except RuntimeError:          # queue closed: drain raced us
            _send({"op": "reject", "rid": rid, "reason": "draining"})
            return
        except (ValueError, TypeError) as e:
            _send({"op": "error", "rid": rid,
                   "etype": type(e).__name__, "message": str(e)})
            return
        with lock:
            inflight[rid] = fut
            local_to_rid[fut.request_id] = rid
            if retried:
                retried_rids.add(rid)
        threading.Thread(target=waiter, args=(rid, fut),
                         daemon=True,
                         name=f"mgt-worker-waiter-{rid}").start()

    def heartbeat_loop():
        while True:
            if time.time() >= chaos["heartbeat_pause_until"]:
                # The compact resource snapshot rides every
                # heartbeat (known-keys codec; the key stays off the
                # message for an unmonitored scheduler, so a legacy
                # router sees the pre-resources protocol verbatim).
                snap = (resources_to_wire(sched.resources.snapshot())
                        if sched.resources is not None else None)
                # The rollup delta is the since-last-heartbeat slice
                # of the worker's history plane; idle intervals (and
                # history-less schedulers) ship no key at all, so a
                # legacy router sees the pre-rollup protocol
                # verbatim.
                roll = (rollup_to_wire(sched.rollup.take_delta())
                        if sched.rollup is not None else None)
                try:
                    chan.send({
                        "op": "heartbeat", "worker": args.worker_id,
                        "t": time.time(),
                        "queue_depth": len(sched.queue),
                        "inflight": len(inflight),
                        "draining": state["draining"],
                        "stats": _compact_stats(),
                        **({"resources": snap}
                           if snap is not None else {}),
                        **({"rollup": roll}
                           if roll is not None else {})})
                except OSError:
                    return
            time.sleep(args.heartbeat_s)

    threading.Thread(target=heartbeat_loop, daemon=True,
                     name="mgt-worker-heartbeat").start()

    for msg in chan:
        op = msg.get("op")
        if op == "submit":
            handle_submit(msg)
        elif op == "ping":
            # t0 echoed back verbatim: the router's RPC round-trip
            # probe (multigrad_fleet_rpc_rtt) — absent from old
            # routers' pings, so echo None rather than require it.
            _send({"op": "pong", "worker": args.worker_id,
                   "t0": msg.get("t0"),
                   "queue_depth": len(sched.queue),
                   "stats": _compact_stats()})
        elif op == "drain":
            begin_drain("drain op")
        elif op == "stop":
            sched.close(drain=False)
            _shutdown(0)
        elif op == "chaos" and args.chaos:
            what = msg.get("what")
            if what == "queue_full":
                chaos["reject_queue_full"] += int(msg.get("n", 1))
            elif what == "stall":
                chaos["stall_until"] = time.time() \
                    + float(msg.get("duration_s", 1.0))
            elif what == "pause_heartbeat":
                chaos["heartbeat_pause_until"] = time.time() \
                    + float(msg.get("duration_s", 1.0))
    # Router hung up: drain what we hold, then exit (the drain
    # thread calls _shutdown).
    begin_drain("router disconnected")
    while True:
        time.sleep(1.0)


if __name__ == "__main__":
    raise SystemExit(main())
