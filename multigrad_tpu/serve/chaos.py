"""Chaos-injection harness for the fit-fleet.

The robustness proof of :mod:`~multigrad_tpu.serve.fleet` is not the
happy path — it is what happens when a spot TPU host disappears
mid-burst.  :class:`ChaosController` injects exactly those failures
against a live :class:`~multigrad_tpu.serve.fleet.FleetRouter`, at
configurable points, so tests and demos can assert the invariant the
fleet promises: **every submitted FitFuture resolves — result or
typed error, none lost, none hung.**

Injections (process-level faults need nothing from the worker;
protocol-level ones require workers spawned with ``chaos=True``):

===================  ==========================================
:meth:`kill`         SIGKILL — the spot-preemption worst case:
                     no drain, no goodbye; the router must detect
                     heartbeat/connection loss and re-enqueue.
:meth:`preempt`      SIGTERM — graceful preemption: the worker
                     drains and exits 0; the router routes around
                     it meanwhile.
:meth:`suspend` /    SIGSTOP / SIGCONT — a frozen host: heartbeats
:meth:`resume`       stop while the process lives; on resume, late
                     duplicate results must be ignored.
:meth:`inject_queue_full`
                     The worker rejects its next ``n`` submits as
                     queue-full — deterministic saturation, no
                     timing games — driving the reroute →
                     admission-reject path.
:meth:`stall`        The worker's submit path sleeps while
                     heartbeats keep flowing — the alive-but-
                     useless slow worker.
:meth:`pause_heartbeat`
                     Heartbeats stop while the worker keeps
                     serving — exercises false-positive death
                     declarations and late-result dedup.
===================  ==========================================

Scheduling hooks: :meth:`after` runs an injection on a timer,
:meth:`when` polls a predicate over the router (e.g. "≥ 16 requests
in flight on the victim") and fires at the matching moment —
the "configurable points" of the chaos contract.  Every injection is
recorded in ``.events`` for the post-run report.
"""
from __future__ import annotations

import os
import signal
import threading
import time

__all__ = ["ChaosController"]


class ChaosController:
    """Fault injector bound to one :class:`~multigrad_tpu.serve
    .fleet.FleetRouter` (see module docstring for the menu)."""

    def __init__(self, router):
        self.router = router
        self.events: list = []
        self._timers: list = []
        self._watchers: list = []
        self._closed = False

    # ------------------------------------------------------------------ #
    # plumbing
    # ------------------------------------------------------------------ #
    def _handle(self, worker):
        """Resolve a worker index or id to its handle."""
        if isinstance(worker, int):
            return self.router.workers[worker]
        for w in self.router.workers:
            if w.id == worker:
                return w
        raise KeyError(f"no fleet worker {worker!r}")

    def _record(self, kind: str, **detail):
        self.events.append({"t": time.time(), "kind": kind,
                            **detail})

    def _signal(self, worker, sig, kind: str):
        handle = self._handle(worker)
        if handle.proc is None or handle.proc.poll() is not None:
            raise RuntimeError(
                f"worker {handle.id} has no live process to signal")
        os.kill(handle.proc.pid, sig)
        self._record(kind, worker=handle.id, pid=handle.proc.pid)
        return handle

    def _chaos_op(self, worker, **payload):
        if not self.router.chaos_enabled:
            raise RuntimeError(
                "protocol-level chaos needs FleetRouter(chaos=True) "
                "(workers ignore chaos ops otherwise)")
        handle = self._handle(worker)
        handle.send({"op": "chaos", **payload})
        self._record("chaos_op", worker=handle.id, **payload)
        return handle

    # ------------------------------------------------------------------ #
    # process-level faults
    # ------------------------------------------------------------------ #
    def kill(self, worker=0):
        """SIGKILL: the un-drained spot preemption."""
        return self._signal(worker, signal.SIGKILL, "kill")

    def preempt(self, worker=0):
        """SIGTERM: graceful preemption (worker drains, exits 0)."""
        return self._signal(worker, signal.SIGTERM, "preempt")

    def suspend(self, worker=0):
        """SIGSTOP: freeze the process (heartbeats stop)."""
        return self._signal(worker, signal.SIGSTOP, "suspend")

    def resume(self, worker=0):
        """SIGCONT: thaw a suspended worker."""
        return self._signal(worker, signal.SIGCONT, "resume")

    # ------------------------------------------------------------------ #
    # protocol-level faults (workers spawned with chaos=True)
    # ------------------------------------------------------------------ #
    def inject_queue_full(self, worker=0, n: int = 1):
        """The worker rejects its next ``n`` submits as queue-full."""
        return self._chaos_op(worker, what="queue_full", n=int(n))

    def stall(self, worker=0, duration_s: float = 1.0):
        """Wedge the worker's submit path for ``duration_s`` while
        heartbeats keep flowing."""
        return self._chaos_op(worker, what="stall",
                              duration_s=float(duration_s))

    def pause_heartbeat(self, worker=0, duration_s: float = 1.0):
        """Silence heartbeats for ``duration_s`` while the worker
        keeps serving — long enough and the router declares it lost
        and re-enqueues; the late duplicates must be dropped."""
        return self._chaos_op(worker, what="pause_heartbeat",
                              duration_s=float(duration_s))

    # ------------------------------------------------------------------ #
    # scheduling: injections at configurable points
    # ------------------------------------------------------------------ #
    def after(self, delay_s: float, fn, *args, **kwargs):
        """Run ``fn(*args, **kwargs)`` after ``delay_s`` seconds."""
        t = threading.Timer(delay_s, self._guarded, (fn,) + args,
                            kwargs)
        t.daemon = True
        t.name = "mgt-chaos-timer"
        t.start()
        self._timers.append(t)
        return t

    def when(self, predicate, fn, *args, poll_s: float = 0.02,
             timeout_s: float = 60.0, **kwargs):
        """Fire ``fn`` the first moment ``predicate(router)`` is
        true (polled every ``poll_s``); give up after ``timeout_s``.
        Returns an event set once the injection has fired."""
        fired = threading.Event()

        def _watch():
            deadline = time.time() + timeout_s
            while not self._closed and time.time() < deadline:
                try:
                    if predicate(self.router):
                        self._guarded(fn, *args, **kwargs)
                        fired.set()
                        return
                except Exception:
                    return
                time.sleep(poll_s)

        t = threading.Thread(target=_watch, daemon=True,
                             name="mgt-chaos-watch")
        t.start()
        self._watchers.append(t)
        return fired

    def when_inflight(self, n: int, fn, *args, worker=None,
                      **kwargs):
        """Fire once ≥ ``n`` requests are in flight (on ``worker``
        if given, else fleet-wide) — "SIGKILL mid-burst with ≥ 16
        in-flight requests" as one line."""
        def _pred(router):
            if worker is None:
                return sum(len(w.inflight)
                           for w in router.workers) >= n
            return len(self._handle(worker).inflight) >= n
        return self.when(_pred, fn, *args, **kwargs)

    # ------------------------------------------------------------------ #
    def report(self) -> str:
        """Human-readable injection log."""
        if not self.events:
            return "no chaos injected"
        t0 = self.events[0]["t"]
        lines = []
        for e in self.events:
            detail = " ".join(f"{k}={v}" for k, v in e.items()
                              if k not in ("t", "kind"))
            lines.append(f"+{e['t'] - t0:6.2f}s  {e['kind']:<10s} "
                         f"{detail}")
        return "\n".join(lines)

    def _guarded(self, fn, *args, **kwargs):
        try:
            fn(*args, **kwargs)
        except Exception as e:           # a late timer must not die
            self._record("injection_failed", error=repr(e))

    def close(self):
        self._closed = True
        for t in self._timers:
            t.cancel()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
