"""Bucketed fit scheduler: pad-and-pack dispatch over the batched scan.

The dispatcher half of the fit-fleet serving layer.  A daemon thread
drains the :class:`~multigrad_tpu.serve.queue.FitQueue`, packs
same-config requests into a few **quantized bucket sizes** (default
``K ∈ {1, 4, 16, 64}``), pads the guess matrix up to the bucket, and
drives the whole bucket through ONE batched ``(K, ndim)`` Adam scan —
the same :func:`~multigrad_tpu.optim.adam.run_adam_scan` +
``batched_loss_and_grad`` path :func:`~multigrad_tpu.inference
.run_multistart_adam` already uses, through the same cached wrapper,
so ensembles and served fits share compiled programs.

Why quantize?  The compiled program's identity includes the batch
shape, so admitting arbitrary K would retrace per distinct request
count.  With buckets, **retraces are bounded by the bucket count per
fit config, not by the request count**: serving 10 000 requests of
one config compiles at most ``len(buckets)`` programs
(``tests/test_serve.py`` counts the traces).  Padding rows replicate
the first request's guess — they advance as a redundant fit and are
sliced away in finalize (Adam's elementwise update makes batch rows
exact independent fits, so padding never perturbs real rows).

Fault isolation (the serving layer's robustness contract, helpers in
:mod:`.robustness`):

* a NaN/Inf in one tenant's fit is contained to its own row — its
  batch-mates' results are bitwise identical to a clean batch;
* the poisoned request alone gets a flight-recorder postmortem
  bundle and (after one retry in a fresh bucket, if enabled) an
  errored future carrying the bundle path;
* deadlines are enforced at dispatch time; cancelled requests are
  purged before they cost a bucket row;
* :meth:`FitScheduler.close` drains gracefully by default — pending
  requests are served before the dispatcher exits.

Observability: scheduler gauges (queue depth, bucket occupancy,
fits/hour, per-outcome counters) land in the PR-9
:class:`~multigrad_tpu.telemetry.LiveServer` registry via ``live=``,
and every served request closes with its own ``fit_summary``
telemetry record via ``telemetry=``.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .compile_cache import DEFAULT_BUCKETS, warmup_buckets
from .qos import QosPolicy, make_tag, request_tag
from .queue import (FitCancelled, FitConfig, FitFailed, FitFuture,
                    FitOOMError, FitQueue, FitRequest, FitResult)
from .robustness import nonfinite_rows, request_postmortem, \
    split_expired

__all__ = ["FitScheduler", "DEFAULT_BUCKETS"]

#: Message fragments that identify a device out-of-memory failure
#: across backends (XLA's RESOURCE_EXHAUSTED, pjrt "out of memory",
#: TPU HBM allocator messages).  Deliberately no bare "oom" token:
#: as a substring it matches innocent words (room/bloom/doom) and
#: would reclassify unrelated failures.
_OOM_MARKERS = ("resource_exhausted", "out of memory",
                "hbm_allocator", "allocation failure")


def _is_oom(exc: BaseException) -> bool:
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        text = f"{type(exc).__name__}: {exc}".lower()
        if any(m in text for m in _OOM_MARKERS):
            return True
        exc = exc.__cause__ or exc.__context__
    return False


class FitScheduler:
    """Multi-tenant batched fit scheduler over one model.

    Parameters
    ----------
    model : OnePointModel
        The model every request fits (its comm decides the mesh; the
        batched kernel vmaps the K evaluations inside the SPMD
        block, so collectives batch and the per-request communication
        stays O(|sumstats| + |params|)).
    buckets : sequence of int, or "auto"
        Quantized batch sizes (sorted ascending internally).  A
        dispatch group of n requests runs in the smallest bucket
        ≥ n; groups larger than the top bucket split across
        dispatches.  ``"auto"`` (the default) resolves the ladder
        the autotuner measured for this model's shape from the
        on-disk tuning table — bucket sizes chosen by measured
        fits/hour (:func:`multigrad_tpu.tune.tune_buckets`) instead
        of the hardcoded set; a cold table resolves to
        :data:`DEFAULT_BUCKETS`, the historical default.  Workers
        sharing the compile cache share the table, so a fleet boots
        tuned.
    max_pending : int
        Queue bound — the backpressure knob (see
        :class:`~multigrad_tpu.serve.queue.FitQueue`).
    batch_window_s : float
        How long the dispatcher holds a non-full bucket open for a
        burst to coalesce.  0 disables coalescing (lowest latency,
        worst packing).
    telemetry : MetricsLogger, optional
        Per-request ``fit_summary`` records and per-dispatch
        ``serve_dispatch`` records join this stream; the scheduler's
        flight recorder is attached as a sink so postmortem bundles
        carry the records around the failure.
    live : LiveServer | LiveSink | LiveMetrics, optional
        Scheduler gauges (``multigrad_serve_*``) land in this
        registry — pass the same :class:`~multigrad_tpu.telemetry
        .LiveServer` the fits' monitors use and ``/metrics`` serves
        the fleet view.  Also joined to ``telemetry`` as a sink when
        both are given.
    flight_dir : str, optional
        Where per-request postmortem bundles land (default: a fresh
        temp dir on first dump).
    retry_poisoned : bool
        Re-enqueue a poisoned request once, at the head of the queue
        (a fresh bucket).  A second poisoning fails the future.
    on_poison_retry : callable, optional
        Called with the :class:`~multigrad_tpu.serve.queue
        .FitRequest` the moment its one poison retry is consumed —
        the fleet worker uses this to tell its router, so a request
        re-enqueued after a worker death cannot double-fire the
        retry.  Exceptions from the callback are swallowed (a
        notification must never fail the retry it reports).
    donate_carry : bool, optional
        Forwarded to the batched scan (None = backend auto) — wide
        buckets hold K moment sets instead of 2K on TPU/GPU.
    tuning_table : TuningTable | str, optional
        Tuning table ``buckets="auto"`` resolves from (default: the
        table beside the persistent compile cache; see
        :func:`multigrad_tpu.tune.default_table_path`).
    k_sharded : {"auto", True, False}
        Run bucket dispatches on the sharded-K path: on a 2-level
        :func:`~multigrad_tpu.parallel.ensemble_comm` mesh, a
        bucket's ``(K, ndim)`` batch — params, trajectory and both
        Adam moment sets — is partitioned K/R per device over the
        replica axis, so the serve layer's max bucket is bounded by
        the POD's memory instead of one device's.  ``"auto"`` (the
        default) enables it exactly when the model's comm carries a
        replica axis (a no-op on ordinary one-axis comms); only
        buckets divisible by the replica count shard — the K=1
        singleton rung always runs the replicated program.  Results
        are bitwise-stable per request in exact arithmetic and agree
        with the replicated path to float tolerance on real models.
    k_budget_bytes : int, optional
        Per-device memory budget for bucket dispatch state.  When
        set, the bucket ladder is capped per (config, ndim) by the
        sharded-K memory model
        (:func:`~multigrad_tpu.inference.max_k_for_budget`) instead
        of a hardcoded max: a dispatch group larger than the cap
        splits across dispatches rather than risking a device OOM.
        An OOM that still happens fails its group with the typed
        :class:`~multigrad_tpu.serve.queue.FitOOMError` carrying the
        memory-model estimate and the sharded-K remedy.
    tracer : Tracer, optional
        Distributed request tracing (:class:`~multigrad_tpu
        .telemetry.tracing.Tracer`): every dispatched request's hops
        — ``queue_wait``, ``bucket_coalesce``, ``dispatch``
        (compile-vs-cached flagged), ``adam_segments``,
        ``finalize``, ``result_return`` — are recorded as
        ``trace_span`` records under the request's trace context.
        Requests submitted without a context (direct single-process
        serving) get one minted here, and the scheduler also records
        their root ``request`` span at settle; requests arriving
        WITH a context (a fleet worker relaying router traffic)
        parent their hops into it, and the root stays the router's.
        Hop latencies additionally feed ``multigrad_serve_hop_
        seconds`` / ``multigrad_serve_fit_latency_seconds``
        histograms in ``live=`` with the trace id as the exemplar.
    qos : QosPolicy | bool, optional
        Multi-tenant QoS (:mod:`multigrad_tpu.serve.qos`): replaces
        the FIFO dequeue with per-tenant deficit-round-robin +
        EDF-within-config scheduling, per-tenant quotas, and
        class-aware shedding.  ``True`` builds a default
        :class:`~multigrad_tpu.serve.qos.QosPolicy`; ``None`` /
        ``False`` (the default) keeps legacy FIFO behavior
        bit-for-bit.  Tag requests via :meth:`submit`'s ``qos`` /
        ``tenant`` / ``priority_class`` / ``slo_deadline_s``.
    slo : SloMonitor | iterable of (Slo | str), optional
        Declared latency objectives (:mod:`multigrad_tpu.serve
        .slo`): per-class latency histograms and SLO verdict gauges
        (``multigrad_qos_*``) export into ``live=``; with QoS on
        and no SLOs declared, a bare monitor still observes
        per-class latency for ``/status``.
    monitor_resources : bool
        Run a per-process :class:`~multigrad_tpu.telemetry
        .ResourceMonitor` for the scheduler's lifetime (default on):
        host RSS / device memory / compile accounting sampled on a
        daemon thread, every bucket dispatch bracketed for the
        busy/idle duty cycle, ``multigrad_resource_*`` gauges in
        ``live=``, and a ``measured_vs_modeled`` memory-truth record
        per dispatch comparing the measured device peak against the
        sharded-K memory model.
    history : bool
        Keep a windowed history plane (default on): a
        :class:`~multigrad_tpu.telemetry.RollupStore` fed from the
        settle/shed paths (fits, sheds, device-busy seconds,
        queue-wait samples, per-(tenant, class) usage), scraped
        against ``live=``'s gauges on a daemon thread, and exporting
        the ``multigrad_rollup_*`` windowed signals
        ``autoscaler_inputs`` v2 reads.  The fleet worker cuts its
        heartbeat ``rollup`` deltas from this store.  ``False``
        turns the plane off entirely (the rollup-overhead bench's
        baseline leg).
    start : bool
        Start the dispatcher thread immediately.  ``start=False``
        lets tests and bulk loaders queue a full burst first.
    """

    def __init__(self, model, buckets="auto",
                 max_pending: int = 1024,
                 batch_window_s: float = 0.05, telemetry=None,
                 live=None, flight_dir: Optional[str] = None,
                 retry_poisoned: bool = True, donate_carry=None,
                 on_poison_retry=None, tuning_table=None,
                 tracer=None, k_sharded="auto",
                 k_budget_bytes: Optional[int] = None,
                 qos=None, slo=None, monitor_resources: bool = True,
                 history: bool = True, start: bool = True):
        self.model = model
        self.tracer = tracer
        # "auto": shard whenever the model was built on a 2-level
        # ensemble mesh — the operator chose that topology for
        # exactly this — and never otherwise (the shared resolution
        # rule of every sharded-K consumer).
        from ..inference.ensemble import resolve_k_shard_topology
        self.k_sharded, self._k_replicas = \
            resolve_k_shard_topology(model, k_sharded)
        self.k_budget_bytes = (int(k_budget_bytes)
                               if k_budget_bytes is not None else None)
        self._bucket_caps: dict = {}
        if isinstance(buckets, str):
            if buckets != "auto":
                raise ValueError(
                    f"buckets must be a sequence of ints or 'auto', "
                    f"got {buckets!r}")
            from ..tune.resolve import resolve_buckets
            buckets = resolve_buckets(model, table=tuning_table)
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"buckets must be positive ints, got "
                             f"{buckets}")
        self.batch_window_s = float(batch_window_s)
        self.retry_poisoned = bool(retry_poisoned)
        self.on_poison_retry = on_poison_retry
        self.donate_carry = donate_carry
        if qos is True:
            qos = QosPolicy()
        elif qos is False:
            qos = None
        if qos is not None and not isinstance(qos, QosPolicy):
            raise TypeError(
                f"qos must be a QosPolicy or bool, got "
                f"{type(qos).__name__}")
        self.qos = qos
        self.queue = FitQueue(max_pending=max_pending, qos=qos,
                              on_settle=self._queue_settled)
        self.telemetry = telemetry
        # A LiveServer/LiveSink exposes its registry as .metrics; a
        # bare LiveMetrics IS the registry.
        self._metrics = getattr(live, "metrics", live)
        from .slo import SloMonitor
        if isinstance(slo, SloMonitor):
            self.slo = slo
        elif slo:
            self.slo = SloMonitor(self._metrics, slo)
        elif qos is not None:
            # QoS without declared objectives still observes
            # per-class latency — /status needs the histograms.
            self.slo = SloMonitor(self._metrics, ())
        else:
            self.slo = None
        if telemetry is not None and live is not None \
                and hasattr(live, "write"):
            telemetry.add_sink(live)

        from ..telemetry.flight import FlightRecorder
        # Serve recorders never latch fatal on stalls/divergences —
        # one tenant's anomaly must not wedge the fleet.
        self._recorder = FlightRecorder(
            dump_dir=flight_dir, trip_on_stall=False,
            divergence_spike=None)
        if telemetry is not None:
            telemetry.add_sink(self._recorder)

        self._dynamic = model.aux_leaves()
        self._wrappers: dict = {}
        # (config, ndim, bucket) keys already dispatched:
        # the compile-vs-cached flag on `dispatch` trace spans — the
        # first dispatch of a program identity pays trace+build (or
        # an on-disk cache read), every later one reuses it.
        self._dispatched_programs: set = set()
        self._window_open_t: Optional[float] = None
        from ..telemetry.live import LatencyObserver
        from .._lockdep import make_lock
        self._latency = LatencyObserver(self._metrics,
                                        "multigrad_serve",
                                        "served fit")
        self._lock = make_lock("serve.scheduler.FitScheduler._lock")
        self._stats = collections.Counter()
        self._inflight_group: Optional[list] = None
        # (bucket, use_sharded) of the dispatch currently executing —
        # what _fail_group's OOM diagnostic reports, so the typed
        # error names the bucket/layout that actually failed rather
        # than re-deriving one from the pending count.
        self._inflight_dispatch: Optional[tuple] = None
        self._bucket_dispatches: collections.Counter = \
            collections.Counter()
        self._first_submit_t: Optional[float] = None
        self._last_completed_t: Optional[float] = None
        self.resources = None
        if monitor_resources:
            from ..telemetry.resources import ResourceMonitor
            self.resources = ResourceMonitor(
                live=self._metrics, logger=telemetry).start()
        # History plane (PR 20): windowed rollups fed from the
        # settle/shed paths below; the scrape thread samples the
        # registry's gauges and publishes the multigrad_rollup_*
        # windowed signals autoscaler_inputs v2 reads.
        self.rollup = None
        self._usage_logged_t = 0.0
        if history:
            from ..telemetry.rollup import RollupStore
            self.rollup = RollupStore()
            if self._metrics is not None:
                self.rollup.attach_live(self._metrics)
        self._stop = threading.Event()
        self._abort = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #
    def start(self) -> "FitScheduler":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._abort.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="mgt-fit-scheduler")
            self._thread.start()
        return self

    def close(self, drain: bool = True,
              timeout: Optional[float] = None):
        """Shut the scheduler down.

        ``drain=True`` (default, the graceful path): stop accepting
        new requests, serve everything already queued, then exit.
        ``drain=False``: stop immediately; still-pending futures are
        resolved with :class:`~multigrad_tpu.serve.queue
        .FitCancelled`.
        """
        self.queue.close()
        self._stop.set()
        if not drain:
            self._abort.set()
        if self._thread is not None:
            self._thread.join(timeout)
        for req in self.queue.drain_pending():
            # Root-before-resolve, like every other settle path: the
            # woken caller must see a rooted trace and a bumped
            # counter, not catch up to them later.
            self._trace_root(req, "cancelled")
            self._count("cancelled")
            req.future._set_exception(FitCancelled(
                f"request {req.id} cancelled by scheduler shutdown"))
        if self.resources is not None:
            self.resources.close()
        if self.rollup is not None:
            # Final per-tenant accounting flush, then stop the
            # scrape thread.
            self._emit_usage()
            self.rollup.close()

    def __enter__(self):
        # Deliberately NOT start(): a scheduler built with
        # start=False stays paused inside `with` so callers can queue
        # a deterministic burst before dispatch begins (the default
        # construction already started the thread).
        return self

    def __exit__(self, *exc):
        self.close(drain=True)
        return False

    # ------------------------------------------------------------------ #
    # submit side
    # ------------------------------------------------------------------ #
    def submit(self, guess, nsteps: int = 100,
               learning_rate: float = 0.01, param_bounds=None,
               randkey=None, const_randkey: bool = False,
               config: Optional[FitConfig] = None,
               deadline_s: Optional[float] = None,
               block: bool = False,
               timeout: Optional[float] = None,
               retried: bool = False, trace=None,
               submitted_t: Optional[float] = None,
               qos=None, tenant: Optional[str] = None,
               priority_class: Optional[str] = None,
               slo_deadline_s: Optional[float] = None) -> FitFuture:
        """Queue one fit; returns its :class:`~multigrad_tpu.serve
        .queue.FitFuture`.

        Either pass the fit schedule piecewise (``nsteps`` /
        ``learning_rate`` / ``param_bounds`` / ``randkey``) or a
        prebuilt :class:`~multigrad_tpu.serve.queue.FitConfig` —
        requests sharing a config are batchable into one bucket.
        ``deadline_s`` is a relative deadline: a request still queued
        when it expires is resolved with
        :class:`~multigrad_tpu.serve.queue.FitDeadlineExceeded`
        instead of occupying a bucket row.  ``block``/``timeout``
        select the backpressure behavior at a full queue (see
        :meth:`~multigrad_tpu.serve.queue.FitQueue.submit`).
        ``retried=True`` marks the request as having already consumed
        its one poison retry elsewhere — the fleet router sets it
        when re-enqueuing a request off a dead worker, so the retry
        cannot double-fire across worker generations.

        ``trace`` propagates a :class:`~multigrad_tpu.telemetry
        .tracing.TraceContext` minted upstream (the fleet worker
        passes the router's); with a ``tracer`` configured and no
        context given, one is minted HERE — this submit is then the
        trace's origin and the scheduler records its root span.
        ``submitted_t`` backdates the request's arrival to its
        origin wall clock (the fleet worker passes the router-side
        submit time) so ``queue_wait`` — and ``wait_s`` on the
        result — measure the tenant's real wait, transit included.

        ``qos`` (a prebuilt :class:`~multigrad_tpu.serve.qos
        .QosTag`) or the piecewise ``tenant`` / ``priority_class``
        / ``slo_deadline_s`` tag the request for QoS scheduling —
        on the request, never in the config, so same-config fits
        from different tenants still co-batch.  A tag's
        ``slo_deadline_s`` becomes the request's deadline when
        ``deadline_s`` is not given.
        """
        tag = make_tag(qos, tenant, priority_class, slo_deadline_s)
        if (deadline_s is None and tag is not None
                and tag.slo_deadline_s is not None):
            deadline_s = tag.slo_deadline_s
        if config is None:
            config = FitConfig(
                nsteps=nsteps, learning_rate=learning_rate,
                param_bounds=param_bounds, randkey=randkey,
                const_randkey=const_randkey)
        guess = np.asarray(guess, dtype=float)
        self._validate(guess, config)
        rid = self.queue.next_id()
        owns_trace = False
        if trace is None and self.tracer is not None:
            trace = self.tracer.new_trace()
            owns_trace = True
        future = FitFuture(rid)
        if trace is not None:
            future.trace_id = trace.trace_id
        request = FitRequest(
            id=rid, guess=guess, config=config,
            future=future,
            deadline=(time.time() + float(deadline_s)
                      if deadline_s is not None else None),
            retried=bool(retried), trace=trace,
            owns_trace=owns_trace, qos=tag)
        if submitted_t is not None:
            request.submitted_t = float(submitted_t)
        self.queue.submit(request, block=block, timeout=timeout)
        with self._lock:
            self._stats["submitted"] += 1
            if self._first_submit_t is None:
                self._first_submit_t = request.submitted_t
        self._gauge("multigrad_serve_queue_depth", len(self.queue),
                    help="fit requests waiting for a bucket")
        return request.future

    def _queue_settled(self, req, kind: str):
        """Bookkeeping for a request the QUEUE settles itself —
        take/submit-time expiry purge (``kind="expired"``) and
        class-aware shed (``kind="shed"``).  Called by the queue
        outside its lock, before the future resolves: the same
        root-before-resolve accounting as the dispatch-time
        paths."""
        self._trace_root(req, kind)
        self._count(kind)
        self._fits_counter(kind)
        if kind == "shed":
            tag = request_tag(req)
            if self.slo is not None:
                self.slo.record_shed(tag.priority_class, tag.tenant)
            if self.rollup is not None:
                from ..telemetry.rollup import SHEDS
                self.rollup.inc(SHEDS)
                self.rollup.note_usage(tag.tenant,
                                       tag.priority_class, sheds=1)

    def _note_history(self, req, queue_wait_s: float,
                      busy_share_s: float, now: float):
        """Feed the history plane at settle: fleet-level fit /
        queue-wait / device-busy series plus the (tenant, class)
        usage ledger, and the rate-limited ``tenant_usage`` /
        ``slo_budget`` record emission the report/dashboard
        surfaces read."""
        from ..telemetry.rollup import (DEVICE_BUSY_S, FITS,
                                        QUEUE_WAIT_S)
        self.rollup.inc(FITS)
        self.rollup.observe(QUEUE_WAIT_S, queue_wait_s)
        self.rollup.inc(DEVICE_BUSY_S, busy_share_s)
        tag = request_tag(req)
        violations = 0
        slo = self.slo.slos.get(tag.priority_class) \
            if self.slo is not None else None
        if slo is not None and now - req.submitted_t \
                > slo.threshold_s:
            violations = 1
        self.rollup.note_usage(tag.tenant, tag.priority_class,
                               fits=1, busy_s=busy_share_s,
                               violations=violations)
        if self.telemetry is not None \
                and now - self._usage_logged_t >= 2.0:
            self._emit_usage(now=now)

    def _emit_usage(self, now: Optional[float] = None):
        """Log one ``tenant_usage`` record per (tenant, class) pair
        and one ``slo_budget`` record per budgeted class — the
        stream-side view of the history plane (``telemetry.report``
        ``usage:`` section, ``telemetry.top --tenants``, the
        dashboard's budget line)."""
        if self.telemetry is None or self.rollup is None:
            return
        # lock-ok: unlocked-shared-write benign rate-limit stamp: the settle loop is the only periodic writer; close() writes once after the loop stopped, and the worst race outcome is one duplicate usage emission, never corruption
        self._usage_logged_t = time.time() if now is None else now
        for rec in self.rollup.usage_records():
            self.telemetry.log("tenant_usage", **rec)
        if self.slo is not None:
            for cls, ledger in self.slo.budgets.items():
                snap = ledger.snapshot()
                self.telemetry.log(
                    "slo_budget", priority_class=cls,
                    budget=snap["budget"],
                    remaining_frac=round(snap["remaining_frac"], 6),
                    burn_rate=round(snap["burn_rate"], 4),
                    fast_burning=snap["fast_burning"],
                    exhaustion_eta_s=snap["exhaustion_eta_s"],
                    violations=snap["violations"])

    @staticmethod
    def _validate(guess: np.ndarray, config: FitConfig):
        """Admission control: structural validity, checked at submit
        so a bad request fails its caller instead of a whole bucket.
        (Runtime failures — a finite guess whose fit goes NaN — are
        the dispatcher's per-row containment problem, not
        admission's.)"""
        if guess.ndim != 1 or guess.size == 0:
            raise ValueError(
                f"guess must be a 1-D parameter vector, got shape "
                f"{guess.shape}")
        if config.param_bounds is not None:
            from ..optim.transforms import (bounds_to_arrays,
                                            check_strictly_inside)
            low, high = bounds_to_arrays(config.bounds_list(),
                                         guess.shape[0])
            check_strictly_inside(jnp.asarray(guess), low, high,
                                  config.bounds_list())

    def warmup(self, configs, ndim: Optional[int] = None,
               buckets=None) -> list:
        """Pre-trace + pre-compile this scheduler's bucket programs
        for ``configs`` (see :func:`~multigrad_tpu.serve
        .compile_cache.warmup_buckets`); with
        :func:`~multigrad_tpu.serve.compile_cache
        .enable_compile_cache` active the executables persist for
        future processes."""
        return warmup_buckets(
            self.model, configs,
            buckets=self.buckets if buckets is None else buckets,
            ndim=ndim, donate_carry=self.donate_carry,
            k_sharded=self.k_sharded)

    # ------------------------------------------------------------------ #
    # dispatch side (scheduler thread)
    # ------------------------------------------------------------------ #
    def _loop(self):
        try:
            self._loop_body()
        except BaseException as e:
            # The dispatcher thread itself is dying — an escape the
            # per-group handler below cannot catch (BaseException, or
            # a failure in take_group/grouping).  A dead dispatcher
            # would strand every pending future forever, so settle
            # ALL of them with the cause chain attached before the
            # thread exits.  Not re-raised: the cause now lives on
            # every failed future and in the postmortem bundle, and
            # an unhandled-thread-exception would only add noise.
            self._dispatcher_backstop(e)

    def _loop_body(self):
        while not self._abort.is_set():
            group = []
            try:
                # Wall-clock anchor of the batch window: the
                # bucket_coalesce trace span measures from here (or
                # from a later request's own arrival) to dispatch.
                self._window_open_t = time.time()
                group, cancelled = self.queue.take_group(
                    self.buckets[-1],
                    window_s=self.batch_window_s,
                    timeout=0.05)
                for _ in cancelled:
                    self._count("cancelled")
                if group:
                    # Tracked for the backstop: a BaseException out
                    # of _dispatch must still fail THIS group.
                    self._inflight_group = group
                    self._dispatch(group)
                self._inflight_group = None
                self._inflight_dispatch = None
            except Exception as e:
                # ANY failure in the loop body — a dispatch dying for
                # a non-row reason (device loss, OOM) or an
                # unexpected grouping error — must fail at most its
                # own group's requests, never the dispatcher thread:
                # a dead dispatcher strands every pending future
                # forever.  Only not-yet-resolved futures count:
                # requests the dispatch already settled (expired,
                # poison-failed) must not be double-counted.
                self._fail_group(group, e, "dispatch_failed")
                self._inflight_group = None
                self._inflight_dispatch = None
            if not group and self._stop.is_set() and self.queue.empty():
                break

    def _fail_group(self, requests, exc: BaseException, reason: str,
                    bundle: Optional[str] = None):
        """Settle a group's unresolved futures with a typed error
        carrying the originating exception (``__cause__``) and the
        postmortem bundle path — the caller sees WHY its fit died,
        not a bare backstop exception.  A device OOM is classified
        into :class:`~multigrad_tpu.serve.queue.FitOOMError` with
        the sharded-K memory-model estimate and remedy in both the
        message and the bundle."""
        pending = [r for r in requests if not r.future.done()]
        if not pending:
            return
        oom = _is_oom(exc)
        est = bucket = None
        oom_msg = f"{reason}: {exc!r}"
        extra = {}
        if oom:
            from ..inference.ensemble import ensemble_memory_model
            req0 = pending[0]
            ndim = int(req0.guess.shape[0])
            nsteps = int(req0.config.nsteps)
            # The estimate and the layout named in the message must
            # describe the dispatch that actually OOMed: a dying
            # dispatch leaves its (bucket, use_sharded) in
            # _inflight_dispatch (a split group may be failing far
            # more pending requests than the failed bucket held, so
            # re-deriving the bucket from the pending count would
            # name one that never ran).  The fallback — no dispatch
            # in flight — mirrors the dispatch rule on the group
            # size.
            if self._inflight_dispatch is not None:
                bucket, sharded = self._inflight_dispatch
            else:
                from ..inference.ensemble import k_shards_bucket
                n = len(pending)
                bucket = next(b for b in self.buckets + (n,)
                              if b >= n)
                sharded = k_shards_bucket(bucket, self.k_sharded,
                                          self._k_replicas)
            n_replicas = self._k_replicas if sharded else 1
            est = ensemble_memory_model(bucket, ndim, nsteps,
                                        n_replicas=n_replicas)
            layout = (f"sharded over {n_replicas} replica slices"
                      if sharded else "replicated")
            if sharded:
                remedy = (
                    "widen the mesh — more replica slices in "
                    "parallel.ensemble_comm(n_replicas=R) shrink "
                    "per-device state K/R — or cap the bucket "
                    "ladder with k_budget_bytes")
            elif self.k_sharded:
                remedy = (
                    f"this bucket is not divisible by the replica "
                    f"count ({self._k_replicas}) so it ran the "
                    "replicated layout — use bucket sizes the "
                    "replica count divides, or cap the ladder "
                    "with k_budget_bytes")
            else:
                remedy = (
                    "shard the K axis — build the model on "
                    "parallel.ensemble_comm(n_replicas=R) and pass "
                    "FitScheduler(k_sharded=True) — or cap the "
                    "bucket ladder with k_budget_bytes")
            oom_msg = (
                f"bucket dispatch ran out of device memory "
                f"(K={bucket}, nsteps={nsteps}, {layout}: estimated "
                f"per-device fit state ≈ {est / 1e6:.1f} MB); "
                f"{remedy} (docs/distributed.md, "
                "'Sharded ensembles')")
            extra = {"oom": True, "estimated_bytes": est,
                     "bucket": bucket, "k_sharded": sharded,
                     "n_replicas": n_replicas}
        if bundle is None:
            bundle = self._recorder.dump(
                reason, error=repr(exc),
                requests=[r.id for r in pending],
                resources=self._resource_ring(), **extra)
        for req in pending:
            if oom:
                err = FitOOMError(oom_msg, req.id,
                                  bundle_path=bundle,
                                  estimated_bytes=est, bucket=bucket)
            else:
                err = FitFailed(oom_msg, req.id, bundle_path=bundle)
            err.__cause__ = exc
            # Root-before-resolve, like every other settle path: the
            # woken caller's trace triage must find a rooted trace
            # and already-bumped counters.
            self._trace_root(req, "failed", bundle=bundle)
            self._count("failed")
            self._fits_counter("failed")
            req.future._set_exception(err)

    def _dispatcher_backstop(self, exc: BaseException):
        """The dispatcher thread is exiting abnormally: refuse new
        work and fail every claimed-but-unresolved and still-queued
        request with the cause chain + one shared postmortem bundle.
        No future may hang on a dead dispatcher."""
        bundle = self._recorder.dump("dispatcher_died",
                                     error=repr(exc),
                                     resources=self._resource_ring())
        self.queue.close()
        stranded = list(self._inflight_group or []) \
            + self.queue.drain_pending()
        self._inflight_group = None
        self._fail_group(stranded, exc, "scheduler dispatcher died",
                         bundle=bundle)

    def _wrapper(self, with_key: bool, k_sharded: bool = False):
        key = (with_key, "k_sharded") if k_sharded else with_key
        if key not in self._wrappers:
            from ..inference.ensemble import batched_fit_wrapper
            self._wrappers[key] = batched_fit_wrapper(
                self.model, with_key, k_sharded=k_sharded)
        return self._wrappers[key]

    def _bucket_caps_for(self, config, ndim: int):
        """``(replicated_cap, sharded_cap)`` — the largest K the
        memory budget admits under EACH layout for this (config,
        ndim); the sharded-K memory model replacing any hardcoded
        max.  None without a budget."""
        if self.k_budget_bytes is None:
            return None
        key = (int(config.nsteps), int(ndim))
        if key not in self._bucket_caps:
            from ..inference.ensemble import max_k_for_budget
            cap_rep = max_k_for_budget(self.k_budget_bytes, ndim,
                                       config.nsteps)
            cap_sh = max_k_for_budget(
                self.k_budget_bytes, ndim, config.nsteps,
                n_replicas=self._k_replicas) if self.k_sharded \
                else cap_rep
            self._bucket_caps[key] = (cap_rep, cap_sh)
        return self._bucket_caps[key]

    def _allowed_buckets(self, config, ndim: int) -> tuple:
        caps = self._bucket_caps_for(config, ndim)
        if caps is None:
            return self.buckets
        cap_rep, cap_sh = caps
        # Each rung is judged under the layout it would actually
        # dispatch with: indivisible rungs run REPLICATED (full K
        # rows per device), so the sharded cap must not admit them.
        from ..inference.ensemble import k_shards_bucket
        allowed = tuple(
            b for b in self.buckets
            if b <= (cap_sh if k_shards_bucket(b, self.k_sharded,
                                               self._k_replicas)
                     else cap_rep))
        # The smallest rung always stays servable: a budget too tight
        # even for it degrades to singleton dispatches, never to a
        # scheduler that can serve nothing.
        return allowed or self.buckets[:1]

    def _dispatch(self, requests):
        now = time.time()
        # Roots for about-to-expire requests land BEFORE
        # split_expired resolves their futures (it raises
        # FitDeadlineExceeded inside itself) — root-before-resolve,
        # like every other settle path.  Same `now`, same verdicts.
        for req in requests:
            if req.expired(now):
                self._trace_root(req, "expired", now)
        live, expired = split_expired(requests, now)
        for req in expired:
            self._count("expired")
            self._fits_counter("expired")
        live = [r for r in live if r.future._set_running()]
        if not live:
            return
        config = live[0].config
        ndim = int(live[0].guess.shape[0])
        allowed = self._allowed_buckets(config, ndim)
        coalesce_open_t = self._window_open_t or now
        # A group larger than the memory-capped top bucket splits
        # across dispatches instead of risking a device OOM.
        step = allowed[-1]
        for i in range(0, len(live), step):
            self._dispatch_group(live[i:i + step], config, ndim,
                                 allowed, coalesce_open_t)

    def _dispatch_group(self, live, config, ndim: int, allowed,
                        coalesce_open_t):
        from ..optim import adam as _adam
        from ..optim.adam import init_randkey

        now = time.time()
        n = len(live)
        bucket = next(b for b in allowed + (n,) if b >= n)
        # Sharded-K dispatch: buckets divisible by the replica count
        # run the K-partitioned program (K/R rows of params,
        # trajectory and both Adam moment sets per device); the K=1
        # singleton rung — and any other indivisible rung — keeps
        # the replicated program (the shared k_shards_bucket rule).
        from ..inference.ensemble import k_shards_bucket
        use_sharded = k_shards_bucket(bucket, self.k_sharded,
                                      self._k_replicas)
        self._inflight_dispatch = (bucket, use_sharded)
        # compile-vs-cached for the dispatch trace span: the first
        # dispatch of this program identity pays trace+build (or an
        # on-disk XLA cache read); later ones hit the live cache.
        program_key = (config, ndim, bucket, use_sharded)
        compiled = program_key not in self._dispatched_programs
        self._dispatched_programs.add(program_key)
        t_claim = now
        # Pad-and-pack: rows n..K replicate request 0's guess.  The
        # rows advance as redundant independent fits (elementwise
        # Adam) and finalize slices them away — padding is masking by
        # construction, no in-graph select needed.
        inits = np.empty((bucket, ndim), dtype=float)
        for i, req in enumerate(live):
            inits[i] = req.guess
        inits[n:] = inits[0]
        inits = jnp.asarray(inits)
        carry_sharding = None
        if use_sharded:
            carry_sharding = self.model.k_sharding(2)
            inits = jax.device_put(inits, carry_sharding)

        if self.resources is not None:
            # Busy-window bracket: everything between enter and exit
            # is device work, the numerator of the duty-cycle
            # busy_frac the autoscaler contract publishes.
            self.resources.dispatch_enter()
        try:
            t0 = time.perf_counter()
            traj = _adam.run_adam_scan(
                self._wrapper(config.with_key, use_sharded), inits,
                nsteps=config.nsteps,
                param_bounds=config.bounds_list(),
                learning_rate=config.learning_rate,
                randkey=config.randkey,
                const_randkey=config.const_randkey, progress=False,
                fn_args=(self._dynamic,),
                donate_carry=self.donate_carry,
                carry_sharding=carry_sharding)
            finals = traj[-1]
            if hasattr(finals, "block_until_ready"):
                # Fence so the adam_segments trace span measures the
                # scan itself, not jax's async dispatch returning
                # early (the arrays are materialized a few lines
                # down anyway).
                finals.block_until_ready()
            t_scan_wall = time.time()
            # Finalize: one batched evaluation ranks/validates every
            # row (the ensemble driver's convention — final loss is
            # not in the scan's return).
            key = init_randkey(config.randkey) if config.with_key \
                else jnp.zeros(())
            losses, _ = self.model.batched_loss_and_grad_fn(
                config.with_key, k_sharded=use_sharded)(
                finals, self._dynamic, key)
            fit_s = time.perf_counter() - t0
        finally:
            if self.resources is not None:
                self.resources.dispatch_exit()

        finals_np = np.asarray(finals)
        losses_np = np.asarray(losses)
        traj_np = np.asarray(traj)
        poisoned = nonfinite_rows(finals_np, losses_np)
        done_t = time.time()
        t_fit_wall = done_t
        # Dispatch-level counters land BEFORE any future resolves: a
        # caller that wakes on the last result and reads .stats must
        # see the dispatch that produced it (bench_serve snapshots
        # exactly that way).
        self._count("dispatches")
        with self._lock:
            self._bucket_dispatches[bucket] += 1
            self._stats["rows_total"] += bucket
            self._stats["rows_padded"] += bucket - n
        for i, req in enumerate(live):
            self._trace_dispatch_hops(
                req, coalesce_open_t, t_claim, t_scan_wall,
                t_fit_wall, bucket, n, compiled)
            if poisoned[i]:
                self._resolve_poisoned(req, i, bucket, finals_np[i],
                                       losses_np[i])
                continue
            hops = {
                "queue_wait": round(
                    max(0.0, t_claim - req.submitted_t), 6),
                "bucket_coalesce": round(max(0.0, t_claim - max(
                    coalesce_open_t, req.submitted_t)), 6),
                "dispatch": round(t_fit_wall - t_claim, 6),
                "adam_segments": round(t_scan_wall - t_claim, 6),
                "finalize": round(t_fit_wall - t_scan_wall, 6),
            }
            # .copy(): a row slice is a VIEW pinning the whole
            # (nsteps+1, K, ndim) bucket trajectory — one retained
            # result must not hold K rows of memory in a
            # long-running service.
            result = FitResult(
                request_id=req.id, params=finals_np[i].copy(),
                loss=float(losses_np[i]),
                traj=traj_np[:, i, :].copy(),
                steps=config.nsteps, bucket=bucket,
                wait_s=round(now - req.submitted_t, 6),
                fit_s=round(fit_s, 6), retried=req.retried,
                trace_id=(req.trace.trace_id if req.trace is not None
                          else None),
                hops=hops, job_id=config.job_id, stage=config.stage)
            # Counters, trace spans, and latency observations all
            # land BEFORE the future resolves: a caller that wakes
            # on result() and immediately reads .stats, /status, or
            # the trace files must see a fully-accounted request.
            t_set = time.time()
            if self.tracer is not None and req.trace is not None:
                self.tracer.record(req.trace.child(),
                                   "result_return", t_fit_wall,
                                   t_set)
            self._trace_root(req, "ok", t_set)
            self._latency.observe(t_set - req.submitted_t, hops,
                                  result.trace_id)
            if self.slo is not None:
                tag = request_tag(req)
                self.slo.observe(tag.priority_class, tag.tenant,
                                 t_set - req.submitted_t,
                                 trace_id=result.trace_id)
            if self.rollup is not None:
                self._note_history(req, hops["queue_wait"],
                                   fit_s / n, t_set)
            self._fits_counter("ok")
            with self._lock:
                self._stats["completed"] += 1
                self._last_completed_t = done_t
            req.future._set_result(result)
            if self.telemetry is not None:
                self.telemetry.log(
                    "fit_summary", request=req.id,
                    steps=config.nsteps,
                    final_loss=float(losses_np[i]), bucket=bucket,
                    occupancy=round(n / bucket, 4),
                    wait_s=result.wait_s, fit_s=result.fit_s,
                    retried=req.retried, serve=True,
                    trace_id=result.trace_id, hops=hops,
                    job_id=config.job_id, stage=config.stage,
                    **({"tenant": req.qos.tenant,
                        "priority_class": req.qos.priority_class}
                       if req.qos is not None else {}))

        if self.telemetry is not None:
            self.telemetry.log(
                "serve_dispatch", bucket=bucket, n_requests=n,
                occupancy=round(n / bucket, 4),
                fit_s=round(fit_s, 6),
                poisoned=int(np.sum(poisoned[:n])))
        self._memory_truth(config, ndim, bucket, use_sharded)
        self._refresh_gauges(bucket, n)

    def _resource_ring(self):
        """The monitor's sample ring for postmortem bundles, with
        one fresh sample so the bundle carries "now" (``None`` when
        monitoring is off — the key stays a null in the bundle,
        distinguishing "unmonitored" from "no samples yet")."""
        if self.resources is None:
            return None
        self.resources.sample()          # never raises
        return self.resources.ring()

    def _memory_truth(self, config, ndim: int, bucket: int,
                      use_sharded: bool):
        """Per-dispatch memory-truth record: measured device peak
        (``memory_stats`` high-water, ``None`` on backends that
        cannot measure — the regress gate treats nulls as warn-only)
        cross-checked against the PR-14 memory model for the layout
        that just ran.  Never raises — a probe failure costs the
        record, not the dispatch."""
        if self.telemetry is None and self._metrics is None:
            return
        try:
            from ..inference.ensemble import ensemble_memory_model
            from ..telemetry.resources import (device_memory,
                                               measured_vs_modeled,
                                               read_rss_bytes)
            n_replicas = self._k_replicas if use_sharded else 1
            modeled = ensemble_memory_model(
                bucket, ndim, int(config.nsteps),
                n_replicas=n_replicas)
            mvm = measured_vs_modeled(
                device_memory()["peak_bytes"], modeled)
            if self.telemetry is not None:
                self.telemetry.log(
                    "measured_vs_modeled", bucket=bucket, ndim=ndim,
                    nsteps=int(config.nsteps),
                    sharded=bool(use_sharded),
                    n_replicas=n_replicas,
                    rss_bytes=read_rss_bytes(), **mvm)
            if self._metrics is not None \
                    and mvm["accuracy_frac"] is not None:
                self._metrics.set(
                    "multigrad_resource_memory_model_accuracy_frac",
                    mvm["accuracy_frac"],
                    help="1 - |measured peak - modeled| / modeled "
                         "for the last bucket dispatch")
        except Exception:
            pass

    def _resolve_poisoned(self, req, row, bucket, params, loss):
        bundle = request_postmortem(self._recorder, req, row, bucket,
                                    params, loss,
                                    resources=self._resource_ring())
        if self.telemetry is not None:
            self.telemetry.log(
                "fit_summary", request=req.id,
                steps=req.config.nsteps, final_loss=None,
                bucket=bucket, retried=req.retried,
                postmortem_bundle=bundle, serve=True,
                trace_id=(req.trace.trace_id
                          if req.trace is not None else None))
        if self.retry_poisoned and not req.retried:
            req.retried = True
            req.future._requeued()
            if self.on_poison_retry is not None:
                try:
                    self.on_poison_retry(req)
                except Exception:
                    pass
            try:
                # Head of the queue, capacity bypassed (`force`: the
                # request was already admitted once — a full queue
                # must not silently eat the promised retry): the
                # fresh bucket runs before newer work.
                self.queue.submit(req, front=True, force=True)
                self._count("retried")
                return
            except RuntimeError:
                pass        # closed mid-drain: fall through to fail
        # Failure is navigable from either end: the bundle carries
        # the trace id (request_postmortem), the trace's root span
        # carries the bundle path — recorded BEFORE the future
        # resolves, so the woken caller's triage sees a rooted trace.
        self._trace_root(req, "failed", bundle=bundle)
        self._count("failed")
        self._fits_counter("failed")
        req.future._set_exception(FitFailed(
            "fit produced non-finite parameters or loss", req.id,
            bundle_path=bundle))

    # ------------------------------------------------------------------ #
    # observability
    # ------------------------------------------------------------------ #
    def _trace_dispatch_hops(self, req, coalesce_open_t, t_claim,
                             t_scan_wall, t_fit_wall, bucket, n,
                             compiled):
        """One set of hop spans for a request that rode a dispatch:
        queue_wait / bucket_coalesce parent to the request root;
        adam_segments and finalize nest under dispatch.  Recorded
        for poisoned rows too — a poisoned request's waterfall shows
        BOTH its attempts."""
        tracer, ctx = self.tracer, req.trace
        if tracer is None or ctx is None:
            return
        tracer.record(ctx.child(), "queue_wait",
                      min(req.submitted_t, t_claim), t_claim)
        tracer.record(ctx.child(), "bucket_coalesce",
                      min(max(coalesce_open_t, req.submitted_t),
                          t_claim),
                      t_claim, bucket=bucket, n_requests=n)
        dispatch_ctx = ctx.child()
        tracer.record(dispatch_ctx, "dispatch", t_claim, t_fit_wall,
                      bucket=bucket, n_requests=n,
                      compiled=compiled)
        tracer.record(dispatch_ctx.child(), "adam_segments",
                      t_claim, t_scan_wall,
                      nsteps=req.config.nsteps)
        tracer.record(dispatch_ctx.child(), "finalize",
                      t_scan_wall, t_fit_wall)

    def _trace_root(self, req, outcome: str, t_end=None, **attrs):
        """Close a trace this scheduler minted (single-process
        serving) with its root `request` span.  Fleet-relayed
        requests (``owns_trace=False``) keep their root on the
        router, which sees the true end-to-end settle."""
        if (self.tracer is None or req.trace is None
                or not req.owns_trace):
            return
        if req.config.job_id is not None:
            attrs.setdefault("job_id", req.config.job_id)
        if req.config.stage is not None:
            attrs.setdefault("stage", req.config.stage)
        self.tracer.record(req.trace, "request", req.submitted_t,
                           t_end, outcome=outcome, request=req.id,
                           **attrs)

    def _count(self, key: str):
        with self._lock:
            self._stats[key] += 1

    def _gauge(self, name, value, help=None, labels=None):
        if self._metrics is not None:
            self._metrics.set(name, float(value), help=help,
                              labels=labels)

    def _fits_counter(self, outcome: str):
        if self._metrics is not None:
            self._metrics.inc("multigrad_serve_fits_total",
                              help="served fit requests, by outcome",
                              labels={"outcome": outcome})

    def fits_per_hour(self) -> Optional[float]:
        """Served-fit throughput: completions per hour over the span
        from the first submission to the latest completion (None
        until the first fit lands)."""
        with self._lock:
            n = self._stats["completed"]
            if (not n or self._first_submit_t is None
                    or self._last_completed_t is None):
                return None
            span = self._last_completed_t - self._first_submit_t
        if span <= 0:
            return None
        return n / span * 3600.0

    def _refresh_gauges(self, bucket, n):
        if self._metrics is None:
            return
        self._gauge("multigrad_serve_queue_depth", len(self.queue),
                    help="fit requests waiting for a bucket")
        self._gauge("multigrad_serve_occupancy", n / bucket,
                    help="valid rows / bucket rows of the last "
                         "dispatch")
        self._metrics.inc("multigrad_serve_dispatches_total",
                          help="bucket dispatches, by bucket size",
                          labels={"bucket": str(bucket)})
        self._metrics.inc("multigrad_serve_padded_rows_total",
                          float(bucket - n),
                          help="bucket rows filled by padding")
        rate = self.fits_per_hour()
        if rate is not None:
            self._gauge("multigrad_serve_fits_per_hour", rate,
                        help="trailing served-fit rate")

    @property
    def stats(self) -> dict:
        """Counters snapshot: submitted / completed / failed /
        expired / cancelled / retried / shed / dispatches /
        rows_total / rows_padded, plus per-bucket dispatch counts,
        the trailing fits/hour, and (with QoS on) the class-aware
        shed counters."""
        with self._lock:
            out = dict(self._stats)
            out["bucket_dispatches"] = dict(self._bucket_dispatches)
        out["fits_per_hour"] = self.fits_per_hour()
        out["queue_depth"] = len(self.queue)
        if self.qos is not None:
            out["qos_shed"] = self.queue.qos_counts()
        return out
