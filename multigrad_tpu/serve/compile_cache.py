"""Persistent compilation cache + bucket-program warmup.

Two halves of the "a fresh process serves its first fit without
paying compile" story:

* :func:`enable_compile_cache` wires jax's **persistent on-disk XLA
  compilation cache** (``jax_compilation_cache_dir``): every program
  the serving process compiles is written to disk, and any later
  process that compiles the same program reads the binary back
  instead of re-running XLA.  The thresholds are dropped to zero so
  even the small CPU-mesh programs of a test/CI deployment persist
  (jax's defaults skip sub-second compiles — exactly the ones a
  serving smoke test needs cached).

* :func:`warmup_buckets` **pre-traces and pre-compiles the bucket
  programs** — for each ``(FitConfig, bucket K)`` pair, the batched
  ``(K, ndim)`` Adam segment scan plus the batched final-loss program
  the scheduler's finalize step runs — through jax's AOT path
  (``jit(...).lower(...).compile()``), with the REAL aux arrays as
  lowering arguments so shardings and layouts match the live
  dispatch exactly.  Nothing executes: lowering is trace-only, and
  the compile lands in the persistent cache, so a warmed deployment
  directory serves its first real fit with a cache read instead of
  an XLA compile (measured in this repo's CI: ~5x faster first
  dispatch on the CPU mesh).

Typical service start::

    from multigrad_tpu.serve import (FitScheduler, FitConfig,
                                     enable_compile_cache)

    enable_compile_cache("/var/cache/multigrad_jax")   # process-wide
    sched = FitScheduler(model)
    sched.warmup(FitConfig(nsteps=500), ndim=2)        # pre-trace
    ...serve...
"""
from __future__ import annotations

import os
import sys
import time
from typing import Optional

import jax
import jax.numpy as jnp
import optax

from .queue import FitConfig

__all__ = ["enable_compile_cache", "cache_entries", "warmup_buckets",
           "DEFAULT_BUCKETS"]

#: Quantized batch sizes the scheduler packs requests into.  The
#: whole point of quantization: compiled-program variants (and so
#: retraces) are bounded by ``len(DEFAULT_BUCKETS)`` per fit config,
#: not by the number of requests served.
DEFAULT_BUCKETS = (1, 4, 16, 64)


def enable_compile_cache(cache_dir: Optional[str] = None,
                         min_compile_time_s: float = 0.0
                         ) -> Optional[str]:
    """Turn on jax's persistent on-disk compilation cache.

    Parameters
    ----------
    cache_dir : str, optional
        Where compiled executables land (created by jax on first
        write).  Default: ``$TMPDIR/multigrad_tpu_jax_cache`` — a
        stable per-machine location, so repeated service starts warm
        each other.
    min_compile_time_s : float
        jax's persistence threshold (default here 0.0 — persist
        everything; jax's own default of ~1 s would skip the small
        CPU-mesh programs entirely).

    Returns the cache dir, or ``None`` when the installed jax
    predates the config flags (the serving layer then simply runs
    without persistence — a capability knob, never a hard
    dependency).
    """
    if cache_dir is None:
        import tempfile
        cache_dir = os.path.join(tempfile.gettempdir(),
                                 "multigrad_tpu_jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_s))
    except Exception as e:          # older jax: no such flags
        print(f"persistent compilation cache unavailable: {e}",
              file=sys.stderr)
        return None
    try:
        # Persist small executables too (flag exists on jax >= 0.4.30
        # lineages; absence only re-raises jax's own size threshold).
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          -1)
    except Exception:
        pass
    try:
        # jax initializes the cache object lazily at the FIRST
        # compile and never re-reads the dir config afterwards — a
        # process that compiled anything before this call would
        # silently keep running uncached.  Reset so the next compile
        # re-initializes against the configured dir.
        from jax._src import compilation_cache
        compilation_cache.reset_cache()
    except Exception:
        pass
    return cache_dir


def cache_entries(cache_dir: Optional[str] = None) -> int:
    """Number of executables in the persistent cache (0 when the dir
    does not exist yet).  Default: the currently configured dir."""
    if cache_dir is None:
        cache_dir = getattr(jax.config, "jax_compilation_cache_dir",
                            None)
    if not cache_dir or not os.path.isdir(cache_dir):
        return 0
    return len(os.listdir(cache_dir))


def _config_ndim(config: FitConfig, ndim: Optional[int]) -> int:
    if config.param_bounds is not None:
        return len(config.param_bounds)
    if ndim is None:
        raise ValueError(
            "warmup of an unbounded FitConfig needs ndim= (bounded "
            "configs derive it from their bounds)")
    return int(ndim)


def warmup_buckets(model, configs, buckets=DEFAULT_BUCKETS,
                   ndim: Optional[int] = None,
                   donate_carry=None, k_sharded: bool = False) -> list:
    """AOT-compile every ``(config, bucket)`` program pair.

    For each :class:`~multigrad_tpu.serve.queue.FitConfig` and each
    bucket size K: lower and compile (1) the batched ``(K, ndim)``
    Adam segment scan — the very program :func:`~multigrad_tpu.optim
    .adam.run_adam_scan` will build for a bucket dispatch, obtained
    through the same :func:`~multigrad_tpu.optim.adam
    .adam_fit_program` hook the analyzer uses, so the cache can never
    warm a *different* program than the one that serves — and (2) the
    model's batched final-loss program (the scheduler's finalize
    step).  Trace-only: no fit executes, and with
    :func:`enable_compile_cache` active every compile persists to
    disk for future processes.

    Returns one ``{"nsteps", "learning_rate", "bucket",
    "compile_s"}`` entry per pair (the service's startup log).  With
    ``k_sharded=True`` the warmed programs are the K-partitioned
    variants of the sharded-K dispatch path, for every bucket the
    replica count divides (indivisible rungs — K=1 — warm the
    replicated program, matching the scheduler's dispatch rule).
    """
    from ..inference.ensemble import (batched_fit_wrapper,
                                      k_shards_bucket)
    from ..optim.adam import adam_fit_program, init_randkey
    from ..optim.transforms import bounds_to_arrays

    if isinstance(configs, FitConfig):
        configs = [configs]
    dynamic = model.aux_leaves()
    n_replicas = model.k_shard_replicas if k_sharded else 1
    entries = []
    for config in configs:
        nd = _config_ndim(config, ndim)
        low, high = bounds_to_arrays(config.bounds_list(), nd)
        key0 = init_randkey(config.randkey) if config.with_key \
            else jax.random.key(0)
        eval_key = key0 if config.with_key else jnp.zeros(())
        for bucket in sorted(set(int(b) for b in buckets)):
            sharded = k_shards_bucket(bucket, k_sharded, n_replicas)
            wrapper = batched_fit_wrapper(model, config.with_key,
                                          k_sharded=sharded)
            loss_program = model.batched_loss_and_grad_fn(
                config.with_key, k_sharded=sharded)
            t0 = time.perf_counter()
            zeros = jnp.zeros((bucket, nd), jnp.result_type(float))
            carry_sharding = None
            if sharded:
                # Concrete K-partitioned carries as lowering args so
                # the warmed executable's layout matches the live
                # sharded dispatch exactly.
                carry_sharding = model.k_sharding(2)
                zeros = jax.device_put(zeros, carry_sharding)
            u = zeros
            opt_state = optax.adam(config.learning_rate).init(zeros)
            fit = adam_fit_program(
                wrapper, config.nsteps,
                learning_rate=config.learning_rate,
                with_key=config.with_key,
                const_randkey=config.const_randkey,
                bounded=config.bounded, donate_carry=donate_carry,
                carry_sharding=carry_sharding)
            # The real (possibly sharded) aux leaves as lowering
            # arguments: layouts/shardings in the compiled executable
            # match the live dispatch, so the persistent-cache entry
            # written here is the one a serving process reads.
            fit.lower(u, opt_state, key0, low, high,
                      (dynamic,)).compile()
            loss_program.lower(u, dynamic, eval_key).compile()
            entries.append({
                "nsteps": config.nsteps,
                "learning_rate": config.learning_rate,
                "bucket": bucket,
                "k_sharded": sharded,
                "compile_s": round(time.perf_counter() - t0, 4),
            })
    return entries
