"""Fit-fleet serving layer: multi-tenant batched fits as a service.

The paper's core identity makes the *marginal* cost of one more fit
tiny — sumstats and gradients cost O(|sumstats| + |params|) in
communication regardless of catalog size — and the batched
``(K, ndim)`` ensemble kernel already runs K independent fits as one
program.  This package puts a scheduler in front of that kernel and
turns the repo's hand-driven fits into sustained throughput:

* :mod:`.queue` — the tenant surface: :class:`FitConfig` +
  :meth:`FitScheduler.submit` → :class:`FitFuture` (await / poll /
  cancel), with admission control and bounded backpressure
  (:class:`QueueFullError`).
* :mod:`.scheduler` — :class:`FitScheduler`: a dispatcher thread
  pad-and-packs compatible requests into quantized ``(K, ndim)``
  buckets (default ``K ∈ {1, 4, 16, 64}``) dispatched through the
  batched Adam scan, so compiled-program retraces are bounded by the
  bucket count, not the request count; finalize splits the batched
  carry back into per-request results (bitwise identical to solo
  fits).
* :mod:`.compile_cache` — persistent on-disk XLA compilation cache
  wiring (:func:`enable_compile_cache`) plus bucket-program warmup
  (:func:`warmup_buckets`): a fresh process serves its first fit
  without paying compile.
* :mod:`.robustness` — per-request fault isolation: a NaN/Inf in one
  tenant's fit is contained to its own batch row; the poisoned
  request alone gets a flight-recorder postmortem bundle and an
  errored future (:class:`FitFailed`), with one retry on a fresh
  bucket; deadline timeouts (:class:`FitDeadlineExceeded`) and
  graceful drain on shutdown.
* :mod:`.fleet` + :mod:`.worker` + :mod:`.wire` — the horizontal
  dimension: :class:`FleetRouter` shards config traffic across N
  worker *processes* (``python -m multigrad_tpu.serve.worker``) with
  config-affinity routing over the shared on-disk compile cache,
  heartbeat health tracking, load shedding / work stealing
  (:class:`FleetSaturatedError`), and preemption-resilient draining —
  a killed worker's in-flight requests re-enqueue on survivors
  (requeue history on the future; :class:`WorkerLostError` when the
  fleet truly cannot finish one).
* :mod:`.chaos` — :class:`ChaosController`: SIGKILL / SIGTERM /
  SIGSTOP, forced queue-full, stalls — injected at configurable
  points, proving "every future resolves" under fire.
* :mod:`.qos` + :mod:`.slo` — the multi-tenant scheduling dimension:
  a :class:`QosTag` (tenant, priority class, optional SLO deadline)
  rides each request — deliberately NOT part of the batchability
  key, so same-config fits from different tenants still co-batch —
  and a :class:`QosPolicy` turns FIFO dequeue into weighted-fair
  (deficit round-robin over tenants), makes shedding class-aware
  (:class:`FitShedError`, :class:`TenantQuotaError`), and packs
  buckets deadline-first (EDF).  :class:`SloMonitor` states latency
  objectives declaratively (``"p95 < 2 s for interactive"``),
  evaluates them live, and exports ``multigrad_qos_*`` gauges.
* :mod:`.jobs` + :mod:`.stages` — the pipeline dimension:
  :class:`JobRunner` runs a whole posterior pipeline submitted as
  ONE :class:`Job` — a typed DAG of stages (sweep → ensemble →
  Laplace → HMC → predictive checks) — fanning fit-type stages out
  through the scheduler/fleet, running host-side inference stages
  locally, flowing small JSON artifacts between stages, tracing the
  whole job as one waterfall, and checkpointing at stage boundaries
  so a lost worker costs a stage, not the job.

Minimal service::

    from multigrad_tpu.serve import FitScheduler, enable_compile_cache

    enable_compile_cache()                   # warm across processes
    with FitScheduler(model) as sched:
        futs = [sched.submit(g, nsteps=500, param_bounds=bounds)
                for g in guesses]
        results = [f.result() for f in futs]     # FitResult each

Scheduler gauges (queue depth, bucket occupancy, fits/hour) land in
the :class:`~multigrad_tpu.telemetry.LiveServer` ``/metrics``
endpoint via ``live=``, and every served request closes with a
``fit_summary`` telemetry record via ``telemetry=``.
"""
from .queue import (FitCancelled, FitConfig,  # noqa: F401
                    FitDeadlineExceeded, FitFailed, FitFuture,
                    FitOOMError, FitQueue, FitRequest, FitResult,
                    QueueFullError)
from .compile_cache import (DEFAULT_BUCKETS,  # noqa: F401
                            cache_entries, enable_compile_cache,
                            warmup_buckets)
from .qos import (FitShedError, QosPolicy, QosTag,  # noqa: F401
                  TenantQuotaError)
from .slo import Slo, SloMonitor, parse_slo  # noqa: F401
from .scheduler import FitScheduler  # noqa: F401
from .robustness import nonfinite_rows  # noqa: F401
from .fleet import (FleetRouter, FleetSaturatedError,  # noqa: F401
                    WorkerHandle, WorkerLostError)
from .chaos import ChaosController  # noqa: F401
from .stages import (EnsembleStage, FitStage, HmcStage,  # noqa: F401
                     LaplaceStage, PredictiveCheckStage, Stage,
                     StageRuntime, SweepStage)
from .jobs import (Job, JobFailed, JobFuture, JobResult,  # noqa: F401
                   JobRunner, StageResult)

__all__ = [
    "FitScheduler", "FitConfig", "FitRequest", "FitFuture",
    "FitResult", "FitQueue", "QueueFullError", "FitCancelled",
    "FitDeadlineExceeded", "FitFailed", "FitOOMError",
    "enable_compile_cache", "cache_entries", "warmup_buckets",
    "DEFAULT_BUCKETS", "nonfinite_rows",
    "FleetRouter", "WorkerHandle", "WorkerLostError",
    "FleetSaturatedError", "ChaosController",
    "QosTag", "QosPolicy", "TenantQuotaError", "FitShedError",
    "Slo", "SloMonitor", "parse_slo",
    "Job", "JobRunner", "JobFuture", "JobResult", "JobFailed",
    "StageResult", "Stage", "StageRuntime", "FitStage",
    "SweepStage", "EnsembleStage", "LaplaceStage", "HmcStage",
    "PredictiveCheckStage",
]
