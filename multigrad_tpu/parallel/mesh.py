"""TPU-native communicator abstraction.

The reference library (``multigrad``) scopes every collective to an
``mpi4py`` communicator (``/root/reference/multigrad/multigrad.py:149-183``)
and builds sub-communicators with ``comm.Split``
(``multigrad.py:88-146``).  On TPU the analog of a communicator is a
**named axis of a `jax.sharding.Mesh`**: a set of devices plus a name
that in-graph collectives (``lax.psum`` et al.) reduce over.

:class:`MeshComm` wraps exactly that.  It intentionally mirrors the
mpi4py surface the reference uses (``size``, ``rank``-free SPMD,
sub-communicator splitting) while being a thin, hashable, static
object that can be closed over by jitted programs.

Key differences from MPI, by design (single-controller JAX):

* There is no per-rank Python process; one controller drives all
  devices.  "Rank-local" code lives *inside* ``shard_map`` blocks.
* ``split_subcomms`` therefore returns **all** sub-communicators to
  every caller (each wraps a disjoint device subset), rather than
  one subcomm per rank.  In multi-host mode, ``my_group`` identifies
  the group whose devices are attached to this host.
* ``split_subcomms_by_node`` groups devices by their physical host
  (``device.process_index``) — the ICI/DCN analog of grouping MPI
  ranks by node name (``multigrad.py:48-85``).
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..telemetry.comm import record_collective as _record_collective


def _flat_devices(devices) -> list:
    return list(np.asarray(devices).ravel())


class MeshComm:
    """A communicator backed by a one-axis :class:`jax.sharding.Mesh`.

    Parameters
    ----------
    devices : sequence of jax devices, optional
        Devices in this communicator (default: ``jax.devices()``).
    axis_name : str
        Name of the mesh axis collectives reduce over.
    name : str
        Human-readable communicator name (mirrors ``comm.Set_name``,
        reference ``multigrad.py:81-82``).
    """

    def __init__(self, devices=None, axis_name: str = "shards",
                 name: str = "WORLD", _mesh: Optional[Mesh] = None):
        if _mesh is not None:
            self.mesh = _mesh
            self.axis_name = (axis_name if isinstance(axis_name, str)
                              else tuple(axis_name))
            # One device per shard: slice index 0 of any mesh axis the
            # comm does NOT reduce over (for a full-axes comm this is
            # every device).  Keeps the devices/size/__len__ contract —
            # len(devices) == size always.
            index = tuple(slice(None) if a in self.axes else 0
                          for a in _mesh.axis_names)
            self._devices = tuple(_mesh.devices[index].ravel())
        else:
            if devices is None:
                devices = jax.devices()
            devices = _flat_devices(devices)
            self._devices = tuple(devices)
            self.axis_name = axis_name
            self.mesh = Mesh(np.asarray(devices), (axis_name,))
        self.name = name

    @classmethod
    def from_mesh(cls, mesh: Mesh, axes=None,
                  name: str = "WORLD") -> "MeshComm":
        """Communicator over named axes of an *existing* multi-axis mesh.

        The hierarchical (ICI/DCN) story — the TPU analog of the
        reference's ``split_subcomms_by_node`` (``multigrad.py:48-85``):
        wrap a :func:`hybrid_mesh`'s both axes and the model's psums
        reduce over ``("hosts", "data")`` as one collective, which XLA
        lowers hierarchically — on-chip interconnect inside a host
        group first, DCN across host groups second.

        Parameters
        ----------
        mesh : jax.sharding.Mesh
            Any mesh (e.g. from :func:`hybrid_mesh`).
        axes : str | sequence[str], optional
            The mesh axis name(s) this communicator reduces over, in
            mesh-major order.  Default: all of ``mesh.axis_names``.
        """
        if axes is None:
            axes = tuple(mesh.axis_names)
        elif isinstance(axes, str):
            axes = (axes,)
        else:
            axes = tuple(axes)
        for a in axes:
            if a not in mesh.axis_names:
                raise ValueError(
                    f"axis {a!r} not in mesh axes {mesh.axis_names}")
        if axes != tuple(a for a in mesh.axis_names if a in axes):
            raise ValueError(
                f"axes {axes} must be in mesh-major order "
                f"{mesh.axis_names} (sharding specs, axis_index, and "
                "the device ordering all follow the mesh layout)")
        axis_name = axes[0] if len(axes) == 1 else axes
        return cls(axis_name=axis_name, name=name, _mesh=mesh)

    # -- MPI-like properties -------------------------------------------------
    @property
    def axes(self) -> tuple:
        """The comm's mesh axis names, always as a tuple."""
        return (self.axis_name,) if isinstance(self.axis_name, str) \
            else self.axis_name

    @property
    def free_axes(self) -> tuple:
        """Mesh axes this comm does NOT reduce over (mesh-major).

        Empty for ordinary one-axis comms and for :func:`hybrid_comm`
        (which reduces over both of its axes).  Non-empty exactly for
        2-level layouts like :func:`ensemble_comm`, where the free
        axis is the ensemble's replica (K-sharding) axis: data-axis
        collectives stay within a replica slice, and anything sharded
        over a free axis — ensemble members, their Adam moments, HMC
        chains — is partitioned ZeRO-style instead of replicated.
        """
        return tuple(a for a in self.mesh.axis_names
                     if a not in self.axes)

    @property
    def size(self) -> int:
        return len(self._devices)

    @property
    def devices(self):
        return self._devices

    def __len__(self) -> int:
        return self.size

    def __repr__(self) -> str:
        return (f"MeshComm(name={self.name!r}, size={self.size}, "
                f"axis={self.axis_name!r})")

    # Static/hashable so models closing over a comm stay jit-friendly.
    # (The reference needed custom __hash__/__eq__ on the *model* for
    # this, multigrad.py:540-544; here the comm itself is the static.)
    def __hash__(self):
        # name is display-only and excluded from __eq__, so it must
        # not enter the hash (hash/eq contract).
        return hash((self._devices, tuple(self.mesh.axis_names),
                     self.axis_name))

    def __eq__(self, other):
        return (isinstance(other, MeshComm)
                and self._devices == other._devices
                and tuple(self.mesh.axis_names) ==
                tuple(other.mesh.axis_names)
                and self.axis_name == other.axis_name)

    # -- sharding helpers ----------------------------------------------------
    def sharding(self, axis: int = 0, ndim: Optional[int] = None
                 ) -> NamedSharding:
        """NamedSharding that shards dimension `axis` over this comm."""
        if ndim is None:
            ndim = axis + 1
        spec = [None] * ndim
        spec[axis] = self.axis_name
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    # -- in-graph collectives (valid inside shard_map over this comm) --------
    # Each reports its payload to any active telemetry CommCounter at
    # trace time (multigrad_tpu.telemetry.comm) before lowering to the
    # lax primitive.
    def psum(self, value):
        _record_collective("psum", value)
        return jax.lax.psum(value, self.axis_name)

    def pmean(self, value):
        _record_collective("pmean", value)
        return jax.lax.pmean(value, self.axis_name)

    def pmax(self, value):
        _record_collective("pmax", value)
        return jax.lax.pmax(value, self.axis_name)

    def pmin(self, value):
        _record_collective("pmin", value)
        return jax.lax.pmin(value, self.axis_name)

    def all_gather(self, value, axis: int = 0, tiled: bool = True):
        _record_collective("all_gather", value)
        return jax.lax.all_gather(value, self.axis_name, axis=axis,
                                  tiled=tiled)

    def axis_index(self):
        """Linearized index of this device among the comm's shards
        (mesh-major over multi-axis comms)."""
        axes = self.axes
        idx = jax.lax.axis_index(axes[0])
        for a in axes[1:]:
            idx = idx * self.mesh.shape[a] + jax.lax.axis_index(a)
        return idx


def global_comm(axis_name: str = "shards") -> MeshComm:
    """Communicator over every addressable device (MPI.COMM_WORLD analog)."""
    return MeshComm(jax.devices(), axis_name=axis_name, name="WORLD")


def split_subcomms(num_groups: Optional[int] = None,
                   ranks_per_group: Optional[Sequence[int]] = None,
                   comm: Optional[MeshComm] = None):
    """Split a communicator's devices into disjoint sub-communicators.

    TPU-native port of ``multigrad.split_subcomms``
    (``/root/reference/multigrad/multigrad.py:88-146``): either
    ``num_groups`` evenly-sized groups or explicit ``ranks_per_group``
    sizes ("ranks" = devices here).

    Returns
    -------
    subcomms : tuple[MeshComm]
        One sub-communicator per group (all returned, since a single
        controller owns every device — see module docstring).
    num_groups : int
    my_group : int
        Index of the group containing this *process*'s local devices
        (0 in single-host mode).
    """
    if comm is None:
        comm = global_comm()
    # Explicit raises (not asserts): this is user-facing argument
    # validation and must survive `python -O`.
    main_msg = "Specify either num_groups OR ranks_per_group"
    if num_groups is not None:
        if ranks_per_group is not None:
            raise ValueError(main_msg)
        if comm.size < num_groups:
            raise ValueError(
                "Cannot create more subcomms than there are devices: "
                f"num_groups={num_groups} > comm.size={comm.size}")
        num_groups = int(num_groups)
        # Same grouping rule as the reference (multigrad.py:119-128):
        # a (num_groups, ceil(size/num_groups)) label grid is raveled
        # and re-split into `size` chunks with np.array_split; each
        # rank takes its chunk's first label.  This guarantees every
        # group is non-empty when size % num_groups != 0 (e.g. 8
        # devices, 5 groups -> sizes [1, 1, 2, 2, 2]).
        grid = (np.ones(math.ceil(comm.size / num_groups))[None, :]
                * np.arange(num_groups)[:, None])[:comm.size]
        raveled = grid.ravel().astype(int)
        labels = np.array([chunk[0] for chunk in
                           np.array_split(raveled, comm.size)])
    else:
        if ranks_per_group is None:
            raise ValueError(main_msg)
        if sum(ranks_per_group) != comm.size:
            raise ValueError(
                "The sum of ranks_per_group must equal comm.size: "
                f"sum({list(ranks_per_group)}) != {comm.size}")
        num_groups = len(ranks_per_group)
        labels = np.repeat(np.arange(num_groups), ranks_per_group)

    subcomms = []
    devices = np.asarray(comm.devices)
    # Sub-communicators are always one-axis meshes over their device
    # group; a multi-axis parent contributes its innermost (ICI) axis
    # name.
    sub_axis = comm.axes[-1]
    for g in range(num_groups):
        sub_devices = devices[labels == g]
        subcomms.append(MeshComm(
            sub_devices, axis_name=sub_axis,
            name=f"{comm.name}.{g}".replace("WORLD.", "")))

    my_group = 0
    pid = jax.process_index()
    for g, sc in enumerate(subcomms):
        if any(d.process_index == pid for d in sc.devices):
            my_group = g
            break
    return tuple(subcomms), num_groups, my_group


def split_subcomms_by_node(comm: Optional[MeshComm] = None):
    """Split a communicator into one sub-communicator per physical host.

    Port of ``multigrad.split_subcomms_by_node``
    (``/root/reference/multigrad/multigrad.py:48-85``), which groups
    MPI ranks by node name.  Here devices are grouped by
    ``device.process_index`` — devices of one host share ICI-adjacent
    mesh positions while cross-host traffic rides DCN, so this split
    is the natural "fast axis inside, slow axis outside" topology
    (cf. ``mesh_utils.create_hybrid_device_mesh``).
    """
    if comm is None:
        comm = global_comm()
    pids = sorted({d.process_index for d in comm.devices})
    subcomms = []
    for pid in pids:
        sub = [d for d in comm.devices if d.process_index == pid]
        subcomms.append(MeshComm(
            sub, axis_name=comm.axes[-1],
            name=f"{comm.name}.{pid}".replace("WORLD.", "")))
    my_group = pids.index(jax.process_index()) \
        if jax.process_index() in pids else 0
    return tuple(subcomms), len(pids), my_group


def ensemble_mesh(n_replicas: int, data_axis: str = "data",
                  replica_axis: str = "replica", devices=None) -> Mesh:
    """Two-level ``(replica, data)`` mesh for sharded-K ensembles.

    Splits the device grid into ``n_replicas`` replica slices of
    ``n_devices / n_replicas`` devices each.  The *data* axis is the
    halo-shard axis models psum over (as today); the *replica* axis
    carries the ensemble's K batch axis — each replica slice owns
    ``K / n_replicas`` members, their trajectories and their Adam
    moments, so device memory stops bounding ensemble width (the
    ZeRO-style partitioning of the weight-update-sharding paper,
    composed with the 2-level fast/slow-axis topology of the MPMD
    pipeline-parallelism paper: nothing crosses the replica axis
    during a fit — members are independent — so the replica axis can
    be the slow link).

    The replica axis is OUTERMOST: on a multi-host pod the hybrid
    device order puts DCN-adjacent devices on the outer axis, which
    is exactly where the traffic-free replica axis belongs.
    """
    if devices is None:
        devices = jax.devices()
    devices = _flat_devices(devices)
    n_replicas = int(n_replicas)
    if n_replicas < 1:
        raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
    if len(devices) % n_replicas != 0:
        raise ValueError(
            f"n_replicas={n_replicas} must divide the device count "
            f"({len(devices)})")
    grid = np.asarray(devices).reshape(
        n_replicas, len(devices) // n_replicas)
    return Mesh(grid, (replica_axis, data_axis))


def ensemble_comm(n_replicas: int, data_axis: str = "data",
                  replica_axis: str = "replica", devices=None,
                  name: str = "WORLD") -> MeshComm:
    """Communicator for sharded-K ensembles: a 2-level
    :func:`ensemble_mesh` with the comm reducing over the DATA axis
    only.

    Models built on this comm behave exactly as on a one-axis comm —
    sumstats/gradients psum over ``data_axis``, ``scatter_nd`` shards
    catalogs along it (replicated across replica slices) — but the
    mesh carries a *free* replica axis (:attr:`MeshComm.free_axes`),
    which unlocks the K-sharded program variants: ``model
    .batched_loss_and_grad_fn(k_sharded=True)``,
    ``run_multistart_adam(k_sharded=...)``, ``run_hmc(k_sharded=
    True)`` and ``FitScheduler(k_sharded=...)`` partition the
    ensemble axis (params, trajectories and both Adam moment sets)
    ``K / n_replicas`` per device.

    The trade: each replica slice holds a full catalog copy spread
    over ``n_devices / n_replicas`` data shards, so per-device
    catalog memory grows ×``n_replicas`` while per-device optimizer
    state shrinks ÷``n_replicas`` — the right exchange whenever K·
    nsteps·ndim state (ensembles, HMC chain blocks, serve buckets)
    dominates, which is what
    :func:`~multigrad_tpu.inference.ensemble_memory_model` decides.
    """
    return MeshComm.from_mesh(
        ensemble_mesh(n_replicas, data_axis=data_axis,
                      replica_axis=replica_axis, devices=devices),
        axes=(data_axis,), name=name)


def hybrid_mesh(ici_axis: str = "data", dcn_axis: str = "hosts"):
    """Two-axis mesh with the inter-host (DCN) axis outermost.

    Convenience for pod-scale runs: collectives over `ici_axis` stay
    on-chip-interconnect; `dcn_axis` crosses hosts.  Uses
    ``mesh_utils.create_hybrid_device_mesh`` when multiple hosts are
    present, else a trivial (1, n) mesh.
    """
    from jax.experimental import mesh_utils

    n_proc = jax.process_count()
    n_dev = len(jax.devices())
    if n_proc > 1:
        per_host = n_dev // n_proc
        devices = mesh_utils.create_hybrid_device_mesh(
            (per_host,), (n_proc,), devices=jax.devices())
        devices = devices.reshape(n_proc, per_host)
    else:
        devices = np.asarray(jax.devices()).reshape(1, n_dev)
    return Mesh(devices, (dcn_axis, ici_axis))


def hybrid_comm(ici_axis: str = "data", dcn_axis: str = "hosts",
                name: str = "WORLD") -> MeshComm:
    """Communicator over a :func:`hybrid_mesh`'s both axes.

    Data scattered with :func:`~multigrad_tpu.parallel.scatter_nd`
    over this comm is sharded host-major (contiguous block per host,
    split over that host's chips), and the model's total-sumstat psum
    reduces hierarchically: ICI within each host, DCN across hosts —
    the TPU-native equivalent of the reference's node-aware
    ``split_subcomms_by_node`` topology (``multigrad.py:48-85``).
    """
    return MeshComm.from_mesh(
        hybrid_mesh(ici_axis=ici_axis, dcn_axis=dcn_axis),
        axes=(dcn_axis, ici_axis), name=name)
