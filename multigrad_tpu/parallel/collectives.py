"""Collectives façade: the reference's MPI ops, TPU-native.

Maps the reference's host-side mpi4py collectives onto XLA's in-graph
collectives over a :class:`~multigrad_tpu.parallel.mesh.MeshComm`:

====================================  =====================================
reference (mpi4py, host-side)         this module (XLA, in-graph)
====================================  =====================================
``reduce_sum`` / ``Allreduce(SUM)``   ``lax.psum`` over the comm axis
(``multigrad.py:149-183``)            (:func:`reduce_sum`)
``comm.allgather``                    ``lax.all_gather`` (:func:`all_gather`)
``comm.bcast``                        replicated SPMD compute — no op needed
``util.scatter_nd`` send/recv loop    ``jax.device_put`` with a
(``util.py:65-77``)                   ``NamedSharding`` (:func:`scatter_nd`)
``mpi4jax.allreduce`` (in-graph       native here: every collective is
experiment, ``mpi4jax/multigrad.py``) in-graph by construction
====================================  =====================================

``reduce_sum`` keeps the reference's contract — *"each participant
contributes an array; the result is the elementwise sum of the
contributions"* — in both of its calling contexts:

* **Inside** a ``shard_map`` block over the comm's axis, it is exactly
  ``lax.psum`` (each device's block is its contribution).
* **Outside** any trace, an array sharded over the comm's axis is
  interpreted as "one contribution per device" (the shards are the
  contributions) and the shards are summed; an unsharded/replicated
  value is, as with ``MPI.Allreduce`` of identical buffers, multiplied
  by ``comm.size``.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from .mesh import MeshComm
from ._shard_map_compat import shard_map
from ..telemetry.comm import record_collective


def psum(value, axis_name):
    """``lax.psum`` with telemetry: reports the payload to any active
    :class:`~multigrad_tpu.telemetry.CommCounter` at trace time (a
    no-op otherwise).  Every collective this package — and the model
    core — emits goes through an instrumented wrapper like this one,
    so the O(|sumstats|+|params|) communication claim is measurable,
    not asserted (see :mod:`multigrad_tpu.telemetry.comm`).
    """
    record_collective("psum", value)
    return lax.psum(value, axis_name)


def _instrumented_all_gather(value, axis_name, axis=0, tiled=True):
    record_collective("all_gather", value)
    return lax.all_gather(value, axis_name, axis=axis, tiled=tiled)


def _under_trace(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _leaf_under_trace(value) -> bool:
    return any(_under_trace(leaf) for leaf in jax.tree_util.tree_leaves(value))


def reduce_sum(value, root: Optional[int] = None,
               comm: Optional[MeshComm] = None):
    """Sum `value` over all participants of `comm`.

    TPU-native port of ``multigrad.reduce_sum``
    (``/root/reference/multigrad/multigrad.py:149-183``).

    Parameters
    ----------
    value : array-like (or pytree, inside-graph)
        Each participant's contribution (see module docstring for what
        "participant" means inside vs outside the graph).
    root : int, optional
        Accepted for API parity.  ``lax.psum`` is an all-reduce, so the
        result is valid on *all* participants — a strict superset of
        the reference's reduce-to-root behavior.
    comm : MeshComm, optional
        ``None`` is the single-process identity, mirroring the
        reference's mpi4py-less fallback (``multigrad.py:168-169``).
    """
    del root  # all-reduce result is valid everywhere (superset of Reduce)
    if comm is None:
        return value
    if _leaf_under_trace(value):
        # Inside jit/shard_map: a true in-graph collective.
        return psum(value, comm.axis_name)

    # Outside any trace: interpret shards (if any) as the per-device
    # contributions and sum them with a tiny jitted shard_map program.
    # 0-d inputs (python scalars and 0-d arrays alike) come back 0-d,
    # mirroring the reference's scalar round-trip (multigrad.py:170,
    # 181-183); python scalars come back as python scalars.
    was_0d = np.ndim(value) == 0
    is_py_scalar = isinstance(value, (bool, int, float, complex))
    arr = jnp.atleast_1d(jnp.asarray(value))
    spec = _spec_on_comm(arr, comm)
    out = _psum_program(comm, spec)(arr)
    if was_0d:
        out = out.reshape(())
        if is_py_scalar:
            out = out.item()
    return out


def _spec_on_comm(arr, comm: MeshComm) -> PartitionSpec:
    """Infer the PartitionSpec of `arr` relative to `comm`'s mesh."""
    sh = getattr(arr, "sharding", None)
    if (isinstance(sh, NamedSharding) and sh.mesh.shape_tuple ==
            comm.mesh.shape_tuple and set(comm.axes) &
            set(jax.tree_util.tree_leaves(tuple(sh.spec)))):
        return sh.spec
    return PartitionSpec()  # replicated contribution


@functools.lru_cache(maxsize=None)
def _psum_program(comm: MeshComm, spec: PartitionSpec):
    fn = shard_map(
        lambda v: psum(v, comm.axis_name),
        mesh=comm.mesh, in_specs=(spec,), out_specs=PartitionSpec())
    return jax.jit(fn)


def all_gather(value, comm: Optional[MeshComm] = None, axis: int = 0):
    """Gather every participant's contribution, concatenated along `axis`.

    In-graph analog of the reference's ``comm.allgather`` calls
    (e.g. ``multigrad.py:578-579``).  Inside shard_map only; outside a
    trace a comm-sharded array already *is* the gathered global view.
    """
    if comm is None:
        return value
    if _leaf_under_trace(value):
        return _instrumented_all_gather(value, comm.axis_name, axis=axis)
    return jnp.asarray(value)


def scatter_nd(array, axis: int = 0, comm: Optional[MeshComm] = None,
               root: int = 0, pad_value=None, return_pad_count: bool = False):
    """Shard `array` along `axis` over the devices of `comm`.

    TPU-native port of ``multigrad.util.scatter_nd``
    (``/root/reference/multigrad/util.py:65-77``), which sends
    ``np.array_split`` chunks to each rank and therefore accepts any
    length.  Here the "scatter" is a single ``jax.device_put`` with a
    ``NamedSharding`` — XLA moves each shard to its device (no
    send/recv loop, no host round-trips).

    XLA sharding requires equal shards
    (``array.shape[axis] % comm.size == 0``), so the reference's
    any-length contract needs a pad convention: pass ``pad_value=``
    and a ragged axis is padded up to the next multiple with it.
    Choose a value that is *neutral for your model's statistic* —
    e.g. ``jnp.inf`` log-mass for the SMF's erf kernel, weight 0 for
    weighted pair counts; the shipped ``make_*_data`` builders do
    this.  Without ``pad_value`` a ragged axis raises: there is no
    universally-neutral filler, and a silently wrong sum is worse
    than an error.

    Returns a global jax.Array whose shards live one-per-device; pass
    it inside ``aux_data`` and the model core shards it automatically
    (its NamedSharding is the sharding contract).  With
    ``return_pad_count=True`` the return is ``(sharded, pad_count)``
    where ``pad_count`` is the number of padded rows appended to
    `axis` (0 when it divided evenly) — callers that must mask or
    un-pad (e.g. the streaming chunk planner, exact row counts,
    non-neutral statistics) read it instead of re-deriving the pad
    arithmetic.
    """
    del root  # single controller: no root process
    if comm is None:
        out = jnp.asarray(array)
        return (out, 0) if return_pad_count else out
    n = np.shape(array)[axis]
    pad_count = (-n) % comm.size
    if pad_count:
        if pad_value is None:
            raise ValueError(
                f"scatter_nd: axis {axis} of length {n} is not "
                f"divisible by comm.size={comm.size}; pass pad_value= "
                f"(a model-neutral filler) or pad first (see "
                f"utils.pad_to_multiple)")
        from ..utils.util import pad_to_multiple
        array, _ = pad_to_multiple(array, comm.size, axis=axis,
                                   pad_value=pad_value)
    out = jax.device_put(array, comm.sharding(axis=axis,
                                              ndim=np.ndim(array)))
    return (out, pad_count) if return_pad_count else out


def scatter_from_local(local_array, comm: MeshComm, axis: int = 0):
    """Assemble a global sharded array from per-host local data.

    Multi-host data loading path (the reference's per-rank loading,
    ``smf_grad_descent.py:23-28``, where each rank holds only its
    chunk): each host passes the data for *its own* devices and JAX
    assembles the global array without gathering
    (``jax.make_array_from_process_local_data``).
    """
    sharding = comm.sharding(axis=axis, ndim=np.ndim(local_array))
    return jax.make_array_from_process_local_data(
        sharding, np.asarray(local_array))
