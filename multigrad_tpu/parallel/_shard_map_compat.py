"""Version-portable shard_map import.

`shard_map` moved from `jax.experimental.shard_map` to `jax.shard_map`
around jax 0.6/0.7; support both so the package tracks JAX releases.
"""
import functools as _functools
import inspect as _inspect

import jax as _jax

try:  # jax >= 0.6
    from jax import shard_map as _shard_map_mod  # type: ignore

    shard_map = _shard_map_mod if callable(_shard_map_mod) else None
except ImportError:  # pragma: no cover
    shard_map = None

if shard_map is None:
    from jax.experimental.shard_map import shard_map  # type: ignore

# Pre-vma jax (<= 0.5, identified by shard_map's `check_rep`
# parameter) differs from vma-era jax (0.7+) in two load-bearing ways:
#
# * its static replication checker cannot see that a psum product is
#   replicated and rejects valid REP out_specs ("could not infer
#   replication"), so the checker must be disabled;
# * a `jax.vjp` traced *inside* the shard_map body is mesh-unaware —
#   the transpose does NOT insert the psum that makes a replicated
#   input's cotangent replicated, so callers must all-reduce such
#   gradients themselves (vma-era jax inserts it automatically, and
#   adding another psum there would multiply gradients by comm.size).
#
# `PRE_VMA` lets gradient code apply the manual all-reduce exactly
# when the automatic one is absent.
PRE_VMA = "check_rep" in _inspect.signature(shard_map).parameters
if PRE_VMA:
    shard_map = _functools.partial(shard_map, check_rep=False)


def vma_of(x):
    """The varying-manual-axes set of `x`'s type (empty off-mesh).

    jax 0.7+ tracks which mesh axes a value varies over inside
    shard_map; older jax has neither `jax.typeof` nor the `vma`
    field, so this degrades to "replicated".
    """
    aval = _jax.typeof(x) if hasattr(_jax, "typeof") else None
    return getattr(aval, "vma", frozenset()) or frozenset()


def pvary(x, axis_name):
    """Mark a replicated value as varying over `axis_name`.

    Needed since jax 0.7+ tracks varying-manual-axes types inside
    shard_map: a cotangent built from a psum (replicated) result must
    be cast back to 'varying' before entering a VJP whose primal
    output was device-varying.  `lax.pvary` was renamed `lax.pcast`.
    """
    if hasattr(_jax.lax, "pcast"):
        return _jax.lax.pcast(x, axis_name, to="varying")
    if hasattr(_jax.lax, "pvary"):  # pragma: no cover
        return _jax.lax.pvary(x, axis_name)
    return x  # pragma: no cover (old jax: no vma tracking)


def pvary_like(x, ref):
    """Cast replicated `x` to vary over the same mesh axes as `ref`.

    The scan-carry idiom: a replicated zeros init entering a scan
    whose body output is device-varying (it reads the shard's data)
    must be cast to match, or the carry types disagree under jax
    0.7+ vma typing.  No-op when `ref` is replicated/off-mesh.
    """
    vma = tuple(sorted(vma_of(ref)))
    return pvary(x, vma) if vma else x


__all__ = ["shard_map", "pvary", "pvary_like", "vma_of", "PRE_VMA"]
