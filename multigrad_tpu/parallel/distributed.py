"""Multi-host runtime bootstrap.

The reference's multi-process story is ``mpiexec -n N`` + mpi4py's
import-time ``COMM_WORLD`` capture
(``/root/reference/multigrad/multigrad.py:15-27``).  The TPU-native
equivalent is JAX's single-program multi-host runtime: every host runs
this same program, ``jax.distributed.initialize()`` wires up the
cluster (coordinator discovery is automatic on TPU pods), and all
devices of all hosts appear in ``jax.devices()`` for mesh
construction.  Collectives then ride ICI within a slice and DCN
across slices — no MPI anywhere in the process.
"""
from __future__ import annotations

from typing import Optional

import jax

_initialized = False


def _is_already_initialized_error(e: BaseException) -> bool:
    """Classify a ``jax.distributed.initialize`` RuntimeError.

    True only for the benign "runtime is already up" family —
    "already initialized", "can only be called once", ... — which is
    safe to swallow (idempotent re-init).  Everything else (an
    unreachable coordinator, a timeout, a failed bootstrap) must
    re-raise: silently degrading to single-host would run the fit on
    a fraction of the data with no error.  The grouping is fully
    parenthesized — an earlier version spelled it
    ``a or b and c``, whose meaning silently rode on Python's
    operator binding (`and` before `or`).
    """
    msg = str(e).lower()
    # NB: a bare "already" substring is NOT sufficient — "address
    # already in use" (a stale process holding the coordinator port)
    # is a failed bootstrap, not a benign re-init.
    return ("already initialized" in msg
            or "already been called" in msg
            or "already been initialized" in msg
            or ("initialize" in msg and "once" in msg))


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None, **kwargs) -> None:
    """Initialize the multi-host runtime (idempotent).

    Must be called before any other JAX API that initializes the XLA
    backend (same constraint as ``jax.distributed.initialize``
    itself).  On TPU pods all arguments are auto-detected; on CPU/GPU
    clusters pass them explicitly.  Safe to call in single-process
    runs — it degrades to standalone, mirroring the reference's
    mpi4py-less fallback (``multigrad.py:23-27``).  Extra keyword
    arguments (e.g. ``initialization_timeout``) pass through to
    ``jax.distributed.initialize``.
    """
    global _initialized
    if _initialized:
        return
    # NB: no jax.process_count()/devices() probing here — any backend
    # query would initialize XLA and make distributed.initialize
    # unconditionally fail.
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id,
            **kwargs)
        _initialized = True
    except RuntimeError as e:
        if _is_already_initialized_error(e):
            # Brought up earlier (by us or the launcher): fine.
            _initialized = True
        else:
            # A *failed* bootstrap (unreachable coordinator, timeout)
            # must not silently degrade to single-host — the fit would
            # run on a fraction of the data with no error.
            raise
    except ValueError:
        # No coordinator to connect to: single-process standalone.
        _initialized = True


def process_index() -> int:
    """This host's index (the analog of an MPI node rank)."""
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_main_process() -> bool:
    """True on the host that should print/plot (reference: ``if not
    rank`` guards, e.g. ``smf_grad_descent.py:123``)."""
    return jax.process_index() == 0
