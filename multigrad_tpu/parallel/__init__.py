from .mesh import (MeshComm, ensemble_comm, ensemble_mesh,
                   global_comm, hybrid_comm, hybrid_mesh,
                   split_subcomms, split_subcomms_by_node)
from .collectives import (all_gather, reduce_sum, scatter_from_local,
                          scatter_nd)
from . import distributed

__all__ = [
    "MeshComm", "ensemble_comm", "ensemble_mesh", "global_comm",
    "hybrid_comm", "hybrid_mesh", "split_subcomms",
    "split_subcomms_by_node", "all_gather", "reduce_sum",
    "scatter_from_local", "scatter_nd", "distributed",
]
