"""Runtime lock-dependency validation: the lockdep shadow.

The static pass (:mod:`multigrad_tpu.analysis.concurrency`) proves
lock-order and hold-while-blocking invariants from the AST; this
module is its runtime twin — the Linux-lockdep idea applied to the
serve/fleet layer's hand-threaded code.  Every lock the package
creates goes through the factories below (:func:`make_lock`,
:func:`make_rlock`, :func:`make_condition`) with a **canonical name**
(``"serve.queue.FitQueue._lock"`` — the same name the static pass
derives from the AST, which is what lets the two sides cross-check).

Off by default: without ``MGT_LOCKDEP=1`` the factories return plain
``threading`` primitives — zero overhead, tier-1 wall-clock
untouched.  Enabled, every acquisition records, per thread:

* **acquisition edges** — acquiring B while holding A adds the
  name-level edge ``A -> B`` (first-seen stacks kept for both ends);
* **order violations** — an edge that closes a cycle in the runtime
  edge graph is a potential deadlock, reported with the stack of
  *this* acquisition and the stack that recorded the reverse path's
  first edge (the "names both stacks" contract);
* **self-deadlock** — a thread blocking-acquiring a non-reentrant
  lock it already holds (the PR-9 sink re-entrancy shape) raises
  :class:`LockdepViolation` immediately instead of hanging the
  process;
* **hold-while-blocking** — a lock held longer than
  ``MGT_LOCKDEP_HOLD_S`` seconds (default 1.0) is reported as a
  ``long-hold`` violation with the holder's stack: the runtime
  signature of a blocking call (socket, subprocess, device dispatch)
  made under a lock.  ``Condition.wait`` releases the lock, so
  waiting never counts.

Violations are emitted as ``lockdep_violation`` telemetry records
when a :class:`~multigrad_tpu.telemetry.MetricsLogger` is registered
via :func:`set_logger`, and always kept in :func:`violations`.

**Cross-checking both ways** (:func:`crosscheck`): a runtime edge
absent from the static lock graph is a *static coverage hole* and
fails the run; a static cycle confirmed at runtime names both
stacks.  With ``MGT_LOCKDEP_DUMP=<dir>`` every process dumps its
edges + violations to ``<dir>/lockdep-<pid>.json`` at exit (workers
call :func:`maybe_dump` before ``os._exit``), and
``python -m multigrad_tpu.analysis.lint --targets threads
--runtime-edges <dir>`` performs the cross-check as a CI gate.

This module is **stdlib-only** (no jax, no numpy, no intra-package
imports) so every layer — including :mod:`multigrad_tpu.telemetry
.metrics`, which must stay cycle-free — can depend on it.

The interleaving harness (:mod:`multigrad_tpu.utils.testing`) hooks
in through :func:`set_controller`: with a controller installed,
wrapped locks report blocked acquisitions as scheduling points, and
:func:`sched_point` lets test code mark explicit ones.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time
import traceback
from typing import Optional

__all__ = [
    "LockdepViolation", "enabled", "enable", "disable",
    "make_lock", "make_rlock", "make_condition",
    "edges", "violations", "reset", "crosscheck",
    "dump", "maybe_dump", "load_edge_dumps",
    "set_logger", "set_controller", "sched_point",
]

#: Env knob: ``MGT_LOCKDEP=1`` turns the shadow on process-wide.
ENV_FLAG = "MGT_LOCKDEP"
#: Env knob: directory each process dumps its edges/violations into
#: at exit (``lockdep-<pid>.json``).
ENV_DUMP = "MGT_LOCKDEP_DUMP"
#: Env knob: hold-while-blocking threshold in seconds.
ENV_HOLD_S = "MGT_LOCKDEP_HOLD_S"


class LockdepViolation(RuntimeError):
    """A deterministic lockdep violation (self-deadlock: a thread
    blocking on a non-reentrant lock it already holds).  Raised
    instead of hanging — the whole point of the shadow is to turn a
    wedge into a stack trace."""


# ------------------------------------------------------------------ #
# global state (guarded by a PLAIN lock — the registry must never
# route through the wrappers it implements)
# ------------------------------------------------------------------ #
_STATE = threading.Lock()
_enabled: Optional[bool] = None
_edges: dict = {}          # (src, dst) -> {"stack_src", "stack_dst", "t"}
_violations: list = []
_logger = None
_controller = None
_held = threading.local()  # per-thread list of _Held


class _Held:
    __slots__ = ("name", "obj", "t0", "count")

    def __init__(self, name, obj):
        self.name = name
        self.obj = obj
        self.t0 = time.monotonic()
        self.count = 1


def _held_stack() -> list:
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = _held.stack = []
    return stack


def enabled() -> bool:
    """Whether the shadow is on (env ``MGT_LOCKDEP``, overridable by
    :func:`enable`/:func:`disable` for tests)."""
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get(ENV_FLAG, "") not in ("", "0")
        if _enabled:
            _register_atexit()
    return _enabled


def enable():
    """Programmatic on-switch (tests).  Only locks created AFTER this
    call are wrapped."""
    global _enabled
    _enabled = True
    _register_atexit()


def disable():
    global _enabled
    _enabled = False


def set_logger(logger):
    """Emit every violation as a ``lockdep_violation`` telemetry
    record into ``logger`` (a MetricsLogger; None detaches)."""
    global _logger
    _logger = logger


def set_controller(controller):
    """Install (or remove, with ``None``) the interleaving-harness
    controller.  The controller must expose ``managed(ident)``,
    ``point(tag)`` and ``blocked(name)``."""
    global _controller
    _controller = controller


def sched_point(tag: Optional[str] = None):
    """Explicit scheduling point for the deterministic-interleaving
    harness: a no-op unless a controller is installed AND the calling
    thread is one the controller manages."""
    c = _controller
    if c is not None and c.managed(threading.get_ident()):
        c.point(tag)


def _hold_threshold() -> float:
    try:
        return float(os.environ.get(ENV_HOLD_S, "") or 1.0)
    except ValueError:
        return 1.0


def _record_violation(kind: str, **detail):
    rec = {"kind": kind, "t": time.time(),
           "thread": threading.current_thread().name, **detail}
    with _STATE:
        _violations.append(rec)
    logger = _logger
    if logger is not None:
        try:
            logger.log("lockdep_violation", **rec)
        except Exception:
            pass
    return rec


def _edge_reaches(src: str, dst: str, edge_map: dict) -> Optional[list]:
    """DFS: a path ``src -> ... -> dst`` over name edges, or None."""
    seen = set()
    stack = [(src, [src])]
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        if node in seen:
            continue
        seen.add(node)
        for (a, b) in edge_map:
            if a == node and b not in seen:
                stack.append((b, path + [b]))
    return None


def _record_acquire(lock: "_DepLock"):
    stack = _held_stack()
    if stack:
        # Steady state is all-edges-already-known: probe first so
        # the stack render (the expensive part) happens only when a
        # NEW edge is actually inserted.  The probe-then-insert gap
        # can at worst make two racing threads both render a stack
        # for the same first occurrence — benign.
        with _STATE:
            fresh = any(h.name != lock.name
                        and (h.name, lock.name) not in _edges
                        for h in stack)
        if not fresh:
            stack.append(_Held(lock.name, lock))
            return
        here = "".join(traceback.format_stack(limit=12)[:-2])
        new_edges = []
        with _STATE:
            for h in stack:
                key = (h.name, lock.name)
                if h.name != lock.name and key not in _edges:
                    _edges[key] = {"stack_src": here,
                                   "stack_dst": here,
                                   "t": time.time()}
                    new_edges.append(key)
            # Cycle check OUTSIDE the registry lock would race a
            # concurrent edge insert; the graph is tiny, keep it in.
            cycle_hits = []
            for (a, b) in new_edges:
                path = _edge_reaches(b, a, dict(_edges))
                if path is not None:
                    rev = _edges.get((path[0], path[1]), {})
                    cycle_hits.append(((a, b), path, rev))
        for (a, b), path, rev in cycle_hits:
            _record_violation(
                "lock-order-cycle",
                edge=[a, b], cycle=path + [b],
                stack=here,
                other_stack=rev.get("stack_src", ""))
    stack.append(_Held(lock.name, lock))


def _record_release(lock: "_DepLock"):
    stack = _held_stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i].obj is lock:
            held = stack.pop(i)
            dt = time.monotonic() - held.t0
            if dt > _hold_threshold():
                _record_violation(
                    "long-hold", lock=lock.name,
                    held_s=round(dt, 3),
                    stack="".join(
                        traceback.format_stack(limit=12)[:-2]))
            return


class _DepLock:
    """Name-carrying wrapper around ``threading.Lock`` recording
    acquisition edges, self-deadlock, and hold duration."""

    _reentrant = False

    def __init__(self, name: str):
        self.name = name
        self._inner = threading.Lock()

    def acquire(self, blocking=True, timeout=-1):
        if blocking and not self._reentrant:
            for h in _held_stack():
                if h.obj is self:
                    _record_violation(
                        "self-deadlock", lock=self.name,
                        stack="".join(
                            traceback.format_stack(limit=12)[:-1]))
                    raise LockdepViolation(
                        f"thread {threading.current_thread().name} "
                        f"blocking on non-reentrant lock "
                        f"{self.name!r} it already holds")
        c = _controller
        if (blocking and timeout == -1 and c is not None
                and c.managed(threading.get_ident())):
            # Harness mode: a failed try-acquire is a scheduling
            # point — the controller learns the thread is blocked
            # (deterministic deadlock detection) and re-grants turns
            # until the lock frees up.
            while not self._inner.acquire(False):
                c.blocked(self.name)
            ok = True
        else:
            ok = (self._inner.acquire(blocking, timeout) if blocking
                  else self._inner.acquire(False))
        if ok:
            self._on_acquired()
        return ok

    def _on_acquired(self):
        _record_acquire(self)

    def release(self):
        _record_release(self)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<DepLock {self.name!r}>"


class _DepRLock(_DepLock):
    """Reentrant flavor: inner RLock; only the outermost acquire and
    the matching release touch the held stack."""

    _reentrant = True

    def __init__(self, name: str):
        self.name = name
        self._inner = threading.RLock()
        self._depth_local = threading.local()

    def _depth(self) -> int:
        return getattr(self._depth_local, "n", 0)

    def _on_acquired(self):
        n = self._depth() + 1
        self._depth_local.n = n
        if n == 1:
            _record_acquire(self)

    def release(self):
        n = self._depth() - 1
        self._depth_local.n = n
        if n == 0:
            _record_release(self)
        self._inner.release()

    def locked(self):
        return self._depth() > 0


# ------------------------------------------------------------------ #
# factories — the one creation idiom the whole package uses
# ------------------------------------------------------------------ #
def make_lock(name: str, may_precede=None):
    """A mutex named for the lockdep shadow and the static graph.

    ``name`` is the canonical lock name the static pass derives from
    the AST (``"<module>.<Class>.<attr>"`` relative to the package
    root) — the factories and :mod:`multigrad_tpu.analysis
    .concurrency` cross-check that they agree.  ``may_precede``
    (a tuple of canonical names, or ``"*"``) is a **static
    declaration**, read from the AST, of lock-order edges this lock
    is allowed to open that the analyzer cannot derive (a dynamic
    dispatch — e.g. a metrics logger's pluggable sinks); the runtime
    ignores it.  Returns a plain ``threading.Lock`` unless lockdep
    is enabled.
    """
    del may_precede
    if enabled():
        return _DepLock(name)
    return threading.Lock()


def make_rlock(name: str, may_precede=None):
    """Reentrant twin of :func:`make_lock`."""
    del may_precede
    if enabled():
        return _DepRLock(name)
    return threading.RLock()


def make_condition(name: str, lock=None):
    """A condition variable for the shadow.  ``lock`` (typically a
    sibling :func:`make_lock` product, so several conditions share
    one mutex) is wrapped as-is — ``threading.Condition`` drives any
    object with ``acquire``/``release``, so waits and re-acquires of
    a DepLock keep recording.  With ``lock=None`` and lockdep on,
    the condition gets its own named DepLock."""
    if lock is None and enabled():
        lock = _DepLock(name)
    return threading.Condition(lock)


# ------------------------------------------------------------------ #
# registry access + cross-check
# ------------------------------------------------------------------ #
def edges() -> dict:
    """Snapshot of the runtime edge map:
    ``{(src, dst): {"stack_src", "stack_dst", "t"}}``."""
    with _STATE:
        return dict(_edges)


def violations() -> list:
    with _STATE:
        return list(_violations)


def reset():
    """Clear edges and violations (tests)."""
    with _STATE:
        _edges.clear()
        _violations.clear()


def crosscheck(allowed_edges, wildcard_sources=(),
               runtime_edges=None) -> list:
    """Cross-check runtime acquisition edges against the static lock
    graph — **a runtime edge absent from the static graph is a
    static coverage hole** and must fail the run.

    ``allowed_edges`` is an iterable of ``(src, dst)`` canonical-name
    pairs (the static graph's derived + declared edges);
    ``wildcard_sources`` names locks declared ``may_precede="*"``.
    ``runtime_edges`` defaults to this process's live registry; pass
    a dict/iterable (e.g. from :func:`load_edge_dumps`) to check a
    fleet's dumped edges.  Returns one violation dict per hole.
    """
    allowed = set(tuple(e) for e in allowed_edges)
    wild = set(wildcard_sources)
    observed = runtime_edges if runtime_edges is not None else edges()
    holes = []
    items = (observed.items() if isinstance(observed, dict)
             else ((tuple(e), {}) for e in observed))
    for (src, dst), info in items:
        if (src, dst) in allowed or src in wild:
            continue
        holes.append({
            "kind": "static-coverage-hole",
            "edge": [src, dst],
            "stack": (info or {}).get("stack_src", ""),
        })
    return holes


def dump(path: str) -> str:
    """Write this process's edges + violations as JSON."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with _STATE:
        payload = {
            "pid": os.getpid(),
            "t": time.time(),
            "edges": [[a, b] for (a, b) in _edges],
            "violations": list(_violations),
        }
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
    return path


def maybe_dump() -> Optional[str]:
    """Dump to ``$MGT_LOCKDEP_DUMP/lockdep-<pid>.json`` when the env
    knob is set (no-op otherwise).  Safe to call repeatedly; the
    fleet worker calls it explicitly before ``os._exit`` (which
    skips atexit)."""
    out_dir = os.environ.get(ENV_DUMP)
    if not out_dir or not enabled():
        return None
    return dump(os.path.join(out_dir, f"lockdep-{os.getpid()}.json"))


_atexit_registered = False


def _register_atexit():
    global _atexit_registered
    if not _atexit_registered:
        _atexit_registered = True
        atexit.register(maybe_dump)


def load_edge_dumps(path):
    """Load one dump file — or every ``lockdep-*.json`` in a
    directory — into ``(edges, violations, loaded_paths)``: the
    fleet-wide runtime picture for :func:`crosscheck`.
    ``loaded_paths`` is the evidence trail — a caller gating CI on
    the cross-check MUST fail when it is empty (a missing/empty dump
    dir would otherwise read as a clean run)."""
    paths = []
    if os.path.isdir(path):
        paths = sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if f.startswith("lockdep-") and f.endswith(".json"))
    elif os.path.exists(path):
        paths = [path]
    all_edges: dict = {}
    all_violations: list = []
    loaded = []
    for p in paths:
        try:
            with open(p) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            continue
        loaded.append(p)
        for e in payload.get("edges", ()):
            all_edges.setdefault(tuple(e), {"stack_src": "", "t": 0})
        for v in payload.get("violations", ()):
            all_violations.append(dict(v, source=p))
    return all_edges, all_violations, loaded
