"""Halo-catalog index utilities (diffdesi experimental).

The function names, signatures, and semantics are pinned by the
reference's ``diffdesi_experimental/util.py`` (host-halo resolution by
pointer-jumping ``indices[indices]`` to a fixpoint, plus
sort-and-reindex helpers that reorder catalogs by ultimate host halo);
the implementations here are written fresh against that contract and
its test vectors — not copied — and fix the reference's mutable
default-argument lists.

These are host-side preprocessing utilities (run once per catalog
load), so the NumPy implementations are kept; a JAX variant is
provided for use inside jitted pipelines, with the fixpoint iteration
expressed as a bounded ``lax.while_loop``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

MAX_RECURSION = 50


def sort_all_by_ultimate_top_dump(ultimate_dump, arrays_to_sort=(),
                                  arrays_to_sort_and_reindex=()):
    """Sort catalog arrays by ultimate host index; index-valued arrays
    are additionally remapped into the sorted order (contract:
    ``diffdesi_experimental/util.py:4-15``)."""
    hosts = find_ultimate_top_indices(ultimate_dump)
    order = np.argsort(hosts)
    inverse = np.argsort(order)  # old position -> new position
    return ([np.asarray(x)[order] for x in arrays_to_sort],
            [sort_and_reindex(x, order, inverse)
             for x in arrays_to_sort_and_reindex])


def find_ultimate_top_indices(indices):
    """Resolve each entry to its ultimate host index by pointer
    doubling (contract: ``diffdesi_experimental/util.py:18-28``).

    Each pass replaces every pointer with its parent's pointer, so
    chain depth halves per pass; a cycle (or a chain deeper than
    2**MAX_RECURSION) raises ``RecursionError`` as in the reference.
    """
    idx = np.array(indices)
    for _ in range(MAX_RECURSION):
        parent = idx[idx]
        if np.array_equal(parent, idx):
            return idx
        idx = parent
    raise RecursionError(
        f"Host search hasn't finished after {MAX_RECURSION} steps")


def sort_and_reindex(indices, order=None, inverse=None):
    """Reorder an index-valued array by ``order`` while remapping its
    values to the positions they moved to (contract:
    ``diffdesi_experimental/util.py:31-35``)."""
    indices = np.asarray(indices)
    if order is None:
        order = np.argsort(indices)
    if inverse is None:
        inverse = np.argsort(order)
    return inverse[indices][order]


@jax.jit
def find_ultimate_top_indices_jax(indices):
    """In-graph fixpoint host resolution (``lax.while_loop`` with the
    same 50-step bound; jit/TPU-safe — pointer chasing is a gather,
    which XLA vectorizes).

    Returns ``(resolved_indices, converged)``.  Python exceptions
    cannot be raised from a traced loop, so the NumPy twin's
    ``RecursionError`` (on cycles / >50-deep chains) becomes an
    explicit ``converged`` flag the caller must check.
    """
    indices = jnp.asarray(indices)

    def cond(state):
        i, count = state
        return jnp.logical_and(jnp.any(i != i[i]), count < MAX_RECURSION)

    def body(state):
        i, count = state
        return i[i], count + 1

    out, _ = jax.lax.while_loop(cond, body, (indices, 0))
    converged = jnp.logical_not(jnp.any(out != out[out]))
    return out, converged
