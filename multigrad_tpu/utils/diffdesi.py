"""Halo-catalog index utilities (diffdesi experimental).

Port of ``/root/reference/multigrad/diffdesi_experimental/util.py``:
host-halo resolution by iterating ``indices = indices[indices]`` to a
fixpoint, plus sort-and-reindex helpers used to reorder catalogs by
ultimate host halo.

These are host-side preprocessing utilities (run once per catalog
load), so the NumPy implementations are kept; JAX variants are
provided for use inside jitted pipelines, with the fixpoint iteration
expressed as a bounded ``lax.while_loop``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

MAX_RECURSION = 50


def sort_all_by_ultimate_top_dump(ultimate_dump, arrays_to_sort=[],
                                  arrays_to_sort_and_reindex=[]):
    """Parity: ``diffdesi_experimental/util.py:4-15``."""
    ultimate_top_dump = find_ultimate_top_indices(ultimate_dump)
    argsort = np.argsort(ultimate_top_dump)
    argsort2 = np.argsort(argsort)

    sorted_arrays = [np.asarray(x)[argsort] for x in arrays_to_sort]
    reindexed_arrays = [sort_and_reindex(x, argsort, argsort2)
                        for x in arrays_to_sort_and_reindex]
    return sorted_arrays, reindexed_arrays


def find_ultimate_top_indices(indices):
    """Resolve each entry to its ultimate host index
    (parity: ``diffdesi_experimental/util.py:18-28``)."""
    indices = np.array(indices)
    recursion_count = 0
    while np.any(indices != indices[indices]):
        recursion_count += 1
        if recursion_count > MAX_RECURSION:
            raise RecursionError(
                f"Host search hasn't finished after {MAX_RECURSION} steps")
        indices = indices[indices]
    return indices


def sort_and_reindex(indices, argsort=None, argsort2=None):
    """Parity: ``diffdesi_experimental/util.py:31-35``."""
    indices = np.asarray(indices)
    argsort = np.argsort(indices) if argsort is None else argsort
    argsort2 = np.argsort(argsort) if argsort2 is None else argsort2
    return argsort2[indices][argsort]


@jax.jit
def find_ultimate_top_indices_jax(indices):
    """In-graph fixpoint host resolution (``lax.while_loop`` with the
    same 50-step bound; jit/TPU-safe — pointer chasing is a gather,
    which XLA vectorizes).

    Returns ``(resolved_indices, converged)``.  Python exceptions
    cannot be raised from a traced loop, so the NumPy twin's
    ``RecursionError`` (on cycles / >50-deep chains) becomes an
    explicit ``converged`` flag the caller must check.
    """
    indices = jnp.asarray(indices)

    def cond(state):
        i, count = state
        return jnp.logical_and(jnp.any(i != i[i]), count < MAX_RECURSION)

    def body(state):
        i, count = state
        return i[i], count + 1

    out, _ = jax.lax.while_loop(cond, body, (indices, 0))
    converged = jnp.logical_not(jnp.any(out != out[out]))
    return out, converged
