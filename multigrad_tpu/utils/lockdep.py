"""Public home of the lockdep runtime shadow.

The implementation lives in :mod:`multigrad_tpu._lockdep` — a
stdlib-only module at the package top level so that early-imported,
cycle-sensitive modules (:mod:`multigrad_tpu.telemetry.metrics` is
pulled in while :mod:`multigrad_tpu.parallel.mesh` is still
initializing) can use the factories without triggering this
package's heavier ``utils`` init.  Import from here in user code and
tests::

    from multigrad_tpu.utils import lockdep
    lockdep.enable()
    q = FitQueue()          # locks created now are wrapped
    ...
    lockdep.crosscheck(static_edges, wildcards)

See the implementation module's docstring for the full contract
(``MGT_LOCKDEP`` / ``MGT_LOCKDEP_DUMP`` / ``MGT_LOCKDEP_HOLD_S``,
edge recording, cycle/self-deadlock/long-hold violations, and the
both-ways cross-check against the static lock graph).
"""
from .._lockdep import *  # noqa: F401,F403
from .._lockdep import (ENV_DUMP, ENV_FLAG,  # noqa: F401
                        ENV_HOLD_S, __all__)
