"""Utility functions: simple gradient descent, LHS sampling, data prep.

Port of ``/root/reference/multigrad/util.py``.
"""
from __future__ import annotations

from typing import NamedTuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from scipy.stats import qmc

from ..parallel.collectives import scatter_nd  # noqa: F401  (parity home)

try:
    from tqdm import auto as tqdm
except ImportError:  # pragma: no cover
    tqdm = None


__all__ = ["simple_grad_descent", "simple_grad_descent_scan",
           "GradDescentResult", "latin_hypercube_sampler", "scatter_nd",
           "pad_to_multiple", "trange", "cached_program",
           "evict_cached_programs", "add_compile_observer",
           "remove_compile_observer"]


# Fallback cache for callables that don't accept attributes (rare:
# builtins, slotted callables). Entries here live for the process.
_STRONG_PROGRAM_CACHE = {}

# Compile-accounting observers (telemetry.resources subscribes).
# Every program the package builds passes through cached_program, so
# this single boundary sees every build (miss: build wall seconds)
# and every reuse (hit).  Observers must be cheap and must never
# raise — a broken observer costs its notification, not the program.
_COMPILE_OBSERVERS = []


def add_compile_observer(callback):
    """Register ``callback(key, seconds, hit)`` for program-cache
    traffic: ``hit=False`` with the build's wall seconds on a miss,
    ``hit=True`` with ``seconds=0.0`` on a reuse."""
    if callback not in _COMPILE_OBSERVERS:
        _COMPILE_OBSERVERS.append(callback)


def remove_compile_observer(callback):
    """Unregister a :func:`add_compile_observer` callback (no-op if
    absent)."""
    try:
        _COMPILE_OBSERVERS.remove(callback)
    except ValueError:
        pass


def _notify_compile(key, seconds, hit):
    for cb in list(_COMPILE_OBSERVERS):
        try:
            cb(key, seconds, hit)
        except Exception:
            pass


def cached_program(fn, key, build):
    """Per-callable compiled-program cache with callable-bound lifetime.

    Passing ``fn`` to ``jax.jit`` as a static argument would pin it —
    and everything it closes over, e.g. a model wrapper holding
    multi-GB aux arrays — in jit's global cache for the life of the
    process.  Instead the cache dict is stored *on the callable* (or,
    for bound methods, on the object they are bound to), so dropping
    the last reference to the callable/model frees the compiled
    executables with it; the reference cycle (fn → cache → program →
    closure → fn) is ordinary gc-collectable garbage.
    """
    owner = getattr(fn, "__self__", fn)
    cache = getattr(owner, "_mgt_program_cache", None)
    if cache is None:
        try:
            cache = owner._mgt_program_cache = {}
        except (AttributeError, TypeError):
            cache = _STRONG_PROGRAM_CACHE
    if cache is _STRONG_PROGRAM_CACHE:
        # The shared fallback has no per-owner scoping; fn itself (a
        # bound method hashes by (instance, func)) must disambiguate.
        full_key = (fn, key)
    else:
        # Bound-method objects are recreated per attribute access; key
        # on the stable underlying function (owner disambiguates).
        full_key = (getattr(fn, "__func__", None), key)
    if full_key not in cache:
        if _COMPILE_OBSERVERS:
            import time
            t0 = time.perf_counter()
            cache[full_key] = build()
            _notify_compile(key, time.perf_counter() - t0, False)
        else:
            cache[full_key] = build()
    elif _COMPILE_OBSERVERS:
        _notify_compile(key, 0.0, True)
    return cache[full_key]


def evict_cached_programs(fn, match, keep=None):
    """Drop ``fn``'s cached programs whose key satisfies ``match``.

    The pressure-relief valve for cache keys that embed a session
    object (e.g. a telemetry tap, which carries its logger): without
    eviction, every fresh logger would pin one more compiled
    executable — and the closed logger behind it — for the callable's
    lifetime.  ``match(key)`` selects candidates; the entry whose key
    equals ``keep`` survives.  Evicting a program another in-flight
    fit still references is safe (it holds its own reference; only
    the cache slot is dropped).
    """
    owner = getattr(fn, "__self__", fn)
    cache = getattr(owner, "_mgt_program_cache", None)
    if cache is None:
        cache = _STRONG_PROGRAM_CACHE
    head = fn if cache is _STRONG_PROGRAM_CACHE \
        else getattr(fn, "__func__", None)
    for full_key in list(cache):
        if (full_key[0] == head and full_key[1] != keep
                and match(full_key[1])):
            del cache[full_key]


def trange_no_tqdm(n, desc=None, leave=True):
    return range(n)


def trange_with_tqdm(n, desc=None, leave=True):
    return tqdm.trange(n, desc=desc, leave=leave)


# Single shared progress-range shim (the reference repeats this
# guarded-tqdm block in four modules; one copy here serves all).
trange = trange_no_tqdm if tqdm is None else trange_with_tqdm


class GradDescentResult(NamedTuple):
    """Parity: ``util.py:50-53``."""
    loss: jnp.ndarray
    params: jnp.ndarray
    aux: Union[jnp.ndarray, list]


def latin_hypercube_sampler(xmin, xmax, n_dim, num_evaluations,
                            seed=None, optimization=None):
    """Latin-Hypercube parameter sample (parity: ``util.py:56-62``)."""
    xmin = np.zeros(n_dim) + xmin
    xmax = np.zeros(n_dim) + xmax
    sampler = qmc.LatinHypercube(n_dim, seed=seed, optimization=optimization)
    unit_hypercube = sampler.random(num_evaluations)
    return qmc.scale(unit_hypercube, xmin, xmax)


def pad_to_multiple(array, multiple: int, axis: int = 0, pad_value=0.0):
    """Pad `axis` of `array` up to a multiple of `multiple`.

    XLA sharding needs evenly divisible shards (unlike the reference's
    ``np.array_split`` ragged scatter, ``util.py:69``); pad with a
    value neutral for the model's sumstats (e.g. ``jnp.inf`` halo mass
    for erf-CDF counts in bounded bins) before ``scatter_nd``.

    Returns ``(padded_array, original_length)``.
    """
    n = np.shape(array)[axis]
    remainder = (-n) % multiple
    if remainder == 0:
        return jnp.asarray(array), n
    pad_width = [(0, 0)] * np.ndim(array)
    pad_width[axis] = (0, remainder)
    return jnp.pad(jnp.asarray(array), pad_width,
                   constant_values=pad_value), n


def _resolve_loss_and_grad(loss_func, loss_and_grad_func, grad_loss_func,
                           has_aux, **kwargs):
    """Normalize the three ways a caller can supply gradients into one
    ``params -> ((loss[, aux]), grad)`` callable (capability parity with
    ``/root/reference/multigrad/util.py:90-97``)."""
    if loss_and_grad_func is not None:
        return loss_and_grad_func
    if grad_loss_func is not None:
        return lambda params: (loss_func(params), grad_loss_func(params))
    return jax.value_and_grad(loss_func, has_aux=has_aux, **kwargs)


def simple_grad_descent(
    loss_func,
    guess,
    nsteps,
    learning_rate,
    loss_and_grad_func=None,
    grad_loss_func=None,
    has_aux=False,
    progress=True,
    **kwargs,
):
    """Fixed-learning-rate gradient descent, host loop.

    Capability parity with ``/root/reference/multigrad/util.py:80-134``
    (same signature, full loss/params/aux trajectory return), but
    re-expressed as a plain host loop: each iteration is one call to
    the (typically jitted) loss-and-grad program, so arbitrary
    host-side callables work.  :func:`simple_grad_descent_scan` is the
    fully in-graph variant for jittable functions — prefer it on TPU.
    """
    fn = _resolve_loss_and_grad(loss_func, loss_and_grad_func,
                                grad_loss_func, has_aux, **kwargs)
    steps = (trange(nsteps, desc="Simple Gradient Descent Progress")
             if progress and jax.process_index() == 0 else range(nsteps))

    params = jnp.asarray(guess)
    losses, trajectory, aux_trail = [], [], []
    for _ in steps:
        if has_aux:
            (loss, aux), grad = fn(params)
        else:
            loss, grad = fn(params)
            aux = None
        losses.append(loss)
        trajectory.append(params)
        aux_trail.append(aux)
        params = params - learning_rate * grad

    if has_aux:
        try:
            aux_trail = jnp.array(aux_trail)
        except TypeError:
            pass  # heterogeneous aux stays a list
    return GradDescentResult(loss=jnp.array(losses),
                             params=jnp.array(trajectory),
                             aux=aux_trail)


def _gd_scan_program(fn, nsteps, learning_rate, has_aux):
    """Whole-fit jitted scan, cached per callable (see cached_program)."""
    def build():
        @jax.jit
        def program(p0):
            def loopfunc(params, _x):
                out = fn(params)
                if has_aux:
                    (loss, aux), grad = out
                else:
                    (loss, grad), aux = out, 0.0
                y = (loss, params, aux)
                return params - learning_rate * grad, y

            _, ys = jax.lax.scan(loopfunc, p0, None, length=nsteps)
            return ys
        return program

    return cached_program(fn, ("gd_scan", nsteps, learning_rate, has_aux),
                          build)


def simple_grad_descent_scan(loss_and_grad_func, guess, nsteps,
                             learning_rate, has_aux=False):
    """In-graph fixed-LR gradient descent: one ``lax.scan``.

    The shape the reference's ``mpi4jax`` experiment reached for
    (``mpi4jax/multigrad.py:33-58``) — scan + in-graph collectives —
    minus the rank-0 update + bcast (replicated SPMD updates instead).
    Pass a stable callable: the compiled fit is cached on its identity.
    """
    guess = jnp.asarray(guess, dtype=jnp.result_type(float))
    program = _gd_scan_program(loss_and_grad_func, nsteps,
                               float(learning_rate), has_aux)
    loss, params, aux = program(guess)
    return GradDescentResult(loss=loss, params=params,
                             aux=aux if has_aux else list(aux))
