"""Checkpoint / resume for long fits.

The reference has **no** checkpointing (SURVEY §5.4); its Adam/GD
return full parameter trajectories as the de-facto restart story.
Pod jobs preempt, so this module is a deliberate capability addition:
save/restore of ``(step, params, opt_state, randkey)`` pytrees.

Two backends:

* :func:`save` / :func:`load` — dependency-free ``.npz`` of a
  flattened pytree (portable, host-local).
* :class:`OrbaxCheckpointer` — `orbax.checkpoint` when available,
  for async, multi-host-correct pod checkpoints.
"""
from __future__ import annotations

import json
import os
from typing import Any

import jax
import numpy as np

# Bump when the archive layout changes; load() rejects other versions
# with an explicit "format" error instead of a late unflatten failure.
FORMAT_VERSION = 1


def _flatten_with_paths(tree):
    flat, treedef = jax.tree_util.tree_flatten(tree)
    return flat, treedef


def save(path: str, tree: Any) -> None:
    """Save a pytree of arrays/scalars to ``path`` (a single .npz).

    PRNG keys are stored via ``jax.random.key_data``.  Metadata
    (leaf count, which leaves are PRNG keys) is bundled *inside* the
    archive so the tmp-write + ``os.replace`` is the entire commit —
    a preemption can never leave data and metadata out of sync.
    """
    leaves, treedef = _flatten_with_paths(tree)
    arrays = {}
    is_key = []
    for i, leaf in enumerate(leaves):
        if hasattr(leaf, "dtype") and jax.numpy.issubdtype(
                leaf.dtype, jax.dtypes.prng_key):
            arrays[f"leaf_{i}"] = np.asarray(jax.random.key_data(leaf))
            is_key.append(i)
        else:
            arrays[f"leaf_{i}"] = np.asarray(leaf)
    arrays["__meta__"] = np.frombuffer(json.dumps(
        {"version": FORMAT_VERSION, "n": len(leaves),
         "is_key": is_key}).encode(), dtype=np.uint8)
    final = path if path.endswith(".npz") else path + ".npz"
    tmp = final + ".tmp.npz"
    np.savez(tmp, **arrays)
    os.replace(tmp, final)


def load(path: str, like: Any) -> Any:
    """Restore a pytree saved by :func:`save`; `like` supplies the
    structure (e.g. a freshly initialized state)."""
    npz_path = path if path.endswith(".npz") else path + ".npz"
    data = np.load(npz_path)
    meta = json.loads(bytes(data["__meta__"]).decode())
    # Archives written before the version field existed share version
    # 1's byte layout exactly, so a missing field reads as 1.
    version = meta.get("version", 1)
    if version != FORMAT_VERSION:
        raise ValueError(
            f"checkpoint {npz_path!r} has format version {version!r}; "
            f"this build reads version {FORMAT_VERSION}. Re-save the "
            "checkpoint with the current library (or load it with the "
            "version that wrote it).")
    leaves, treedef = jax.tree_util.tree_flatten(like)
    if len(leaves) != meta["n"]:
        raise ValueError(
            f"checkpoint {npz_path!r} holds {meta['n']} pytree leaves "
            f"but `like` has {len(leaves)}: the checkpoint was written "
            "for a different state structure (e.g. different optimizer "
            "or parameter count).")
    restored = []
    for i in range(meta["n"]):
        arr = data[f"leaf_{i}"]
        if i in meta["is_key"]:
            restored.append(jax.random.wrap_key_data(arr))
        else:
            restored.append(arr)
    return jax.tree_util.tree_unflatten(treedef, restored)


class OrbaxCheckpointer:
    """Thin orbax wrapper for pod-scale async checkpointing.

    Usage::

        ckpt = OrbaxCheckpointer("/tmp/fit_ckpt")
        ckpt.save(step, {"params": params, "opt_state": opt_state})
        state = ckpt.restore_latest({"params": params_like, ...})
    """

    def __init__(self, directory: str):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self.manager = ocp.CheckpointManager(self.directory)

    @staticmethod
    def _normalize(state: Any) -> Any:
        # Some orbax versions' StandardCheckpointHandler accept
        # np.ndarray but reject numpy *scalars* (np.int64(5), ...);
        # promote them to 0-d arrays — same values, supported type.
        return jax.tree_util.tree_map(
            lambda x: np.asarray(x) if isinstance(x, np.generic) else x,
            state)

    def save(self, step: int, state: Any) -> None:
        self.manager.save(step, args=self._ocp.args.StandardSave(
            self._normalize(state)))

    def restore_latest(self, like: Any) -> Any:
        step = self.manager.latest_step()
        if step is None:
            return None
        return self.manager.restore(
            step, args=self._ocp.args.StandardRestore(
                self._normalize(like)))

    def wait(self):
        self.manager.wait_until_finished()
