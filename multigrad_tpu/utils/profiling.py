"""Profiling and timing helpers.

The reference's only instrumentation is wall-clock timing with an
explicit JIT warm-up run (``tests/smf_example/benchmark.py:41-46``)
and ``time.time`` around fits (SURVEY §5.1).  This module keeps that
warm-up-then-time shape and adds ``jax.profiler`` trace capture for
TPU work (op-level timelines viewable in TensorBoard/Perfetto).
"""
from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import jax

from .._lockdep import make_lock


class Timer:
    """Warm-up-then-time harness (the reference benchmark's shape).

    Each timed call is individually fenced (``block_until_ready``) so
    per-call latencies are real measurements, not dispatch times —
    which makes the tail visible: the returned dict carries ``p50``
    and ``p95`` per-call seconds alongside the aggregate
    ``calls_per_sec``.  A p95 far above p50 is the signature of
    tunnel hiccups / recompiles / host interference that a bare mean
    silently averages away.
    """

    def __init__(self, fn: Callable, warmup: int = 1):
        self.fn = fn
        self.warmup = warmup

    def __call__(self, n_calls: int, *args, **kwargs):
        import numpy as np

        for _ in range(self.warmup):
            jax.block_until_ready(self.fn(*args, **kwargs))
        latencies = []
        t0 = time.perf_counter()
        for _ in range(n_calls):
            t1 = time.perf_counter()
            jax.block_until_ready(self.fn(*args, **kwargs))
            latencies.append(time.perf_counter() - t1)
        elapsed = time.perf_counter() - t0
        return dict(calls_per_sec=n_calls / elapsed, elapsed=elapsed,
                    n_calls=n_calls,
                    p50=float(np.percentile(latencies, 50)),
                    p95=float(np.percentile(latencies, 95)),
                    latencies=latencies)


@contextlib.contextmanager
def trace(log_dir: Optional[str] = None, perfetto: bool = False):
    """Capture a ``jax.profiler`` trace around a block; yields the
    trace directory.

    View with TensorBoard's profile plugin or Perfetto.  With
    ``perfetto=True`` a self-contained ``*.trace.json.gz`` is also
    written — parseable without TensorBoard
    (:func:`multigrad_tpu.telemetry.profile.summarize_device_trace`
    aggregates per-op device time from it).

    ``log_dir=None`` (the default) captures into a fresh private
    ``mkdtemp`` child: a fixed shared path would let parallel CI
    jobs (or two fits in one suite) clobber each other's traces —
    read the actual directory off the yielded value.
    """
    import tempfile

    if log_dir is None:
        log_dir = tempfile.mkdtemp(prefix="multigrad_tpu_trace_")
    jax.profiler.start_trace(log_dir, create_perfetto_trace=perfetto)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


@dataclass
class StreamStats:
    """Counters for the streaming-data pipeline (:mod:`..data`).

    Updated concurrently by the prefetcher's background loader thread
    and the consuming fit loop, so every increment goes through one
    lock.  ``stall_s`` is time the *consumer* spent blocked waiting
    for a chunk after the pipeline was primed — the number that should
    be ~0 when host→device transfer of chunk k+1 truly overlaps
    compute on chunk k; the unavoidable first-chunk wait is tracked
    separately as ``fill_s``.  ``max_live_buffers`` is the high-water
    mark of device chunk buffers held by the prefetcher — bounded by
    its ``max_buffers`` (2 = double buffering).
    """

    bytes_streamed: int = 0
    chunks: int = 0
    stall_s: float = 0.0
    fill_s: float = 0.0
    wall_s: float = 0.0
    max_live_buffers: int = 0
    #: Per-pass counter splits, keyed by the pass label the stream's
    #: driver supplies ("sumstats" / "vjp" / "jac" for the streamed
    #: two-pass algebra).  The streamed loss-and-grad re-streams the
    #: catalog for its backward pass, so a single merged stall number
    #: cannot say WHICH pass starved — these can.
    passes: dict = field(default_factory=dict, compare=False)

    _PASS_KEYS = ("bytes_streamed", "chunks", "stall_s", "fill_s",
                  "wall_s")
    _lock: threading.Lock = field(
        default_factory=lambda: make_lock(
            "utils.profiling.StreamStats._lock"),
        repr=False, compare=False)

    def add(self, pass_name: Optional[str] = None, **deltas):
        with self._lock:
            for name, delta in deltas.items():
                setattr(self, name, getattr(self, name) + delta)
            if pass_name is not None:
                per = self.passes.setdefault(
                    pass_name, {k: 0.0 for k in self._PASS_KEYS})
                for name, delta in deltas.items():
                    if name in per:
                        per[name] += delta

    def saw_live_buffers(self, n: int):
        with self._lock:
            self.max_live_buffers = max(self.max_live_buffers, n)

    @property
    def chunks_per_sec(self) -> float:
        return self.chunks / self.wall_s if self.wall_s > 0 else 0.0

    @property
    def stall_fraction(self) -> float:
        """Fraction of streamed wall time the consumer spent starved."""
        return self.stall_s / self.wall_s if self.wall_s > 0 else 0.0

    @staticmethod
    def _overlap(stall_s: float, fill_s: float, wall_s: float) -> float:
        """Overlap achieved in the post-fill window: 1 means the
        consumer never waited for a chunk after the pipeline primed
        (transfer fully hidden behind compute), 0 means every chunk
        was waited for in-line (serial)."""
        busy = wall_s - fill_s
        if busy <= 0.0:
            return 1.0
        return max(0.0, min(1.0, 1.0 - stall_s / busy))

    @property
    def overlap_fraction(self) -> float:
        return self._overlap(self.stall_s, self.fill_s, self.wall_s)

    def pass_summary(self) -> dict:
        """Per-pass counters with derived stall/overlap fractions."""
        with self._lock:
            snap = {name: dict(per) for name, per in self.passes.items()}
        out = {}
        for name, per in snap.items():
            wall = per["wall_s"]
            out[name] = dict(
                bytes_streamed=int(per["bytes_streamed"]),
                chunks=int(per["chunks"]),
                stall_s=round(per["stall_s"], 4),
                fill_s=round(per["fill_s"], 4),
                wall_s=round(wall, 4),
                stall_fraction=round(
                    per["stall_s"] / wall if wall > 0 else 0.0, 4),
                overlap_frac=round(self._overlap(
                    per["stall_s"], per["fill_s"], wall), 4))
        return out

    def summary(self) -> dict:
        return dict(bytes_streamed=int(self.bytes_streamed),
                    chunks=int(self.chunks),
                    chunks_per_sec=round(self.chunks_per_sec, 3),
                    stall_fraction=round(self.stall_fraction, 4),
                    overlap_frac=round(self.overlap_fraction, 4),
                    fill_s=round(self.fill_s, 4),
                    max_live_buffers=int(self.max_live_buffers),
                    passes=self.pass_summary())


class StepsPerSecond:
    """Streaming steps/sec meter for host-side optimizer loops.

    The clock starts at the first :meth:`tick`, so call
    :meth:`reset` right after the first (compile) step completes —
    otherwise ``rate`` averages the one-time trace/compile cost into
    steady state and under-reports throughput for short fits (the
    host loops in ``optim/adam.run_adam_streamed`` do exactly this).
    """

    def __init__(self):
        self.t0: Optional[float] = None
        self.steps = 0

    def tick(self, n: int = 1):
        if self.t0 is None:
            self.t0 = time.perf_counter()
        self.steps += n

    def reset(self):
        """Zero the step count and restart the clock NOW.

        Call at the end of a warm-up/compile step: every subsequently
        ticked step is then measured over its full duration (a tick
        marks a step's END, so a clock started *at* the first tick
        would miss that step's duration and overstate the rate by
        ``steps/(steps-1)`` — degenerately so for short fits).
        """
        self.t0 = time.perf_counter()
        self.steps = 0

    @property
    def rate(self) -> float:
        if self.t0 is None or self.steps == 0:
            return 0.0
        return self.steps / (time.perf_counter() - self.t0)
