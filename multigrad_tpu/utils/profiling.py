"""Profiling and timing helpers.

The reference's only instrumentation is wall-clock timing with an
explicit JIT warm-up run (``tests/smf_example/benchmark.py:41-46``)
and ``time.time`` around fits (SURVEY §5.1).  This module keeps that
warm-up-then-time shape and adds ``jax.profiler`` trace capture for
TPU work (op-level timelines viewable in TensorBoard/Perfetto).
"""
from __future__ import annotations

import contextlib
import time
from typing import Callable, Optional

import jax


class Timer:
    """Warm-up-then-time harness (the reference benchmark's shape)."""

    def __init__(self, fn: Callable, warmup: int = 1):
        self.fn = fn
        self.warmup = warmup

    def __call__(self, n_calls: int, *args, **kwargs):
        for _ in range(self.warmup):
            jax.block_until_ready(self.fn(*args, **kwargs))
        t0 = time.perf_counter()
        out = None
        for _ in range(n_calls):
            out = self.fn(*args, **kwargs)
        jax.block_until_ready(out)
        elapsed = time.perf_counter() - t0
        return dict(calls_per_sec=n_calls / elapsed, elapsed=elapsed,
                    n_calls=n_calls)


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/multigrad_tpu_trace",
          perfetto: bool = False):
    """Capture a ``jax.profiler`` trace around a block.

    View with TensorBoard's profile plugin or Perfetto.  With
    ``perfetto=True`` a self-contained ``*.trace.json.gz`` is also
    written — parseable without TensorBoard (used by
    ``examples/roofline_trace.py`` to aggregate per-op device time).
    """
    jax.profiler.start_trace(log_dir, create_perfetto_trace=perfetto)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


class StepsPerSecond:
    """Streaming steps/sec meter for host-side optimizer loops."""

    def __init__(self):
        self.t0: Optional[float] = None
        self.steps = 0

    def tick(self, n: int = 1):
        if self.t0 is None:
            self.t0 = time.perf_counter()
        self.steps += n

    @property
    def rate(self) -> float:
        if self.t0 is None or self.steps == 0:
            return 0.0
        return self.steps / (time.perf_counter() - self.t0)
