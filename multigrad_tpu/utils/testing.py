"""Shared exactness fixtures for bitwise-equivalence verification.

The sharded-K equivalence claims ("the (replica, data) layout
reproduces the flat layout bit-for-bit") need a model whose
arithmetic is EXACT regardless of how the mesh associates its
reductions — float sums of arbitrary values round differently when
the data axis is 2-wide vs 8-wide, so a real model can only be
compared to tolerance.  :func:`make_exact_shard_model` builds the
one regime where the bitwise claim is meaningful:

* every nonzero catalog value is the same power of two (``2**-10``),
  so partial sums within a shard are exact in any association;
* the nonzero rows all land on data-shard 0 of ANY layout (row-major
  ``scatter_nd`` split), so every cross-shard psum only ever adds
  zeros — exact for any participant count and reduction order.

Used by ``tests/test_sharded_k.py``, ``bench.py``'s
``ensemble_sharded_k_sweep`` config and
``examples/sharded_ensemble_demo.py`` — one construction, one place
to keep the exactness argument honest.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..core.model import OnePointModel
from ..parallel.collectives import scatter_nd

__all__ = ["ExactShardModel", "make_exact_shard_model",
           "bitwise_trajectory_pair"]


@dataclass
class ExactShardModel(OnePointModel):
    """Linear sumstats + quadratic loss over shard-0-only mass (see
    module docstring for why this is exact in any association)."""

    aux_data: dict = field(default_factory=dict)

    def calc_partial_sumstats_from_params(self, params, randkey=None):
        return jnp.sum(jnp.asarray(self.aux_data["x"])) * params

    def calc_loss_from_sumstats(self, sumstats, sumstats_aux=None,
                                randkey=None):
        target = jnp.asarray(self.aux_data["target"])
        return jnp.sum((sumstats - target) ** 2)


def make_exact_shard_model(comm, n_devices: int = None
                           ) -> ExactShardModel:
    """An :class:`ExactShardModel` over `comm` whose reductions are
    exact in any association and participant count: 64 rows of
    ``2**-10`` (all on data-shard 0), zeros elsewhere."""
    if n_devices is None:
        n_devices = len(jax.devices())
    x = np.zeros(64 * int(n_devices), np.float32)
    x[:64] = 2.0 ** -10
    x = scatter_nd(jnp.asarray(x), axis=0, comm=comm, pad_value=0.0)
    scale = 64 * 2.0 ** -10
    return ExactShardModel(aux_data=dict(
        x=x, target=jnp.asarray([scale * -1.5, scale * 0.4])),
        comm=comm)


def bitwise_trajectory_pair(comm_replicated, comm_sharded,
                            k: int = 8, nsteps: int = 12,
                            learning_rate: float = 0.05,
                            n_devices: int = None):
    """The canonical sharded-vs-replicated equivalence protocol.

    Runs the SAME `(k, 2)` batched Adam scan over an
    :func:`make_exact_shard_model` twice — replicated on
    ``comm_replicated``, K-partitioned (sharded wrapper +
    ZeRO-sharded carry) on ``comm_sharded`` — and returns the two
    trajectories.  With the exact fixture they must be bit-identical
    (``np.array_equal``); the one comparison block the test suite,
    ``bench.py``'s ``ensemble_sharded_k_sweep`` and the demo all
    share, so the proof cannot drift between its three consumers.
    """
    from ..inference.ensemble import batched_fit_wrapper
    from ..optim import adam as _adam

    inits = jnp.asarray(np.column_stack(
        [np.linspace(-2.0, -1.0, int(k)),
         np.linspace(0.3, 0.8, int(k))]).astype(np.float32))
    m_rep = make_exact_shard_model(comm_replicated,
                                   n_devices=n_devices)
    m_sh = make_exact_shard_model(comm_sharded, n_devices=n_devices)
    t_rep = _adam.run_adam_scan(
        batched_fit_wrapper(m_rep, False), inits, nsteps=nsteps,
        learning_rate=learning_rate, progress=False,
        fn_args=(m_rep.aux_leaves(),))
    ks = m_sh.k_sharding(2)
    t_sh = _adam.run_adam_scan(
        batched_fit_wrapper(m_sh, False, k_sharded=True),
        jax.device_put(inits, ks), nsteps=nsteps,
        learning_rate=learning_rate, progress=False,
        fn_args=(m_sh.aux_leaves(),), carry_sharding=ks)
    return t_rep, t_sh
