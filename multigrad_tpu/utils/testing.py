"""Shared test harnesses: exactness fixtures + interleaving replay.

Two unrelated-but-shared test facilities live here:

**Exactness fixtures** (below): the sharded-K bitwise-equivalence
model.

**Deterministic-interleaving harness** (:class:`InterleaveController`
/ :func:`run_interleavings`): every serve-era race in this repo's
history — the PR-10 ``_purge_cancelled`` producer deadlock, the PR-9
sink re-entrancy, the PR-11 first-wins duplicate result — was found
by *review*, because the thread schedule that triggers it almost
never happens under test load.  The harness makes those schedules
enumerable: worker callables yield at **scheduling points** (explicit
:func:`~multigrad_tpu.utils.lockdep.sched_point` calls, plus — with
lockdep enabled — every contended wrapped-lock acquisition,
automatically), and a controller replays the workers under a chosen
permutation, one thread running at a time.  A schedule under which
every live thread is parked outside a scheduling point and nothing
changes for the deadlock window is reported as **deadlocked**, with
each stuck thread's stack — turning "found in review" races into
regression tests (``tests/test_concurrency.py`` replays the queue
submit/take_group/cancel triangle and the historical bug fixtures).

The sharded-K equivalence claims ("the (replica, data) layout
reproduces the flat layout bit-for-bit") need a model whose
arithmetic is EXACT regardless of how the mesh associates its
reductions — float sums of arbitrary values round differently when
the data axis is 2-wide vs 8-wide, so a real model can only be
compared to tolerance.  :func:`make_exact_shard_model` builds the
one regime where the bitwise claim is meaningful:

* every nonzero catalog value is the same power of two (``2**-10``),
  so partial sums within a shard are exact in any association;
* the nonzero rows all land on data-shard 0 of ANY layout (row-major
  ``scatter_nd`` split), so every cross-shard psum only ever adds
  zeros — exact for any participant count and reduction order.

Used by ``tests/test_sharded_k.py``, ``bench.py``'s
``ensemble_sharded_k_sweep`` config and
``examples/sharded_ensemble_demo.py`` — one construction, one place
to keep the exactness argument honest.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..core.model import OnePointModel
from ..parallel.collectives import scatter_nd

__all__ = ["ExactShardModel", "make_exact_shard_model",
           "bitwise_trajectory_pair"]


@dataclass
class ExactShardModel(OnePointModel):
    """Linear sumstats + quadratic loss over shard-0-only mass (see
    module docstring for why this is exact in any association)."""

    aux_data: dict = field(default_factory=dict)

    def calc_partial_sumstats_from_params(self, params, randkey=None):
        return jnp.sum(jnp.asarray(self.aux_data["x"])) * params

    def calc_loss_from_sumstats(self, sumstats, sumstats_aux=None,
                                randkey=None):
        target = jnp.asarray(self.aux_data["target"])
        return jnp.sum((sumstats - target) ** 2)


def make_exact_shard_model(comm, n_devices: int = None
                           ) -> ExactShardModel:
    """An :class:`ExactShardModel` over `comm` whose reductions are
    exact in any association and participant count: 64 rows of
    ``2**-10`` (all on data-shard 0), zeros elsewhere."""
    if n_devices is None:
        n_devices = len(jax.devices())
    x = np.zeros(64 * int(n_devices), np.float32)
    x[:64] = 2.0 ** -10
    x = scatter_nd(jnp.asarray(x), axis=0, comm=comm, pad_value=0.0)
    scale = 64 * 2.0 ** -10
    return ExactShardModel(aux_data=dict(
        x=x, target=jnp.asarray([scale * -1.5, scale * 0.4])),
        comm=comm)


def bitwise_trajectory_pair(comm_replicated, comm_sharded,
                            k: int = 8, nsteps: int = 12,
                            learning_rate: float = 0.05,
                            n_devices: int = None):
    """The canonical sharded-vs-replicated equivalence protocol.

    Runs the SAME `(k, 2)` batched Adam scan over an
    :func:`make_exact_shard_model` twice — replicated on
    ``comm_replicated``, K-partitioned (sharded wrapper +
    ZeRO-sharded carry) on ``comm_sharded`` — and returns the two
    trajectories.  With the exact fixture they must be bit-identical
    (``np.array_equal``); the one comparison block the test suite,
    ``bench.py``'s ``ensemble_sharded_k_sweep`` and the demo all
    share, so the proof cannot drift between its three consumers.
    """
    from ..inference.ensemble import batched_fit_wrapper
    from ..optim import adam as _adam

    inits = jnp.asarray(np.column_stack(
        [np.linspace(-2.0, -1.0, int(k)),
         np.linspace(0.3, 0.8, int(k))]).astype(np.float32))
    m_rep = make_exact_shard_model(comm_replicated,
                                   n_devices=n_devices)
    m_sh = make_exact_shard_model(comm_sharded, n_devices=n_devices)
    t_rep = _adam.run_adam_scan(
        batched_fit_wrapper(m_rep, False), inits, nsteps=nsteps,
        learning_rate=learning_rate, progress=False,
        fn_args=(m_rep.aux_leaves(),))
    ks = m_sh.k_sharding(2)
    t_sh = _adam.run_adam_scan(
        batched_fit_wrapper(m_sh, False, k_sharded=True),
        jax.device_put(inits, ks), nsteps=nsteps,
        learning_rate=learning_rate, progress=False,
        fn_args=(m_sh.aux_leaves(),), carry_sharding=ks)
    return t_rep, t_sh


# ------------------------------------------------------------------ #
# deterministic-interleaving harness
# ------------------------------------------------------------------ #
import itertools as _itertools          # noqa: E402
import sys as _sys                      # noqa: E402
import threading as _threading          # noqa: E402
import time as _time                    # noqa: E402
import traceback as _traceback          # noqa: E402

from .. import _lockdep                 # noqa: E402

__all__ += ["InterleaveOutcome", "InterleaveController",
            "run_interleavings", "default_schedules"]


class InterleaveOutcome:
    """Result of replaying one schedule.

    ``deadlocked`` is True when every live thread sat parked outside
    a scheduling point (a real lock wait, a condition wait) with no
    state change for the deadlock window — the harness's verdict
    that this schedule wedges.  ``stuck`` maps each such thread's
    name to its stack at verdict time; ``errors`` collects
    exceptions worker callables raised (a
    :class:`~multigrad_tpu.utils.lockdep.LockdepViolation` raised by
    a wrapped lock counts as a deadlock too — it is the detected
    form of one); ``trace`` is the ordered (thread, point-tag) log
    of scheduling points actually hit.
    """

    def __init__(self, schedule):
        self.schedule = tuple(schedule)
        self.deadlocked = False
        self.errors: list = []
        self.stuck: dict = {}
        self.trace: list = []

    def __repr__(self):
        state = "DEADLOCK" if self.deadlocked else (
            "errors" if self.errors else "ok")
        return (f"<InterleaveOutcome {state} "
                f"schedule={self.schedule}>")


class _TState:
    __slots__ = ("idx", "name", "status", "granted", "error",
                 "tag", "ident")

    def __init__(self, idx, name):
        self.idx = idx
        self.name = name
        self.status = "new"       # new/waiting/blocked/running/done/error
        self.granted = False
        self.error = None
        self.tag = None
        self.ident = None


class InterleaveController:
    """Replays N worker callables under one explicit interleaving.

    One thread runs at a time: each worker parks at every scheduling
    point (:func:`~multigrad_tpu.utils.lockdep.sched_point`, or a
    contended lockdep-wrapped lock acquisition) until the controller
    grants it the next turn per ``schedule`` — a sequence of thread
    indices cycled until every worker finishes.

    A granted thread that neither parks nor finishes within
    ``stall_timeout_s`` is *opaque-blocked* (e.g. inside a plain
    ``Condition.wait`` the harness cannot see into); the controller
    moves on and re-offers turns.  When every live thread is
    opaque-blocked or lock-blocked and nothing changes for
    ``deadlock_timeout_s``, the schedule is declared **deadlocked**
    and each stuck thread's stack is captured.
    """

    def __init__(self, stall_timeout_s: float = 0.05,
                 deadlock_timeout_s: float = 0.5):
        self.stall_timeout_s = float(stall_timeout_s)
        self.deadlock_timeout_s = float(deadlock_timeout_s)
        self._cv = _threading.Condition()
        self._states: list = []
        self._idents: dict = {}
        self._closed = False
        self._version = 0

    # -- worker-side hooks (lockdep protocol) --------------------------- #
    def managed(self, ident) -> bool:
        return not self._closed and ident in self._idents

    def point(self, tag=None):
        self._park(self._idents[_threading.get_ident()],
                   "waiting", tag)

    def blocked(self, lockname):
        self._park(self._idents[_threading.get_ident()],
                   "blocked", lockname)

    def _park(self, ts, status, tag):
        with self._cv:
            if self._closed:
                return
            ts.status = status
            ts.tag = tag
            self._version += 1
            self._cv.notify_all()
            while not ts.granted and not self._closed:
                self._cv.wait()
            ts.granted = False
            ts.status = "running"

    # -- controller side ------------------------------------------------ #
    def _worker(self, ts: _TState, fn, outcome: InterleaveOutcome):
        with self._cv:
            ts.ident = _threading.get_ident()
            self._idents[ts.ident] = ts
        self._park(ts, "waiting", "<start>")
        status, error = "done", None
        try:
            fn()
        except _lockdep.LockdepViolation as e:
            status, error = "error", e
        except BaseException as e:      # noqa: BLE001 — reported
            status, error = "error", e
        with self._cv:
            ts.status = status
            ts.error = error
            if error is not None:
                outcome.errors.append(error)
            self._version += 1
            self._cv.notify_all()

    def run(self, workers, schedule, names=None,
            timeout_s: float = 10.0) -> InterleaveOutcome:
        """Run ``workers`` (callables) under ``schedule``; returns
        the :class:`InterleaveOutcome`.  Threads left stuck by a
        deadlock verdict are daemons and are abandoned."""
        outcome = InterleaveOutcome(schedule)
        self._states = [
            _TState(i, (names[i] if names else f"t{i}"))
            for i in range(len(workers))]
        _lockdep.set_controller(self)
        threads = []
        try:
            for ts, fn in zip(self._states, workers):
                t = _threading.Thread(
                    target=self._worker, args=(ts, fn, outcome),
                    daemon=True,
                    name=f"mgt-interleave-{ts.name}")
                threads.append(t)
                t.start()
            self._drive(schedule, outcome, timeout_s)
        finally:
            with self._cv:
                self._closed = True
                self._cv.notify_all()
            _lockdep.set_controller(None)
            for t in threads:
                t.join(timeout=0.2)
        return outcome

    def _drive(self, schedule, outcome, timeout_s):
        deadline = _time.monotonic() + timeout_s
        cycle = _itertools.cycle(schedule)
        quiet_since = None
        while _time.monotonic() < deadline:
            with self._cv:
                alive = [ts for ts in self._states
                         if ts.status not in ("done", "error")]
                if not alive:
                    return
                grantable = [ts for ts in alive
                             if ts.status in ("waiting", "blocked")]
            if grantable:
                quiet_since = None
                # next schedule entry that is grantable
                ts = None
                for _ in range(len(schedule)):
                    idx = next(cycle)
                    cand = self._states[idx]
                    if cand in grantable:
                        ts = cand
                        break
                if ts is None:
                    ts = grantable[0]
                if ts.status == "waiting":
                    outcome.trace.append((ts.name, ts.tag))
                self._grant(ts)
                continue
            # nothing grantable: either some thread is genuinely
            # computing, or everything is opaque-blocked -> deadlock
            with self._cv:
                v = self._version
                self._cv.wait(self.stall_timeout_s)
                if self._version != v:
                    quiet_since = None
                    continue
            now = _time.monotonic()
            if quiet_since is None:
                quiet_since = now
            elif now - quiet_since >= self.deadlock_timeout_s:
                self._declare_deadlock(outcome)
                return
        self._declare_deadlock(outcome)

    def _grant(self, ts: _TState):
        with self._cv:
            ts.granted = True
            self._cv.notify_all()
            deadline = _time.monotonic() + self.stall_timeout_s
            while (ts.granted or ts.status == "running"):
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return          # opaque-blocked; move on
                self._cv.wait(remaining)

    def _declare_deadlock(self, outcome: InterleaveOutcome):
        outcome.deadlocked = True
        frames = _sys._current_frames()
        with self._cv:
            for ts in self._states:
                if ts.status in ("done", "error"):
                    continue
                frame = frames.get(ts.ident)
                outcome.stuck[ts.name] = (
                    "".join(_traceback.format_stack(frame))
                    if frame is not None else "<no stack>")


def default_schedules(n_threads: int, max_schedules: int = 16):
    """A deterministic schedule set for ``n_threads`` workers: every
    starting-order permutation, plus doubled-turn variants (a thread
    running two points per turn exposes different windows)."""
    perms = list(_itertools.permutations(range(n_threads)))
    doubled = [tuple(x for x in p for _ in range(2))
               for p in perms]
    out = perms + doubled
    return out[:max_schedules]


def run_interleavings(build, schedules=None, n_threads=None,
                      stall_timeout_s: float = 0.05,
                      deadlock_timeout_s: float = 0.5,
                      timeout_s: float = 10.0):
    """Replay a scenario under many schedules.

    ``build()`` must return a fresh list of worker callables (with
    fresh shared state closed over) per call; ``schedules`` defaults
    to :func:`default_schedules` over the worker count.  Returns the
    list of :class:`InterleaveOutcome`\\ s — assert
    ``not any(o.deadlocked for o in outcomes)`` for a fixed
    implementation, ``any(...)`` for a seeded-bug fixture.
    """
    outcomes = []
    first = build()
    if schedules is None:
        schedules = default_schedules(
            n_threads if n_threads is not None else len(first))
    workers = first
    for i, schedule in enumerate(schedules):
        if workers is None:
            workers = build()
        ctrl = InterleaveController(
            stall_timeout_s=stall_timeout_s,
            deadlock_timeout_s=deadlock_timeout_s)
        outcomes.append(ctrl.run(workers, schedule,
                                 timeout_s=timeout_s))
        workers = None
    return outcomes
