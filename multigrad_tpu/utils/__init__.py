from .util import (GradDescentResult, latin_hypercube_sampler,
                   pad_to_multiple, scatter_nd, simple_grad_descent,
                   simple_grad_descent_scan)
from . import checkpoint, debug, diffdesi, profiling

__all__ = [
    "debug",
    "GradDescentResult", "latin_hypercube_sampler", "pad_to_multiple",
    "scatter_nd", "simple_grad_descent", "simple_grad_descent_scan",
    "checkpoint", "diffdesi", "profiling",
]
