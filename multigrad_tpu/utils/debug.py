"""Debug-mode invariant checks for SPMD programs.

The reference's correctness rests on every MPI rank executing
identical collective sequences, enforced only by code structure
(SURVEY §5.2: root-driven command loops, no race detection).  Under
SPMD most divergence bugs are compile-time shape/type errors, but one
class survives: a value that *should* be replicated across a mesh
axis (params, losses, optimizer state) silently varying because some
shard-local quantity leaked in.  These helpers make that an explicit,
checkable invariant inside jitted code.

Usage (inside ``shard_map``/the model's SPMD program)::

    from multigrad_tpu.utils import debug
    debug.assert_replicated(params, "data")          # raises if not
    spread = debug.replication_spread(params, "data")  # 0.0 iff ok
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def replication_spread(tree, axis_name):
    """Max absolute per-element spread of `tree` across `axis_name`.

    ``max_leaves max_elements |pmax - pmin|`` — exactly 0 iff every
    device on the axis holds bit-identical values (the reference's
    implicit invariant for params/losses after its allreduces).
    """
    leaves = jax.tree_util.tree_leaves(tree)
    spreads = []
    for leaf in leaves:
        # Compute in the leaf's own dtype — a float32 cast would hide
        # divergence below float32 resolution (f64 leaks, big ints).
        leaf = jnp.asarray(leaf)
        if not jnp.issubdtype(leaf.dtype, jnp.number):
            leaf = leaf.astype(jnp.int32)
        diff = lax.pmax(leaf, axis_name) - lax.pmin(leaf, axis_name)
        spreads.append(jnp.max(jnp.abs(diff)).astype(jnp.float32))
    return jnp.max(jnp.stack(spreads)) if spreads \
        else jnp.zeros(())


def _raise_if_spread(spread, tol, name):
    import numpy as np
    if float(np.asarray(spread)) > tol:
        raise AssertionError(
            f"replication invariant violated: {name} varies across "
            f"the mesh axis by {float(np.asarray(spread)):.3e} "
            f"(tol={tol:.3e})")
    return np.zeros((), np.float32)


def assert_replicated(tree, axis_name, tol: float = 0.0,
                      name: str = "value"):
    """In-graph assertion that `tree` is replicated over `axis_name`.

    Works under ``jit``/``shard_map`` via a host callback: the check
    runs on-device (one pmax/pmin pair per leaf) and only the scalar
    spread crosses to the host.  On violation an ``AssertionError``
    surfaces through the XLA runtime as a catchable error; subsequent
    computation continues normally.  (``io_callback`` rather than
    ``debug.callback``: the latter's raised exceptions break later
    dispatches.  On some runtimes a cosmetic "exception ignored"
    notice from the runtime's pending-callback token may still print
    at interpreter shutdown; it does not affect results or exit
    status.)

    Returns `tree` unchanged so it can be inserted into dataflow
    (``params = assert_replicated(params, "data")``).
    """
    from functools import partial

    from jax.experimental import io_callback

    spread = replication_spread(tree, axis_name)
    io_callback(partial(_raise_if_spread, tol=tol, name=name),
                jax.ShapeDtypeStruct((), jnp.float32), spread)
    return tree
