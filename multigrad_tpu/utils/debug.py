"""Debug-mode invariant checks for SPMD programs.

The reference's correctness rests on every MPI rank executing
identical collective sequences, enforced only by code structure
(SURVEY §5.2: root-driven command loops, no race detection).  Under
SPMD most divergence bugs are compile-time shape/type errors, but one
class survives: a value that *should* be replicated across a mesh
axis (params, losses, optimizer state) silently varying because some
shard-local quantity leaked in.  These helpers make that an explicit,
checkable invariant inside jitted code.

Usage (inside ``shard_map``/the model's SPMD program)::

    from multigrad_tpu.utils import debug
    debug.assert_replicated(params, "data")          # raises if not
    spread = debug.replication_spread(params, "data")  # 0.0 iff ok
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def replication_spread(tree, axis_name):
    """Max absolute per-element spread of `tree` across `axis_name`.

    ``max_leaves max_elements |pmax - pmin|`` — exactly 0 iff every
    device on the axis holds bit-identical values (the reference's
    implicit invariant for params/losses after its allreduces).
    """
    leaves = jax.tree_util.tree_leaves(tree)
    spreads = []
    for leaf in leaves:
        # Compute in the leaf's own dtype — a float32 cast would hide
        # divergence below float32 resolution (f64 leaks, big ints).
        leaf = jnp.asarray(leaf)
        if not jnp.issubdtype(leaf.dtype, jnp.number):
            leaf = leaf.astype(jnp.int32)
        diff = lax.pmax(leaf, axis_name) - lax.pmin(leaf, axis_name)
        spreads.append(jnp.max(jnp.abs(diff)).astype(jnp.float32))
    return jnp.max(jnp.stack(spreads)) if spreads \
        else jnp.zeros(())


# Violations recorded by in-graph checks, drained by
# :func:`check_replication`.  Raising *inside* an io_callback would
# poison the runtime's pending-callback token and leave an "Exception
# ignored in atexit callback" traceback at interpreter exit, so the
# callback only records and the raise happens host-side.
_pending_violations: list = []


def _record_spread(spread, tol, name):
    import numpy as np
    value = float(np.asarray(spread))
    if value > tol:
        _pending_violations.append(
            f"{name} varies across the mesh axis by {value:.3e} "
            f"(tol={tol:.3e})")
    return np.zeros((), np.float32)


def assert_replicated(tree, axis_name, tol: float = 0.0,
                      name: str = "value"):
    """In-graph replication check over `axis_name`.

    Works under ``jit``/``shard_map`` via a host callback: the check
    runs on-device (one pmax/pmin pair per leaf) and only the scalar
    spread crosses to the host.  A violation is *recorded* host-side;
    call :func:`check_replication` after the program (typically right
    after fetching its results) to raise.  The callback itself never
    raises — that would leave the runtime's callback token carrying a
    pending exception into interpreter shutdown.

    Returns `tree` unchanged so it can be inserted into dataflow
    (``params = assert_replicated(params, "data")``).
    """
    from functools import partial

    from jax.experimental import io_callback

    spread = replication_spread(tree, axis_name)
    io_callback(partial(_record_spread, tol=tol, name=name),
                jax.ShapeDtypeStruct((), jnp.float32), spread)
    return tree


def check_replication():
    """Raise if any in-graph :func:`assert_replicated` recorded a
    violation; clears the record either way.

    Waits on ``jax.effects_barrier()`` first, so callbacks from
    still-in-flight programs are counted — call it any time after the
    program was dispatched.
    """
    jax.effects_barrier()
    if _pending_violations:
        msgs = "; ".join(_pending_violations)
        _pending_violations.clear()
        raise AssertionError(
            f"replication invariant violated: {msgs}")


class replication_check:
    """Context manager form: ``with debug.replication_check(): run()``
    raises on exit if any check inside recorded a violation."""

    def __enter__(self):
        # Drain in-flight callbacks from earlier programs before
        # clearing, so the scope boundary is well-defined (an earlier
        # unchecked violation neither leaks into this block nor is
        # silently discarded mid-flight).
        jax.effects_barrier()
        if _pending_violations:
            import warnings
            warnings.warn(
                "replication_check: discarding unchecked violations "
                f"from before the block: {'; '.join(_pending_violations)}")
            _pending_violations.clear()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            check_replication()
        return False
