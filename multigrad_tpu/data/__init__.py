"""Streaming data subsystem: out-of-core catalogs with exact gradients.

Additive sumstats make the paper's data-parallel algebra sliceable in
*time* as well as space: :class:`StreamingOnePointModel` streams a
catalog of any length through the device mesh in fixed-size chunks —
double-buffered host→device prefetch overlapping transfer with
compute — and reproduces the resident model's loss and gradient
exactly (two-pass chunked VJP) or in one dispatch (in-graph
``lax.scan`` over HBM-resident chunks with per-chunk remat).

Layers:

* :mod:`.source` — :class:`CatalogSource` backends (in-memory,
  ``.npz``, ``np.memmap``) and the deterministic per-mesh-shard
  :class:`ChunkPlan`.
* :mod:`.prefetch` — :class:`ChunkPrefetcher`, the double-buffered
  background loader (≤ 2 device chunk buffers, stall accounting).
* :mod:`.streaming` — :class:`StreamingOnePointModel`, the user-facing
  wrapper with the two-pass and scan execution paths plus
  :meth:`~StreamingOnePointModel.run_adam`.
"""
from .source import (ArraySource, CatalogSource, ChunkPlan,  # noqa: F401
                     ChunkSpec, MemmapSource, NpzSource, as_source,
                     plan_chunks)
from .prefetch import ChunkPrefetcher, prefetch_chunks  # noqa: F401
from .streaming import StreamingOnePointModel  # noqa: F401

__all__ = [
    "CatalogSource", "ArraySource", "NpzSource", "MemmapSource",
    "ChunkSpec", "ChunkPlan", "plan_chunks", "as_source",
    "ChunkPrefetcher", "prefetch_chunks", "StreamingOnePointModel",
]
