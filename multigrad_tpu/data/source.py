"""Catalog sources and the deterministic chunk plan.

The paper's two-stage VJP chain rule makes communication
O(|sumstats| + |params|) independent of data size — and because the
sumstats are *additive*, the same algebra extends to time: a catalog
larger than aggregate HBM can be streamed through the device mesh in
chunks with exact totals and exact gradients
(:mod:`multigrad_tpu.data.streaming`).  This module supplies the two
host-side pieces that makes that deterministic:

* :class:`CatalogSource` — where catalog rows come from.  Three
  backends: in-memory arrays (:class:`ArraySource`), ``.npz`` archives
  (:class:`NpzSource`, a lazy-loading convenience), and
  ``np.memmap``/``.npy`` files (:class:`MemmapSource`, the true
  out-of-core path — reading a chunk touches only that chunk's pages).
* :class:`ChunkPlan` — the deterministic per-mesh-shard chunk
  geometry.  Every chunk has the SAME padded global shape
  ``(rows_per_chunk, ...)`` so one compiled program serves all chunks,
  and ``rows_per_chunk`` is a multiple of the comm size so
  ``jax.device_put`` with the comm's ``NamedSharding`` places shard
  ``s`` of chunk ``k`` at global rows
  ``[k·R + s·R/S, k·R + (s+1)·R/S)`` — contiguous blocks per device,
  the same layout :func:`multigrad_tpu.parallel.scatter_nd` gives a
  resident catalog.  The ragged final chunk is padded with the
  caller's neutral ``pad_value``, reusing the ``scatter_nd`` /
  :func:`~multigrad_tpu.utils.util.pad_to_multiple` pad convention
  (e.g. ``inf`` log-mass for the SMF's erf kernel: exactly zero
  contribution forward and backward).
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["CatalogSource", "ArraySource", "NpzSource", "MemmapSource",
           "ChunkSpec", "ChunkPlan", "plan_chunks", "as_source"]


@dataclass(frozen=True)
class ChunkSpec:
    """One chunk's global row range ``[start, stop)`` plus the rows of
    neutral padding appended to reach the plan's uniform chunk shape."""

    index: int
    start: int
    stop: int
    pad: int

    @property
    def rows(self) -> int:
        """Real (unpadded) rows in this chunk."""
        return self.stop - self.start


@dataclass(frozen=True)
class ChunkPlan:
    """Deterministic chunk geometry for an ``n_rows``-row catalog
    streamed over ``n_shards`` mesh shards.

    Every chunk spans ``rows_per_chunk = shard_rows * n_shards``
    global rows (the final one padded up to it), so a single compiled
    chunk program — whose shapes bake in ``(rows_per_chunk, ...)`` —
    serves the whole stream.
    """

    n_rows: int
    n_shards: int
    shard_rows: int
    chunks: Tuple[ChunkSpec, ...]

    @property
    def rows_per_chunk(self) -> int:
        return self.shard_rows * self.n_shards

    @property
    def n_chunks(self) -> int:
        return len(self.chunks)

    @property
    def pad_rows(self) -> int:
        """Total padding rows (all in the final chunk)."""
        return self.chunks[-1].pad if self.chunks else 0


def plan_chunks(n_rows: int, chunk_rows: int, n_shards: int = 1
                ) -> ChunkPlan:
    """Plan a stream of ``n_rows`` catalog rows in ``chunk_rows``-row
    chunks over ``n_shards`` mesh shards.

    ``chunk_rows`` is the *global* chunk size (rows per chunk summed
    over all shards); it is rounded up to the next multiple of
    ``n_shards`` so every shard receives equal rows per chunk — the
    XLA equal-shards constraint :func:`~multigrad_tpu.parallel
    .scatter_nd` documents.  Any ``n_rows >= 1`` works; the final
    chunk records how many padding rows its loader must append.
    """
    if n_rows < 1:
        raise ValueError(f"n_rows must be >= 1, got {n_rows}")
    if chunk_rows < 1:
        raise ValueError(f"chunk_rows must be >= 1, got {chunk_rows}")
    rows_per_chunk = -(-chunk_rows // n_shards) * n_shards
    n_chunks = -(-n_rows // rows_per_chunk)
    chunks = []
    for k in range(n_chunks):
        start = k * rows_per_chunk
        stop = min(n_rows, start + rows_per_chunk)
        chunks.append(ChunkSpec(index=k, start=start, stop=stop,
                                pad=rows_per_chunk - (stop - start)))
    return ChunkPlan(n_rows=n_rows, n_shards=n_shards,
                     shard_rows=rows_per_chunk // n_shards,
                     chunks=tuple(chunks))


class CatalogSource:
    """A host-side row source for streaming catalogs.

    Subclasses implement ``n_rows`` and :meth:`read`; everything else
    (chunk planning, padded chunk loading) is shared.  Rows are
    indexed along axis 0; trailing axes ride along unchanged.
    """

    @property
    def n_rows(self) -> int:
        raise NotImplementedError

    def read(self, start: int, stop: int) -> np.ndarray:
        """Rows ``[start, stop)`` as a host numpy array."""
        raise NotImplementedError

    def __len__(self) -> int:
        return self.n_rows

    def plan(self, chunk_rows: int, n_shards: int = 1) -> ChunkPlan:
        return plan_chunks(self.n_rows, chunk_rows, n_shards)

    def load_chunk(self, spec: ChunkSpec, pad_value=np.inf) -> np.ndarray:
        """Load one planned chunk, padded to the plan's uniform shape.

        ``pad_value`` must be neutral for the model's sumstats — the
        same contract as ``scatter_nd(pad_value=...)`` (its docstring
        explains why no universal default exists; ``inf`` is correct
        for erf-CDF counts and is the conventional choice here).
        """
        rows = np.asarray(self.read(spec.start, spec.stop))
        if spec.pad:
            pad_width = [(0, spec.pad)] + [(0, 0)] * (rows.ndim - 1)
            rows = np.pad(rows, pad_width, constant_values=pad_value)
        return rows


class ArraySource(CatalogSource):
    """In-memory catalog: wraps an array already resident on the host."""

    def __init__(self, array):
        self._array = np.asarray(array)

    @property
    def n_rows(self) -> int:
        return self._array.shape[0]

    def read(self, start: int, stop: int) -> np.ndarray:
        return self._array[start:stop]


def _npz_member_shape(archive, field) -> tuple:
    """Shape of one npz member from its ``.npy`` header alone.

    ``archive[field].shape`` would decompress the whole member just to
    throw it away; the shape lives in the member's uncompressed npy
    header, so read that.  Falls back to the full read if the header
    walk hits an unexpected layout (non-standard writer).
    """
    try:
        with archive.zip.open(field + ".npy") as f:
            version = np.lib.format.read_magic(f)
            if version == (1, 0):
                shape, _, _ = np.lib.format.read_array_header_1_0(f)
            elif version == (2, 0):
                shape, _, _ = np.lib.format.read_array_header_2_0(f)
            else:
                raise ValueError(f"npy format {version}")
        return shape
    except (AttributeError, KeyError, OSError, ValueError):
        # The expected nonstandard-writer failures: no `.zip` handle
        # on this numpy (AttributeError), member not stored under
        # `<field>.npy` (KeyError), a header/magic layout the fast
        # path does not understand (ValueError), or a short read
        # (OSError).  The full decompression below is the
        # authoritative answer for all of them; anything else — a
        # truly corrupt archive, a real bug — propagates (it would
        # fail the fallback too).
        return archive[field].shape


class NpzSource(CatalogSource):
    """One array of an ``.npz`` archive, loaded lazily.

    Convenience backend: ``np.load`` decompresses the named field once
    on first access and the decompressed array is kept (npz is
    zip-compressed, so it cannot be memory-mapped).  For catalogs that
    must never be host-resident in full, use :class:`MemmapSource`.
    """

    def __init__(self, path: str, field: str):
        self.path = path
        self.field = field
        self._array: Optional[np.ndarray] = None
        with np.load(path) as archive:  # validate early, load lazily
            if field not in archive.files:
                raise KeyError(
                    f"field {field!r} not in {path!r} "
                    f"(has {archive.files})")
            self._shape = _npz_member_shape(archive, field)

    def _load(self) -> np.ndarray:
        if self._array is None:
            with np.load(self.path) as archive:
                self._array = archive[self.field]
        return self._array

    @property
    def n_rows(self) -> int:
        return self._shape[0]

    def read(self, start: int, stop: int) -> np.ndarray:
        return self._load()[start:stop]


class MemmapSource(CatalogSource):
    """Out-of-core catalog backed by ``np.memmap``.

    ``.npy`` files open via ``np.load(mmap_mode="r")`` (shape/dtype
    from the header); raw binary files need explicit ``dtype`` and
    ``shape``.  Reading a chunk copies just that chunk's rows off
    disk — host memory stays O(chunk), which is what lets a catalog
    larger than host RAM stream through a fit.
    """

    def __init__(self, path: str, dtype=None, shape: Optional[Sequence[int]]
                 = None, offset: int = 0):
        self.path = path
        if os.path.splitext(path)[1] == ".npy":
            self._mm = np.load(path, mmap_mode="r")
        else:
            if dtype is None or shape is None:
                raise ValueError(
                    "raw memmap needs explicit dtype= and shape= "
                    "(a .npy file carries them in its header)")
            self._mm = np.memmap(path, dtype=dtype, mode="r",
                                 shape=tuple(shape), offset=offset)

    @property
    def n_rows(self) -> int:
        return self._mm.shape[0]

    def read(self, start: int, stop: int) -> np.ndarray:
        # np.array (not asarray): force the copy out of the mapping so
        # the returned chunk is plain host memory jax can transfer
        # from, and page cache pressure stays bounded by the chunk.
        return np.array(self._mm[start:stop])


def as_source(obj) -> CatalogSource:
    """Coerce ``obj`` into a :class:`CatalogSource`.

    Accepts an existing source (returned as-is), an array-like
    (wrapped in :class:`ArraySource`), or a path string: ``.npy`` maps
    to :class:`MemmapSource`; ``.npz`` paths need a field name, so
    construct :class:`NpzSource` explicitly.
    """
    if isinstance(obj, CatalogSource):
        return obj
    if isinstance(obj, str):
        ext = os.path.splitext(obj)[1]
        if ext == ".npy":
            return MemmapSource(obj)
        raise ValueError(
            f"cannot infer a source from path {obj!r}; use "
            "NpzSource(path, field) or MemmapSource(path, dtype, shape)")
    return ArraySource(obj)
