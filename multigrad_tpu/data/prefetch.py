"""Double-buffered background host→device chunk prefetch.

The streaming loss/grad passes (:mod:`multigrad_tpu.data.streaming`)
consume catalog chunks one at a time.  Dispatch on a JAX backend is
asynchronous, so the overlap discipline of "Scalable Training of
Language Models using JAX pjit and TPUv4" (arXiv 2204.06514) — hide
host→device transfer of step k+1 behind compute on step k — needs
only a loader thread running one chunk ahead of the consumer:

    loader thread:   read chunk k+1 from the source, `jax.device_put`
                     it with the comm's `NamedSharding` (each shard's
                     rows go straight to its device)
    consumer:        dispatch compute on chunk k (returns immediately,
                     device crunches while the loader reads/transfers)

HBM is capped at ``max_buffers`` (= 2: double buffering) live chunk
buffers *held by the prefetcher* via a semaphore the consumer releases
when it moves past a chunk: one buffer under compute, one in
flight/ready.  The consumer drops its reference to chunk k when it
takes k+1, so k's HBM is reclaimable the moment its compute retires —
the backend-portable equivalent of buffer donation (and the chunked
programs additionally donate their chunk arguments on TPU/GPU, see
``core/model.py``).

Both passes of the streamed loss-and-grad go through this machinery —
constructing a :class:`ChunkPrefetcher` starts its loader thread
immediately, so the *backward* (VJP) re-stream's first chunks load
while the host is still computing the loss and the O(|y|) cotangent
from pass 1's totals, and chunk k+1 of the re-stream transfers while
the VJP of chunk k runs.  Counters (bytes streamed, chunks/s,
prefetch-stall time) land in a :class:`multigrad_tpu.utils.profiling
.StreamStats`, split per pass via ``pass_name`` so the stall/overlap
of the forward and backward streams are separately visible.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Optional

import jax

from .._lockdep import make_lock
from ..utils.profiling import StreamStats

__all__ = ["ChunkPrefetcher", "prefetch_chunks"]

_DONE = object()


class ChunkPrefetcher:
    """Iterate device-resident chunks, loading one ahead in background.

    The loader thread starts at CONSTRUCTION time, not first
    iteration: build the prefetcher as soon as the chunk schedule is
    known and its first transfers overlap whatever the host does
    before consuming (the streamed VJP pass exploits exactly this —
    its prefetcher is built before the cotangent computation).

    Parameters
    ----------
    load_fn : callable
        ``load_fn(k) -> host pytree`` for chunk index ``k`` — e.g. a
        closure over :meth:`CatalogSource.load_chunk`.  Runs on the
        loader thread; must be thread-safe with the consumer (sources
        are read-only, so they are).
    n_chunks : int
        Number of chunks in the stream.
    sharding : optional
        A sharding (or pytree of shardings matching ``load_fn``'s
        return) passed to ``jax.device_put`` — typically
        ``comm.sharding(axis=0, ndim=...)`` so each mesh shard
        receives its rows directly.  ``None`` places chunks on the
        default device.
    max_buffers : int
        Device chunk buffers the prefetcher may hold at once.  2 is
        double buffering (the default and the intended operating
        point); 1 degenerates to fully-serial load→compute.
    stats : StreamStats, optional
        Counter sink; a fresh one is created when omitted.
    pass_name : str, optional
        Label under which this stream's counters are split in
        ``stats.passes`` (e.g. "sumstats" / "vjp").
    """

    def __init__(self, load_fn: Callable, n_chunks: int, sharding=None,
                 max_buffers: int = 2,
                 stats: Optional[StreamStats] = None,
                 pass_name: Optional[str] = None):
        if max_buffers < 1:
            raise ValueError("max_buffers must be >= 1")
        self.load_fn = load_fn
        self.n_chunks = n_chunks
        self.sharding = sharding
        self.stats = stats if stats is not None else StreamStats()
        self.pass_name = pass_name
        self._tokens = threading.Semaphore(max_buffers)
        self._live = 0
        self._live_lock = make_lock(
            "data.prefetch.ChunkPrefetcher._live_lock")
        self._queue: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._producer,
                                        daemon=True,
                                        name="mgt-chunk-prefetch")
        self._thread.start()

    # -- loader thread ------------------------------------------------------
    def _producer(self):
        try:
            for k in range(self.n_chunks):
                self._tokens.acquire()
                if self._stop.is_set():
                    return
                host = self.load_fn(k)
                nbytes = sum(
                    getattr(leaf, "nbytes", 0)
                    for leaf in jax.tree_util.tree_leaves(host))
                if self.sharding is None:
                    dev = jax.device_put(host)
                else:
                    dev = jax.device_put(host, self.sharding)
                with self._live_lock:
                    self._live += 1
                    live = self._live
                self.stats.saw_live_buffers(live)
                self.stats.add(self.pass_name, bytes_streamed=nbytes,
                               chunks=1)
                self._queue.put((k, dev))
            self._queue.put(_DONE)
        except BaseException as e:  # surface on the consumer side
            self._queue.put(e)

    # -- consumer side ------------------------------------------------------
    def __iter__(self):
        t_start = time.perf_counter()
        first = True
        try:
            for _ in range(self.n_chunks):
                t0 = time.perf_counter()
                item = self._queue.get()
                waited = time.perf_counter() - t0
                if item is _DONE:
                    break
                if isinstance(item, BaseException):
                    raise item
                self.stats.add(self.pass_name, fill_s=waited) if first \
                    else self.stats.add(self.pass_name, stall_s=waited)
                first = False
                k, dev = item
                yield k, dev
                # Consumer moved on: drop our ref, free a buffer slot.
                dev = None  # noqa: F841
                with self._live_lock:
                    self._live -= 1
                self._tokens.release()
        finally:
            self.stats.add(self.pass_name,
                           wall_s=time.perf_counter() - t_start)
            self.close()

    def close(self):
        """Stop the loader and unblock it if it is waiting on a slot."""
        self._stop.set()
        self._tokens.release()
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _serial_chunks(load_fn, n_chunks, sharding, stats, pass_name):
    t_start = time.perf_counter()
    try:
        for k in range(n_chunks):
            t0 = time.perf_counter()
            host = load_fn(k)
            dev = jax.device_put(host) if sharding is None \
                else jax.device_put(host, sharding)
            stats.add(pass_name, bytes_streamed=sum(
                getattr(leaf, "nbytes", 0)
                for leaf in jax.tree_util.tree_leaves(host)),
                chunks=1,
                **({"fill_s": time.perf_counter() - t0} if k == 0
                   else {"stall_s": time.perf_counter() - t0}))
            stats.saw_live_buffers(1)
            yield k, dev
    finally:
        stats.add(pass_name, wall_s=time.perf_counter() - t_start)


def prefetch_chunks(load_fn, n_chunks, sharding=None, prefetch=True,
                    stats: Optional[StreamStats] = None,
                    pass_name: Optional[str] = None):
    """Iterable of ``(k, device_chunk)`` for every chunk of a stream.

    With ``prefetch=True`` (default) returns a live
    :class:`ChunkPrefetcher` — its loader thread starts IMMEDIATELY,
    so construct it right when the schedule is known and the first
    chunks' host→device transfers overlap whatever work precedes
    consumption.  With ``prefetch=False`` a lazy generator loads and
    transfers chunks synchronously in the consumer's thread — the
    debugging/baseline path the bench's prefetch-stall and overlap
    numbers are measured against.  ``pass_name`` labels this stream's
    split in ``stats.passes``.
    """
    stats = stats if stats is not None else StreamStats()
    if prefetch and n_chunks > 1:
        return ChunkPrefetcher(load_fn, n_chunks, sharding=sharding,
                               stats=stats, pass_name=pass_name)
    return _serial_chunks(load_fn, n_chunks, sharding, stats, pass_name)
