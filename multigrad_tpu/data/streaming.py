"""Out-of-core model fitting: exact streamed loss and gradients.

:class:`StreamingOnePointModel` runs an
:class:`~multigrad_tpu.core.model.OnePointModel` over a catalog that
never needs to be resident in device (or even host) memory.  The
additivity that makes the paper's communication O(|sumstats|+|params|)
also makes *time-slicing* exact:

    y      = Σ_k y_k                      (pass 1: stream chunks,
                                           accumulate total sumstats)
    dL/dy  = ∂loss/∂y |_y                 (computed ONCE, O(|y|))
    dL/dp  = Σ_k (∂y_k/∂p)ᵀ · dL/dy      (pass 2: re-stream chunks,
                                           accumulate VJP contributions)

Both passes stream chunks through the double-buffered prefetcher
(:mod:`.prefetch`), so host→device transfer of chunk k+1 overlaps
compute on chunk k and HBM holds at most two chunk buffers.  The
result is bitwise-independent of the chunk size up to float summation
order — streamed and resident fits agree to fp32 tolerance (tested in
``tests/test_streaming.py``).

For catalogs that DO fit in HBM but whose VJP residuals do not (the
intermediate regime), :meth:`calc_loss_and_grad_scan` materializes the
chunk stack on device once and runs a single-dispatch in-graph
``lax.scan`` over chunks with ``jax.checkpoint`` per chunk — one XLA
program per fit step, no host round-trips, residuals recomputed
chunk-by-chunk.

Contracts
---------
* the wrapped model's ``aux_data`` must be a dict holding only the
  *resident* leaves; streamed leaves are bound per chunk under their
  stream names (``core/model.py``'s aux re-binding).
* with ``sumstats_func_has_aux=True`` the aux must be additive over
  chunks and shards (it is accumulated exactly like the sumstats).
* a ``randkey`` is forwarded identically to every chunk, so streamed
  == resident only holds for sumstats whose randomness is per-row
  independent of position (deterministic kernels always match).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Mapping, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core.model import OnePointModel
from ..optim import adam as _adam
from ..optim.adam import init_randkey
from ..utils.profiling import StreamStats
from .prefetch import prefetch_chunks
from .source import CatalogSource, ChunkPlan, as_source

__all__ = ["StreamingOnePointModel"]


@dataclass
class StreamingOnePointModel:
    """Stream catalogs through an :class:`OnePointModel`'s algebra.

    Parameters
    ----------
    model : OnePointModel
        The wrapped model (defines sumstats/loss, the comm, and the
        resident ``aux_data``).  Its ``aux_data`` dict must NOT
        contain the streamed keys.
    streams : mapping of str -> CatalogSource | array | path
        Per-stream catalog sources, keyed by the ``aux_data`` name the
        model's sumstats method reads.  All streams must be row-aligned
        (same number of rows).  Values pass through
        :func:`~multigrad_tpu.data.source.as_source`.
    chunk_rows : int or "auto"
        Global rows per chunk (rounded up to a multiple of the comm
        size; see :func:`~multigrad_tpu.data.source.plan_chunks`).
        ``"auto"`` resolves the tuned chunk size from the autotuner's
        on-disk table (:func:`multigrad_tpu.tune.tune_streaming`
        writes it; cold table: ``min(n_rows, 2**20)``).
    pad_values : float or mapping of str -> float
        Neutral filler for the ragged final chunk, per stream — same
        contract as ``scatter_nd(pad_value=...)``.  Default ``inf``
        (neutral for erf-CDF counts, the shipped models' kernels).
    prefetch : bool
        Double-buffered background prefetch (default).  ``False``
        loads chunks synchronously (baseline for the stall/overlap
        metrics).
    remat_policy : str | callable | None
        ``jax.checkpoint`` policy for the per-chunk remat of the
        single-dispatch scan path (:meth:`calc_loss_and_grad_scan`).
        Default ``"dots"`` (``jax.checkpoint_policies
        .checkpoint_dots``: matmul results are saved, everything else
        — the erf/cdf intermediates that dominate chunk memory — is
        recomputed); ``None``/``"nothing"`` recomputes everything
        (the historical behavior), ``"everything"`` disables remat,
        or pass any ``jax.checkpoint`` policy callable.  See
        :func:`multigrad_tpu.core.model.resolve_remat_policy`.
        ``"auto"`` resolves the tuned policy from the autotuner's
        table ("dots" on a cold table).
    """

    model: OnePointModel
    streams: Mapping[str, Union[CatalogSource, str, np.ndarray]]
    chunk_rows: int
    pad_values: Union[float, Mapping[str, float]] = np.inf
    prefetch: bool = True
    remat_policy: Union[str, Callable, None] = "dots"
    last_stats: Optional[StreamStats] = field(default=None, repr=False)

    def __post_init__(self):
        self.streams = {name: as_source(src)
                        for name, src in self.streams.items()}
        if not self.streams:
            raise ValueError("streams must name at least one catalog")
        lengths = {name: src.n_rows for name, src in self.streams.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(
                f"streams must be row-aligned, got lengths {lengths}")
        if self.chunk_rows == "auto" or self.remat_policy == "auto":
            # Tuned streaming knobs from the autotuner's table
            # (:func:`multigrad_tpu.tune.tune_streaming` writes
            # them); cold table = bounded power-of-two chunks and
            # the "dots" remat policy — the historical defaults.
            from ..tune.resolve import resolve_stream_knobs
            self.chunk_rows, self.remat_policy = resolve_stream_knobs(
                type(self.model).__name__,
                next(iter(self.streams.values())).n_rows,
                self.model.comm, chunk_rows=self.chunk_rows,
                remat_policy=self.remat_policy)
        if isinstance(self.model.aux_data, dict):
            overlap = set(self.streams) & set(self.model.aux_data)
            if overlap:
                raise ValueError(
                    f"aux_data already holds streamed keys {overlap}; "
                    "resident aux and streams must be disjoint")
        self._names = tuple(self.streams)
        self._scan_stack = None  # device chunk stack, built lazily

    # ------------------------------------------------------------------ #
    # Plumbing
    # ------------------------------------------------------------------ #
    @property
    def comm(self):
        return self.model.comm

    @property
    def n_rows(self) -> int:
        return next(iter(self.streams.values())).n_rows

    def plan(self) -> ChunkPlan:
        """The deterministic chunk plan for the current comm."""
        n_shards = self.comm.size if self.comm is not None else 1
        return next(iter(self.streams.values())).plan(
            self.chunk_rows, n_shards)

    def _pad_value(self, name: str):
        if isinstance(self.pad_values, Mapping):
            return self.pad_values[name]
        return self.pad_values

    def _load_chunk(self, plan: ChunkPlan, k: int):
        spec = plan.chunks[k]
        return [self.streams[name].load_chunk(spec, self._pad_value(name))
                for name in self._names]

    def _chunk_sharding(self, stacked: bool = False):
        if self.comm is None:
            return None
        axis = 1 if stacked else 0
        # One sharding per stream leaf; ndim read off the source row.
        shardings = []
        for name in self._names:
            row = self.streams[name].read(0, 1)
            shardings.append(self.comm.sharding(
                axis=axis, ndim=np.ndim(row) + (1 if stacked else 0)))
        return shardings

    def _iter_chunks(self, plan: ChunkPlan, stats: StreamStats,
                     pass_name: Optional[str] = None):
        return prefetch_chunks(
            lambda k: self._load_chunk(plan, k), plan.n_chunks,
            sharding=self._chunk_sharding(), prefetch=self.prefetch,
            stats=stats, pass_name=pass_name)

    def _key_arg(self, randkey):
        return init_randkey(randkey) if randkey is not None \
            else jnp.zeros(())

    # ------------------------------------------------------------------ #
    # Streamed passes
    # ------------------------------------------------------------------ #
    def _accumulate(self, program, params, randkey,
                    pass_name: Optional[str] = None):
        """Drive a per-chunk program over the whole plan, tree-summing
        its outputs (the additive-algebra accumulation loop shared by
        the sumstats and jacobian passes); records ``last_stats``
        (counters split under ``pass_name``)."""
        params = jnp.asarray(params)
        aux_leaves = self.model.aux_leaves()
        key = self._key_arg(randkey)
        plan = self.plan()
        stats = StreamStats()
        total = None
        for _k, chunk in self._iter_chunks(plan, stats, pass_name):
            out = program(params, chunk, aux_leaves, key)
            total = out if total is None else jax.tree_util.tree_map(
                jnp.add, total, out)
        self.last_stats = stats
        return total

    def calc_sumstats_from_params(self, params, randkey=None):
        """Total sumstats over the full streamed catalog (pass 1).

        Returns the replicated total — identical (to summation-order
        float tolerance) to the resident model's
        ``calc_sumstats_from_params(total=True)``.  With
        ``sumstats_func_has_aux`` returns ``(total, aux_total)``.
        """
        return self._accumulate(
            self.model.chunk_sumstats_fn(self._names,
                                         randkey is not None),
            params, randkey, pass_name="sumstats")

    def calc_sumstats_and_jac_from_params(self, params, randkey=None):
        """Streamed total sumstats and Jacobian (one pass).

        The Jacobian ``∂y/∂p = Σ_k ∂y_k/∂p`` accumulates over chunks
        exactly like the sumstats (it lives in the same additive
        algebra), so Fisher matrices — ``multigrad_tpu.inference
        .fisher_information`` consumes this — cost one pass over a
        catalog of ANY size with O(|y|·|p|) device memory for the
        accumulator.  Matches the resident
        :meth:`~multigrad_tpu.core.model.OnePointModel
        .calc_sumstats_and_jac_from_params` to float summation-order
        tolerance.  Sumstats aux values (if any) are dropped.
        """
        return self._accumulate(
            self.model.chunk_jac_fn(self._names, randkey is not None),
            params, randkey, pass_name="jac")

    def calc_loss_from_params(self, params, randkey=None):
        """Loss at `params` over the streamed catalog (one pass)."""
        total = self.calc_sumstats_from_params(params, randkey=randkey)
        return self._loss_from_total(total, randkey)[0]

    def _loss_from_total(self, total, randkey):
        """(loss, dL/dy) from accumulated totals; handles aux flags."""
        m = self.model
        kwargs = {} if randkey is None \
            else {"randkey": init_randkey(randkey)}
        args = total if m.sumstats_func_has_aux else (total,)
        loss = m.calc_loss_from_sumstats(*args, **kwargs)
        if m.loss_func_has_aux:
            loss = loss[0]
        ct = m._grad_loss_from_sumstats(*args, **kwargs)
        if m.loss_func_has_aux:
            ct = ct[0]
        return loss, ct

    def calc_loss_and_grad_from_params(self, params, randkey=None):
        """Exact loss and gradient via the two-pass streamed algebra.

        Pass 1 accumulates the total sumstats ``y`` chunk by chunk;
        ``dL/dy`` is computed once from the total; pass 2 re-streams
        the chunks accumulating each chunk's VJP contribution to
        ``dL/dparams``.  Matches the resident fused program to float
        summation-order tolerance at any chunk size.

        Pass 2 is double-buffered exactly like pass 1: its prefetcher
        is constructed (loader thread running) BEFORE the cotangent
        computation, so the re-stream's first chunks transfer while
        ``dL/dy`` is evaluated, and chunk k+1 loads while the VJP of
        chunk k runs.  ``last_stats`` holds the merged stream counters
        of both passes, split per pass (``passes["sumstats"]`` /
        ``passes["vjp"]`` — stall and overlap fractions each).
        """
        params = jnp.asarray(params)
        with_key = randkey is not None
        key = self._key_arg(randkey)
        aux_leaves = self.model.aux_leaves()
        plan = self.plan()

        total = self.calc_sumstats_from_params(params, randkey=randkey)
        stats = self.last_stats

        # Start the VJP re-stream NOW: dL/dy below is O(|y|) host-side
        # work the pass-2 transfers should hide behind.
        chunks2 = self._iter_chunks(plan, stats, pass_name="vjp")
        try:
            loss, ct = self._loss_from_total(total, randkey)

            vjp_program = self.model.chunk_vjp_fn(self._names, with_key)
            grad = None
            for _k, chunk in chunks2:
                g = vjp_program(params, chunk, aux_leaves, ct, key)
                grad = g if grad is None else grad + g
        finally:
            close = getattr(chunks2, "close", None)
            if close is not None:
                close()
        self.last_stats = stats
        return loss, grad

    def calc_dloss_dparams(self, params, randkey=None):
        return self.calc_loss_and_grad_from_params(
            params, randkey=randkey)[1]

    # ------------------------------------------------------------------ #
    # Telemetry: collective-traffic accounting
    # ------------------------------------------------------------------ #
    def measure_comm(self, params, randkey=None,
                     use_scan: bool = False) -> dict:
        """Collective payload of ONE streamed loss-and-grad step.

        Traces fresh builds of the chunk programs under a
        :class:`~multigrad_tpu.telemetry.CommCounter` — zero FLOPs,
        exact byte counts (payloads are static shapes).  Two shapes:

        * two-pass stream (default): pass-1 sumstats + pass-2 VJP,
          scaled by the plan's chunk count — per-chunk traffic is
          ``|y| + |params|`` floats *independent of the chunk's
          rows*, so bytes/step depends only on ``n_chunks``, never on
          the catalog size;
        * ``use_scan=True``: the single-dispatch scan program, whose
          psums fire ONCE per step (after in-scan accumulation) —
          ``|y| + |params|`` floats total, chunk count irrelevant.

        ``comm=None`` models report zero.
        """
        from ..telemetry.comm import CommCounter

        with_key = randkey is not None
        params = jnp.asarray(params, dtype=jnp.result_type(float))
        plan = self.plan()
        aux = self.model.aux_leaves()
        key = self._key_arg(randkey)

        def chunk_struct(name, lead):
            row = self.streams[name].read(0, 1)
            return jax.ShapeDtypeStruct(
                lead + (plan.rows_per_chunk,) + row.shape[1:],
                row.dtype)

        if use_scan:
            stacks = [chunk_struct(n, (plan.n_chunks,))
                      for n in self._names]
            program = self.model._build_stream_program(
                "chunk_scan", with_key, self._names,
                remat_policy=self.remat_policy)
            with CommCounter() as cc:
                jax.eval_shape(program, params, stacks, aux, key)
            return cc.step_record(scope="streamed_scan_step",
                                  n_chunks=plan.n_chunks)

        chunk_shapes = [chunk_struct(n, ()) for n in self._names]
        p1 = self.model._build_stream_program(
            "chunk_sumstats", with_key, self._names)
        p2 = self.model._build_stream_program(
            "chunk_vjp", with_key, self._names)
        with CommCounter() as cc:
            total = jax.eval_shape(p1, params, chunk_shapes, aux, key)
            ct = total[0] if self.model.sumstats_func_has_aux else total
            jax.eval_shape(p2, params, chunk_shapes, aux, ct, key)
        return cc.scaled(plan.n_chunks).step_record(
            scope="streamed_loss_and_grad_step",
            n_chunks=plan.n_chunks, bytes_per_chunk=cc.total_bytes)

    def check_shard_safety(self, params, **kwargs):
        """Statically verify the streamed chunk programs.

        One-call access to the shard-safety analyzer
        (:func:`multigrad_tpu.analysis.analyze_streaming`): the
        two-pass chunk programs (and the scan path) are traced at two
        chunk sizes to prove per-chunk collective traffic independent
        of the chunk's rows — the streamed form of the
        O(|sumstats|+|params|) bound — plus the replication, dtype,
        callback and constant-capture checks.  Zero device execution.
        """
        from ..analysis import analyze_streaming
        return analyze_streaming(self, params, **kwargs)

    # ------------------------------------------------------------------ #
    # Single-dispatch scan path (HBM-resident chunks, streamed remat)
    # ------------------------------------------------------------------ #
    def _materialize_scan_stack(self, plan: ChunkPlan):
        """Device-resident (n_chunks, rows_per_chunk, ...) chunk stacks.

        Built once per model (the stack is reused every optimizer
        step) and sharded over axis 1, so each device holds its shard
        of every chunk.
        """
        if self._scan_stack is None:
            stacks = []
            for name in self._names:
                host = np.stack([
                    self.streams[name].load_chunk(spec,
                                                  self._pad_value(name))
                    for spec in plan.chunks])
                stacks.append(host)
            shardings = self._chunk_sharding(stacked=True)
            if shardings is None:
                stacks = [jax.device_put(s) for s in stacks]
            else:
                stacks = [jax.device_put(s, sh)
                          for s, sh in zip(stacks, shardings)]
            self._scan_stack = stacks
        return self._scan_stack

    def calc_loss_and_grad_scan(self, params, randkey=None):
        """Loss and gradient as ONE in-graph ``lax.scan`` over chunks.

        The whole two-stage chain rule — chunked forward scan,
        ``dL/dy``, chunked VJP — compiles into a single XLA program
        with ``jax.checkpoint`` per chunk, so VJP residuals for only
        one chunk exist at a time.  Requires the chunk stack to fit
        in HBM; use the two-pass streamed path above when it does not.
        """
        params = jnp.asarray(params)
        with_key = randkey is not None
        program = self.model.chunk_scan_loss_and_grad_fn(
            self._names, with_key, remat_policy=self.remat_policy)
        stacks = self._materialize_scan_stack(self.plan())
        return program(params, stacks, self.model.aux_leaves(),
                       self._key_arg(randkey))

    # ------------------------------------------------------------------ #
    # Fit loop
    # ------------------------------------------------------------------ #
    def run_adam(self, guess, nsteps=100, param_bounds=None,
                 learning_rate=0.01, randkey=None, progress=True,
                 use_scan: bool = False, checkpoint_dir=None,
                 checkpoint_every=None, telemetry=None,
                 log_every: int = 0, heartbeat_s=None,
                 donate_carry=None, flight=None, live=None,
                 alerts=None, diagnostics: bool = False):
        """Adam fit with streamed loss-and-grad every step.

        ``use_scan=True`` drives the single-dispatch scan program
        instead of the two-pass stream (right when the chunk stack
        fits HBM — the per-step cost drops to one dispatch).  Returns
        the full parameter trajectory, shape ``(nsteps+1, ndim)``,
        like every other fit entry point.  ``checkpoint_dir`` enables
        the same preemption-safe restart contract as the resident
        :meth:`~multigrad_tpu.core.model.OnePointModel.run_adam`
        (see :func:`~multigrad_tpu.optim.adam.run_adam_streamed`; the
        streamed catalog itself must stay fixed across a resume).

        With ``telemetry`` (a :class:`multigrad_tpu.telemetry
        .MetricsLogger`) the fit is fully observable: a ``comm``
        record up front (trace-time bytes/step accounting — see
        :meth:`measure_comm`), per-step ``adam`` records every
        ``log_every`` steps, heartbeat/stall liveness when
        ``heartbeat_s`` is set, and a closing ``stream`` record with
        the prefetcher's counters (stall fraction, bytes, buffer
        high-water mark).

        With ``flight`` (a :class:`multigrad_tpu.telemetry.flight
        .FlightRecorder`) a non-finite loss/parameter stops the fit
        with a postmortem bundle — streamed fits are the longest
        fits, exactly where a NaN three hours in must leave evidence
        (see :func:`multigrad_tpu.optim.adam.run_adam_streamed`).

        ``live``/``alerts`` attach the online monitors (live HTTP
        endpoint, non-fatal alert rules) — wired here so the up-front
        ``comm`` record reaches them too; streamed fits are the runs
        a live view matters most for.  ``diagnostics=True`` adds the
        host-side loss-EMA plateau fields to the emitted ``adam``
        records.
        """
        fn = self.calc_loss_and_grad_scan if use_scan \
            else self.calc_loss_and_grad_from_params
        if donate_carry is None:
            # Tuned donation verdict (autotuner table), keyed on the
            # wrapped model; None on a cold table keeps the backend
            # auto rule downstream.
            from ..tune.resolve import resolve_donate_carry
            donate_carry = resolve_donate_carry(self.model)
        from ..telemetry.live import wire_monitoring
        telemetry, log_every, owned = wire_monitoring(
            telemetry, log_every, live, alerts)
        try:
            if telemetry is not None:
                telemetry.log("comm", **self.measure_comm(
                    jnp.asarray(guess), randkey=randkey,
                    use_scan=use_scan))
            traj = _adam.run_adam_streamed(
                fn, guess, nsteps=nsteps, param_bounds=param_bounds,
                learning_rate=learning_rate, randkey=randkey,
                progress=progress, checkpoint_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every, telemetry=telemetry,
                log_every=log_every, heartbeat_s=heartbeat_s,
                donate_carry=donate_carry,
                stream_stats=lambda: self.last_stats, flight=flight,
                diagnostics=diagnostics)
            if telemetry is not None and self.last_stats is not None:
                telemetry.log("stream", **self.last_stats.summary())
            return traj
        finally:
            if owned is not None:
                owned.close()
