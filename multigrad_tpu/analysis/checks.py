"""The shard-safety check registry.

Each check is a pure function over traced programs returning
:class:`~multigrad_tpu.analysis.findings.Finding` lists.  Program-level
checks (:data:`PROGRAM_CHECKS`) take one trace; the comm-scaling check
takes a *pair* of traces of the same program at two catalog sizes.
:func:`multigrad_tpu.analysis.analyzer.analyze_model` orchestrates
which programs get traced and which checks run; this module holds the
verification logic itself.

Writing a custom check
----------------------
A program-level check is ``fn(closed_jaxpr, program_label) ->
list[Finding]``.  Register it under a new id::

    from multigrad_tpu.analysis import checks

    def check_no_ppermute(closed, program):
        return [Finding("no-ppermute", ERROR, "ppermute is banned",
                        program, eqn_source(eqn), "/".join(path))
                for eqn, path, _ in walk_eqns(closed)
                if eqn.primitive.name == "ppermute"]

    checks.PROGRAM_CHECKS["no-ppermute"] = check_no_ppermute

and it runs in every subsequent ``analyze_model``/CLI invocation.
"""
from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp
import numpy as np

from ..telemetry.comm import leaf_nbytes
from .findings import ERROR, WARNING, Finding
from .jaxprs import (CALLBACK_PRIMS, collect_collectives, eqn_source,
                     iter_consts, walk_eqns)
from .replication import shard_map_leaks

__all__ = ["check_replication", "check_callbacks_in_scan",
           "check_dtype_promotion", "check_captured_consts",
           "check_comm_invariance", "check_k_scaling",
           "PROGRAM_CHECKS", "CHECK_IDS",
           "DEFAULT_CONST_THRESHOLD"]

# Closed-over constants above this many bytes are flagged (they are
# baked into every compiled executable: HBM resident per program
# variant, re-hashed on every cache lookup, and re-staged on every
# recompile).  1 MiB passes every shipped model's edge/target vectors
# while catching any accidentally captured catalog.
DEFAULT_CONST_THRESHOLD = 1 << 20


# --------------------------------------------------------------------- #
# Check 2: replication mismatch (the SPMD race detector)
# --------------------------------------------------------------------- #
def check_replication(closed, program: str = "") -> List[Finding]:
    """Outputs declared replicated must be *provably* replicated.

    Runs the forward variance dataflow
    (:mod:`multigrad_tpu.analysis.replication`) over every
    ``shard_map`` body in the trace and flags outputs whose declared
    out-sharding does not account for their inferred device variance —
    the un-psum'd-output bug the pre-vma ``check_rep=False`` compat
    path silently waves through.
    """
    out = []
    for eqn, path, _ in walk_eqns(closed):
        if eqn.primitive.name != "shard_map":
            continue
        for idx, axes in shard_map_leaks(eqn):
            out.append(Finding(
                "replication", ERROR,
                f"shard_map output {idx} is declared replicated over "
                f"mesh axis(es) {list(axes)} but is computed from "
                "device-varying values with no psum/all_gather "
                "dominating it — each device returns a DIFFERENT "
                "value and the caller silently receives one of them",
                program=program, where=eqn_source(eqn),
                path="/".join(path + ("shard_map",))))
    return out


# --------------------------------------------------------------------- #
# Check 3: host callbacks inside hot loops
# --------------------------------------------------------------------- #
def check_callbacks_in_scan(closed, program: str = "") -> List[Finding]:
    """Flag ungated host callbacks inside ``scan`` bodies.

    A ``debug_callback``/``pure_callback``/``io_callback`` in a scan
    body fires a device→host round trip EVERY iteration — the
    host-interleaved pattern the whole-fit ``lax.scan`` fast path
    exists to avoid.  The shipped telemetry taps are exempt by
    construction: they sit behind a ``lax.cond`` (the
    ``log_every``-gate), so the path from the innermost ``scan`` to
    the callback passes through ``cond`` — the structural signature
    this check keys on.
    """
    out = []
    for eqn, path, _ in walk_eqns(closed):
        if eqn.primitive.name not in CALLBACK_PRIMS:
            continue
        if "scan" not in path:
            continue
        innermost_scan = len(path) - 1 - path[::-1].index("scan")
        if "cond" in path[innermost_scan:]:
            continue                      # gated: telemetry-tap shape
        out.append(Finding(
            "callback-in-scan", WARNING,
            f"{eqn.primitive.name} executes on EVERY iteration of an "
            "enclosing scan (no lax.cond gate between the loop and "
            "the callback): one device->host round trip per step — "
            "gate it (see telemetry.ScalarTap) or hoist it out",
            program=program, where=eqn_source(eqn),
            path="/".join(path)))
    return out


# --------------------------------------------------------------------- #
# Check 4: dtype promotion
# --------------------------------------------------------------------- #
def check_dtype_promotion(closed, program: str = "",
                          expected_dtype=None) -> List[Finding]:
    """Flag inexact values wider than the working precision.

    ``expected_dtype`` defaults to ``jnp.result_type(float)`` — f32
    unless x64 is enabled.  Any equation output or captured constant
    with a wider inexact dtype is a silent upcast: on TPU every f64 op
    is software-emulated (an order of magnitude slower), and a single
    weak-typed ``np.float64`` scalar leaking into the loss path
    promotes the whole gradient chain.  One finding per distinct
    source location, not per eqn, so a single leaky constant does not
    bury the report.
    """
    expected = np.dtype(expected_dtype if expected_dtype is not None
                        else jnp.result_type(float))
    out = []
    seen = set()
    for eqn, path, _ in walk_eqns(closed):
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            dtype = getattr(aval, "dtype", None)
            if dtype is None or not jnp.issubdtype(dtype, jnp.inexact):
                continue
            if np.dtype(dtype).itemsize <= expected.itemsize:
                continue
            key = (eqn.primitive.name, eqn_source(eqn))
            if key in seen:
                continue
            seen.add(key)
            out.append(Finding(
                "dtype-promotion", ERROR,
                f"{eqn.primitive.name} produces {np.dtype(dtype).name} "
                f"but the working precision is {expected.name}: a "
                "weak-type upcast is widening the compute (and, on "
                "TPU, falling off the hardware fast path)",
                program=program, where=eqn_source(eqn),
                path="/".join(path)))
    for const, path in iter_consts(closed):
        dtype = getattr(const, "dtype", None)
        if dtype is None or not jnp.issubdtype(dtype, jnp.inexact):
            continue
        if np.dtype(dtype).itemsize <= expected.itemsize:
            continue
        out.append(Finding(
            "dtype-promotion", ERROR,
            f"captured constant of dtype {np.dtype(dtype).name} "
            f"(shape {tuple(np.shape(const))}) exceeds the working "
            f"precision {expected.name}",
            program=program, path=path))
    return out


# --------------------------------------------------------------------- #
# Check 5: captured-constant bloat
# --------------------------------------------------------------------- #
def check_captured_consts(closed, program: str = "",
                          threshold_bytes: int = DEFAULT_CONST_THRESHOLD
                          ) -> List[Finding]:
    """Flag large arrays baked into the program as constants.

    Data must enter a program as an *argument* (the model core's
    dynamic aux leaves); a closed-over array is copied into every
    compiled variant, hashed on every jit-cache lookup, and silently
    re-staged after any donation/update — the classic
    "why is my fit recompiling and eating HBM" bug.
    """
    out = []
    for const, path in iter_consts(closed):
        nbytes = leaf_nbytes(const)
        if nbytes < threshold_bytes:
            continue
        out.append(Finding(
            "captured-const", WARNING,
            f"program closes over a {nbytes / 1e6:.1f} MB constant "
            f"(shape {tuple(np.shape(const))}, dtype "
            f"{getattr(const, 'dtype', '?')}): pass it as an argument "
            "(model aux_data) instead of capturing it",
            program=program, path=path))
    return out


# --------------------------------------------------------------------- #
# Check 1: communication-scaling invariance (the paper's bound)
# --------------------------------------------------------------------- #
def check_comm_invariance(closed_base, closed_scaled, program: str = "",
                          scale: int = 2,
                          allow_linear: Sequence[str] = ()
                          ) -> List[Finding]:
    """Prove every collective's payload independent of catalog size.

    ``closed_base``/``closed_scaled`` are traces of the SAME program
    with the catalog (comm-sharded) axes scaled by ``scale``.  Walks
    both traces, pairs collective sites positionally (trace order is
    deterministic for a fixed program), and flags any site whose
    per-execution payload changed — a collective that moves O(data)
    bytes, breaking the O(|sumstats| + |params|) bound the framework
    exists to provide.  Zero device execution: both traces are
    ``jax.make_jaxpr`` over ShapeDtypeStructs.

    ``allow_linear`` names collective ops (e.g. ``"ppermute"``) that
    are *declared* neighbor/ring exchanges: a pair-counting member's
    ring rotation moves O(rows-per-shard) by construction, so those
    sites are held to an at-most-linear bound (payload may grow at
    most ``scale``×) instead of invariance — every *reduction*
    collective in the same program still has to meet the exact
    O(|sumstats|+|params|) bound.
    """
    base = collect_collectives(closed_base)
    scaled = collect_collectives(closed_scaled)
    out = []
    if len(base) != len(scaled):
        return [Finding(
            "comm-scaling", ERROR,
            f"collective COUNT changes with catalog size: {len(base)} "
            f"sites at base size vs {len(scaled)} at {scale}x — the "
            "communication schedule itself is data-dependent",
            program=program)]
    for site_b, site_s in zip(base, scaled):
        if site_b.op != site_s.op:
            out.append(Finding(
                "comm-scaling", ERROR,
                f"collective schedule diverges with catalog size: "
                f"{site_b.op} at base size vs {site_s.op} at "
                f"{scale}x in the same trace position",
                program=program, where=site_s.where, path=site_s.path))
            continue
        if site_b.op in allow_linear:
            if site_s.executed_bytes > site_b.executed_bytes * scale:
                grew = site_s.executed_bytes \
                    / max(site_b.executed_bytes, 1)
                out.append(Finding(
                    "comm-scaling", ERROR,
                    f"{site_b.op} payload grows SUPER-linearly with "
                    f"the catalog: {site_b.executed_bytes} B -> "
                    f"{site_s.executed_bytes} B per execution when "
                    f"the catalog grows {scale}x (x{grew:.2f}) — a "
                    "declared ring exchange may move at most "
                    "O(rows-per-shard)",
                    program=program, where=site_s.where,
                    path=site_s.path))
            continue
        if site_b.executed_bytes != site_s.executed_bytes:
            grew = site_s.executed_bytes / max(site_b.executed_bytes, 1)
            out.append(Finding(
                "comm-scaling", ERROR,
                f"{site_b.op} payload SCALES with the catalog: "
                f"{site_b.executed_bytes} B -> "
                f"{site_s.executed_bytes} B per execution when the "
                f"catalog grows {scale}x (x{grew:.2f}) — this "
                "collective moves O(data) and breaks the "
                "O(|sumstats|+|params|) communication bound",
                program=program, where=site_s.where, path=site_s.path))
    return out


# --------------------------------------------------------------------- #
# Check 6: ensemble K-axis scaling (the sharded-K bound)
# --------------------------------------------------------------------- #
def check_k_scaling(closed_base, closed_scaled, program: str = "",
                    scale: int = 2) -> List[Finding]:
    """Prove the batched program's comm scales (at most) linearly in K.

    ``closed_base``/``closed_scaled`` are traces of the SAME batched
    ``(K, ndim)`` program at K and ``scale · K``.  The sharded-K
    contract: doubling the ensemble width may at most double each
    collective's payload — the per-member O(|y|+|params|) data-axis
    bound carries a ``K/R`` batch factor and nothing else.  Pairs
    sites positionally (like :func:`check_comm_invariance`) and flags
    any site whose payload grows SUPER-linearly (an accidental
    cross-member coupling, e.g. a gathered ``(K, K)`` interaction or
    an all-gather of the full batch per member) or a K-dependent
    collective schedule.  Sub-linear (K-independent) sites — scalar
    diagnostics — are fine: the bound is an upper envelope.
    """
    base = collect_collectives(closed_base)
    scaled = collect_collectives(closed_scaled)
    if len(base) != len(scaled):
        return [Finding(
            "k-scaling", ERROR,
            f"collective COUNT changes with ensemble width: "
            f"{len(base)} sites at K vs {len(scaled)} at {scale}·K — "
            "the communication schedule itself depends on K, so "
            "retraces (and comm) grow with ensemble width",
            program=program)]
    out = []
    for site_b, site_s in zip(base, scaled):
        if site_b.op != site_s.op:
            out.append(Finding(
                "k-scaling", ERROR,
                f"collective schedule diverges with ensemble width: "
                f"{site_b.op} at K vs {site_s.op} at {scale}·K in "
                "the same trace position",
                program=program, where=site_s.where,
                path=site_s.path))
            continue
        if site_s.executed_bytes > scale * site_b.executed_bytes:
            grew = site_s.executed_bytes / max(site_b.executed_bytes,
                                               1)
            out.append(Finding(
                "k-scaling", ERROR,
                f"{site_b.op} payload grows SUPER-linearly in the "
                f"ensemble width: {site_b.executed_bytes} B -> "
                f"{site_s.executed_bytes} B per execution when K "
                f"grows {scale}x (x{grew:.2f} > x{scale}) — a "
                "cross-member coupling is hiding in the batched "
                "kernel, breaking the sharded-K "
                "(K/R)·O(|y|+|params|) comm bound",
                program=program, where=site_s.where,
                path=site_s.path))
    return out


# Registry: program-level checks, run by analyze_program on every
# traced program.  comm-scaling needs two traces and is orchestrated
# separately by analyze_model (see module docstring for extension).
PROGRAM_CHECKS = {
    "replication": check_replication,
    "callback-in-scan": check_callbacks_in_scan,
    "dtype-promotion": check_dtype_promotion,
    "captured-const": check_captured_consts,
}

CHECK_IDS = ("comm-scaling", "k-scaling") + tuple(PROGRAM_CHECKS)
