"""Wire-protocol schema extraction and drift lint.

The router and its workers speak newline-delimited JSON
(:mod:`multigrad_tpu.serve.wire`).  The protocol's compatibility
story — a mixed-version fleet where an old router drives new workers
and vice versa — rests on two invariants PRs 13/16/17/18 each
re-tested by hand:

* **Key symmetry** — every key a reader *requires* is a key every
  writer always sends; optional keys are read with ``.get`` and stay
  entirely off the message when absent.
* **Known-keys-only readers** — no reader ever splats a wire dict
  into a constructor (``Thing(**msg)``): unknown fields from a newer
  peer must be ignored, not crash the decode.

This module machine-checks both, the same way :mod:`.lockgraph`
proves lock order: by parsing the serve package's ASTs, never
importing them.  It extracts the full wire schema —

* the five codec pairs (``config/qos/shed/resources/result`` ×
  ``_to_wire``/``_from_wire``), writer keys from the returned dict
  (including loop-writes over module key-tuple constants), reader
  keys split required (``d["k"]``) vs optional (``d.get("k")`` or a
  guarded subscript);
* every ``{"op": ...}`` message constructor in ``worker.py`` /
  ``fleet.py`` / ``chaos.py`` (heartbeat, ready, reject, drain, ...),
  with ``**({...} if cond else {})`` augments and post-hoc
  ``msg["k"] = ...`` decorations classified optional and writer-side
  variable splats marked ``dynamic``;
* both dispatch readers (``worker.main``'s ``op`` chain and
  ``FleetRouter._reader``), following the message dict through
  handler calls (``self._on_result(handle, msg)``, nested
  ``handle_submit(msg)``) to their per-key reads —

and diffs it against the versioned, checked-in
``analysis/protocol.json`` manifest.  Any codec change therefore
becomes an explicit, reviewed manifest bump: CI fails with a
key-level diff naming exactly what drifted.
"""
from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .findings import ERROR, WARNING, Finding

__all__ = ["WIRE_CHECK_IDS", "PROTOCOL_VERSION",
           "DEFAULT_MANIFEST_PATH", "extract_schema", "dump_schema",
           "diff_schema", "protocol_markdown", "analyze_wire"]

#: Registry of wire check ids (the ``--checks`` vocabulary of the
#: ``wire`` lint target).
WIRE_CHECK_IDS = (
    "wire-key-asymmetry",
    "wire-reader-splat",
    "wire-manifest-drift",
)

_PROGRAM = "wire"

#: Schema manifest version.  Bump when the manifest SHAPE (not the
#: protocol content) changes.
PROTOCOL_VERSION = 1

#: The checked-in manifest CI diffs against.
DEFAULT_MANIFEST_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "protocol.json")

REQUIRED = "required"
OPTIONAL = "optional"

#: The stdout handshake line a worker prints before serving
#: (``serve/worker.py``) — the one wire message that is not an
#: ``{"op": ...}`` dict.
_READY_PREFIX = "FLEET-WORKER-READY"


# ---------------------------------------------------------------------- #
# small AST helpers
# ---------------------------------------------------------------------- #
def _walk_no_fn(node):
    """ast.walk that does not descend into nested function/class
    definitions."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _key_tuple(node, consts) -> Optional[Tuple[str, ...]]:
    """Resolve an iterable expression to a tuple of string keys:
    an inline tuple/list of constants, or a module-level tuple
    constant's name."""
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    if isinstance(node, (ast.Tuple, ast.List)):
        keys = tuple(_const_str(e) for e in node.elts)
        if all(k is not None for k in keys):
            return keys
    return None


@dataclass
class _Fn:
    module: str
    cls: Optional[str]
    name: str
    node: ast.AST
    params: List[str]


@dataclass
class SplatSite:
    module: str
    func: str
    lineno: int
    param: str


@dataclass
class _Mod:
    module: str
    consts: Dict[str, Tuple[str, ...]] = field(default_factory=dict)
    fns: Dict[str, List[_Fn]] = field(default_factory=dict)


class _Scanner:
    """One module's function table + module-level key-tuple
    constants (``_RESOURCE_INT_KEYS`` and friends)."""

    def __init__(self, module: str, tree: ast.Module):
        self.mod = _Mod(module)
        for node in tree.body:
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                keys = _key_tuple(node.value, {})
                if keys:
                    self.mod.consts[node.targets[0].id] = keys
        self._collect(tree, cls=None)

    def _collect(self, node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                params = [a.arg for a in child.args.args]
                self.mod.fns.setdefault(child.name, []).append(
                    _Fn(self.mod.module, cls, child.name, child,
                        params))
                self._collect(child, cls)      # nested defs
            elif isinstance(child, ast.ClassDef):
                self._collect(child, cls=child.name)
            else:
                self._collect(child, cls)


# ---------------------------------------------------------------------- #
# writer-side key extraction
# ---------------------------------------------------------------------- #
def _dict_literal_keys(node: ast.Dict, keys: Dict[str, str],
                       dynamic: List[bool]):
    """Keys of one dict literal.  ``**({...} if c else {})`` splats
    classify inner keys by presence in both arms; a variable splat
    marks the whole message dynamic."""
    for k, v in zip(node.keys, node.values):
        if k is not None:
            name = _const_str(k)
            if name is not None:
                keys.setdefault(name, REQUIRED)
            continue
        # ** splat
        if isinstance(v, ast.Dict):
            _dict_literal_keys(v, keys, dynamic)
        elif isinstance(v, ast.IfExp) \
                and isinstance(v.body, ast.Dict) \
                and isinstance(v.orelse, ast.Dict):
            both: Dict[str, str] = {}
            one: Dict[str, str] = {}
            _dict_literal_keys(v.body, one, dynamic)
            _dict_literal_keys(v.orelse, both, dynamic)
            for name in set(one) | set(both):
                status = REQUIRED if name in one and name in both \
                    else OPTIONAL
                keys.setdefault(name, status)
        else:
            dynamic.append(True)


def _writer_keys(fn: _Fn, consts) -> Dict[str, str]:
    """Keys a ``*_to_wire`` codec always writes: the returned dict
    literal's keys, plus loop-writes over key-tuple constants and
    direct ``out["k"] = ...`` stores on a returned name."""
    keys: Dict[str, str] = {}
    dynamic: List[bool] = []
    returned: set = set()
    for node in _walk_no_fn(fn.node):
        if isinstance(node, ast.Return):
            if isinstance(node.value, ast.Dict):
                _dict_literal_keys(node.value, keys, dynamic)
            elif isinstance(node.value, ast.Name):
                returned.add(node.value.id)
    if returned:
        for node in _walk_no_fn(fn.node):
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Subscript) \
                    and isinstance(node.targets[0].value, ast.Name) \
                    and node.targets[0].value.id in returned:
                sub = node.targets[0].slice
                name = _const_str(sub)
                if name is not None:
                    keys.setdefault(name, REQUIRED)
                elif isinstance(sub, ast.Name):
                    for loop_keys in _loop_vars(fn.node, consts,
                                                sub.id):
                        for k in loop_keys:
                            keys.setdefault(k, REQUIRED)
    return keys


def _loop_vars(fn_node, consts, var: str) -> List[Tuple[str, ...]]:
    """Key tuples a ``for <var> in <keys>:`` loop binds ``var``
    to, anywhere in the function."""
    out = []
    for node in _walk_no_fn(fn_node):
        if isinstance(node, ast.For) \
                and isinstance(node.target, ast.Name) \
                and node.target.id == var:
            keys = _key_tuple(node.iter, consts)
            if keys:
                out.append(keys)
    return out


# ---------------------------------------------------------------------- #
# reader-side key extraction
# ---------------------------------------------------------------------- #
class _ReaderScan:
    """Reads of ONE wire-dict parameter inside one function, with
    one splat check.  ``.get`` / membership-tested keys are optional;
    bare subscripts are required — unless the same key was also
    ``.get``-probed (the guarded-subscript idiom), which keeps it
    optional."""

    def __init__(self, fn: _Fn, param: str, consts,
                 splats: List[SplatSite]):
        self.keys: Dict[str, str] = {}
        self.handoffs: List[Tuple[ast.Call, int]] = []
        node = fn.node
        loop_cache: Dict[str, List[Tuple[str, ...]]] = {}

        def loops(var):
            if var not in loop_cache:
                loop_cache[var] = _loop_vars(node, consts, var)
            return loop_cache[var]

        subscripts: List[Optional[str]] = []
        for n in _walk_no_fn(node):
            if isinstance(n, ast.Call):
                f = n.func
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == param \
                        and f.attr == "get" and n.args:
                    key = _const_str(n.args[0])
                    if key is not None:
                        self.keys.setdefault(key, OPTIONAL)
                    elif isinstance(n.args[0], ast.Name):
                        for keys in loops(n.args[0].id):
                            for k in keys:
                                self.keys.setdefault(k, OPTIONAL)
                for kw in n.keywords:
                    if kw.arg is None \
                            and isinstance(kw.value, ast.Name) \
                            and kw.value.id == param:
                        splats.append(SplatSite(
                            fn.module, fn.name, n.lineno, param))
                for i, a in enumerate(n.args):
                    if isinstance(a, ast.Name) and a.id == param:
                        self.handoffs.append((n, i))
            elif isinstance(n, ast.Compare) \
                    and len(n.ops) == 1 \
                    and isinstance(n.ops[0], (ast.In, ast.NotIn)) \
                    and isinstance(n.comparators[0], ast.Name) \
                    and n.comparators[0].id == param:
                key = _const_str(n.left)
                if key is not None:
                    self.keys.setdefault(key, OPTIONAL)
            elif isinstance(n, ast.Subscript) \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id == param:
                key = _const_str(n.slice)
                if key is not None:
                    subscripts.append(key)
                elif isinstance(n.slice, ast.Name):
                    for keys in loops(n.slice.id):
                        subscripts.extend(keys)
        for key in subscripts:
            if key is not None and key not in self.keys:
                self.keys[key] = REQUIRED


def _follow_reads(fn: _Fn, param: str, mod: _Mod,
                  splats: List[SplatSite],
                  visited: set) -> Dict[str, str]:
    """Reads of ``param`` in ``fn`` plus (recursively) in every
    same-module function the dict is handed to — how ``_reader``'s
    dispatch reaches ``_on_result``'s reads, and ``_on_error``
    reaches ``_exception_from_wire``'s."""
    if (fn.module, fn.cls, fn.name, param) in visited:
        return {}
    visited.add((fn.module, fn.cls, fn.name, param))
    scan = _ReaderScan(fn, param, mod.consts, splats)
    keys = dict(scan.keys)
    for call, argidx in scan.handoffs:
        callee = _resolve_call(call, fn, mod)
        if callee is None:
            continue
        idx = argidx
        if callee.params and callee.params[0] in ("self", "cls"):
            idx += 1
        if idx >= len(callee.params):
            continue
        sub = _follow_reads(callee, callee.params[idx], mod,
                            splats, visited)
        for k, status in sub.items():
            if status == REQUIRED:
                keys[k] = REQUIRED
            else:
                keys.setdefault(k, OPTIONAL)
    return keys


def _resolve_call(call: ast.Call, caller: _Fn,
                  mod: _Mod) -> Optional[_Fn]:
    f = call.func
    name = None
    want_cls = None
    if isinstance(f, ast.Name):
        name = f.id
    elif isinstance(f, ast.Attribute) \
            and isinstance(f.value, ast.Name) \
            and f.value.id in ("self", "cls"):
        name = f.attr
        want_cls = caller.cls
    if name is None:
        return None
    cands = mod.fns.get(name, [])
    if want_cls is not None:
        cands = [c for c in cands if c.cls == want_cls] or cands
    return cands[0] if cands else None


# ---------------------------------------------------------------------- #
# schema extraction
# ---------------------------------------------------------------------- #
@dataclass
class WireModel:
    """Extraction result: the schema plus the finding anchors."""

    schema: dict
    splats: List[SplatSite] = field(default_factory=list)
    #: (module, lineno) anchor per codec base / message op, for
    #: finding locations.
    anchors: Dict[str, Tuple[str, int]] = field(default_factory=dict)


def _scan_modules(root: Optional[str]):
    serve_only = root is None
    if root is None:
        root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
    mods = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d != "__pycache__"]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root)
            module = rel[:-3].replace(os.sep, ".")
            if module.endswith(".__init__"):
                module = module[:-len(".__init__")]
            # Dict literals with an "op" key exist outside the wire
            # protocol too (telemetry profiling records); on the
            # default package scan only serve.* speaks the protocol.
            if serve_only and not module.startswith("serve"):
                continue
            with open(path, encoding="utf-8") as f:
                source = f.read()
            mods.append((module, ast.parse(source, filename=path)))
    return mods


def extract_schema(root: Optional[str] = None) -> WireModel:
    """Extract the full wire schema from the package's ASTs.

    ``root=None`` scans ``multigrad_tpu`` itself (serve modules
    only); pass an explicit directory (e.g. a fixture tree) to scan
    everything under it.
    """
    codecs: Dict[str, dict] = {}
    messages: Dict[str, dict] = {}
    model = WireModel(schema={})
    scanners = [(_Scanner(m, t), t) for m, t in _scan_modules(root)]

    # 1. codec pairs (module-level functions only — a class's
    #    `_exception_from_wire`-style helper is a message handler,
    #    reached through the reader dispatch, not a codec)
    for sc, _tree in scanners:
        mod = sc.mod
        for name, fns in mod.fns.items():
            for fn in fns:
                if fn.cls is not None:
                    continue
                if name.endswith("_to_wire"):
                    base = name[:-len("_to_wire")]
                    entry = codecs.setdefault(
                        base, {"writer": None, "reader": None})
                    entry["writer"] = _writer_keys(fn, mod.consts)
                    model.anchors.setdefault(
                        f"codec:{base}",
                        (mod.module, fn.node.lineno))
                elif name.endswith("_from_wire") and fn.params:
                    base = name[:-len("_from_wire")]
                    entry = codecs.setdefault(
                        base, {"writer": None, "reader": None})
                    wire_param = fn.params[0] \
                        if fn.params[0] not in ("self", "cls") \
                        else (fn.params[1] if len(fn.params) > 1
                              else None)
                    if wire_param is None:
                        continue
                    entry["reader"] = _follow_reads(
                        fn, wire_param, mod, model.splats, set())
                    model.anchors.setdefault(
                        f"codec:{base}",
                        (mod.module, fn.node.lineno))

    # 2. message constructors ({"op": ...} dict literals, including
    #    post-hoc msg["k"] = ... decorations), and the READY
    #    handshake line.
    for sc, tree in scanners:
        mod = sc.mod
        for fns in mod.fns.values():
            for fn in fns:
                _collect_messages(fn, mod, messages, model)
        _collect_ready(mod, tree, messages, model)

    # 3. dispatch readers (op = msg.get("op") ... if op == ...:)
    for sc, _tree in scanners:
        mod = sc.mod
        for fns in mod.fns.values():
            for fn in fns:
                _collect_reader(fn, mod, messages, model)

    model.schema = {
        "version": PROTOCOL_VERSION,
        "codecs": codecs,
        "messages": messages,
    }
    return model


def _direction(module: str, reading: bool = False) -> str:
    from_worker = "worker" in module.rsplit(".", 1)[-1]
    if reading:
        from_worker = not from_worker
    return "worker_to_router" if from_worker else "router_to_worker"


def _collect_messages(fn: _Fn, mod: _Mod, messages, model: WireModel):
    # (op, keys, dynamic, holding var, lineno) per {"op": ...} literal
    found: List[tuple] = []
    for n in _walk_no_fn(fn.node):
        if not isinstance(n, ast.Dict):
            continue
        op = None
        for k, v in zip(n.keys, n.values):
            if k is not None and _const_str(k) == "op":
                op = _const_str(v)
        if op is None:
            continue
        keys: Dict[str, str] = {}
        dynamic: List[bool] = []
        _dict_literal_keys(n, keys, dynamic)
        keys.pop("op", None)
        var = None
        for a in _walk_no_fn(fn.node):
            if isinstance(a, ast.Assign) and a.value is n \
                    and len(a.targets) == 1 \
                    and isinstance(a.targets[0], ast.Name):
                var = a.targets[0].id
        found.append((op, keys, bool(dynamic), var, n.lineno))
    if not found:
        return
    # Post-hoc decoration BEFORE merging: a key added to the held
    # message conditionally (`if req.trace is not None:
    # msg["trace"] = ...`) is an optional writer key.
    byvar = {var: keys for op, keys, _dyn, var, _ln in found if var}
    for n in _walk_no_fn(fn.node):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Subscript) \
                and isinstance(n.targets[0].value, ast.Name) \
                and n.targets[0].value.id in byvar:
            key = _const_str(n.targets[0].slice)
            if key is not None and key != "op":
                byvar[n.targets[0].value.id] \
                    .setdefault(key, OPTIONAL)
    for op, keys, dynamic, _var, lineno in found:
        _merge_writer(messages, op, keys, dynamic,
                      _direction(fn.module))
        model.anchors.setdefault(f"message:{op}",
                                 (fn.module, lineno))


def _merge_writer(messages, op: str, keys: Dict[str, str],
                  dynamic: bool, direction: str):
    """Several constructors may write one op (three ``reject``
    shapes): the writer contract is the union of keys, required only
    when required by every constructor."""
    entry = messages.setdefault(op, {
        "direction": direction, "writer": None, "dynamic": False,
        "reader": None})
    entry["dynamic"] = entry["dynamic"] or dynamic
    if entry["writer"] is None:
        entry["writer"] = dict(keys)
        return
    prev = entry["writer"]
    for k in set(prev) | set(keys):
        if prev.get(k) == REQUIRED and keys.get(k) == REQUIRED:
            prev[k] = REQUIRED
        else:
            prev[k] = OPTIONAL


def _collect_ready(mod: _Mod, tree, messages, model: WireModel):
    """The ``FLEET-WORKER-READY {json}`` stdout handshake — detected
    as json.dumps of a dict literal concatenated to the marker
    string."""
    for n in ast.walk(tree):
        if not (isinstance(n, ast.BinOp)
                and isinstance(n.op, ast.Add)):
            continue
        marker = _const_str(n.left) or _const_str(n.right) or ""
        if not marker.startswith(_READY_PREFIX):
            continue
        other = n.right if _const_str(n.left) else n.left
        if isinstance(other, ast.Call) \
                and isinstance(other.func, ast.Attribute) \
                and other.func.attr == "dumps" \
                and other.args \
                and isinstance(other.args[0], ast.Dict):
            keys: Dict[str, str] = {}
            dynamic: List[bool] = []
            _dict_literal_keys(other.args[0], keys, dynamic)
            _merge_writer(messages, "ready", keys, bool(dynamic),
                          _direction(mod.module))
            model.anchors.setdefault("message:ready",
                                     (mod.module, n.lineno))


def _collect_reader(fn: _Fn, mod: _Mod, messages, model: WireModel):
    """A dispatch reader: ``op = msg.get("op")`` followed by an
    ``if op == "...":`` chain.  Per-branch reads of the msg dict are
    followed through handler calls."""
    opvar = msgvar = None
    for n in _walk_no_fn(fn.node):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name) \
                and isinstance(n.value, ast.Call) \
                and isinstance(n.value.func, ast.Attribute) \
                and n.value.func.attr == "get" \
                and isinstance(n.value.func.value, ast.Name) \
                and n.value.args \
                and _const_str(n.value.args[0]) == "op":
            opvar = n.targets[0].id
            msgvar = n.value.func.value.id
            break
    if opvar is None:
        return
    for n in _walk_no_fn(fn.node):
        if not isinstance(n, ast.If):
            continue
        op = _op_test(n.test, opvar)
        if op is None:
            continue
        splats: List[SplatSite] = []
        keys: Dict[str, str] = {}
        visited: set = set()
        for stmt in n.body:
            branch = _Fn(fn.module, fn.cls, fn.name, stmt, fn.params)
            sub = _follow_reads(branch, msgvar, mod, splats, visited)
            for k, status in sub.items():
                if status == REQUIRED:
                    keys[k] = REQUIRED
                else:
                    keys.setdefault(k, OPTIONAL)
            # each branch statement gets a fresh visited-key for the
            # top frame but shares callee memoization
            visited.discard((fn.module, fn.cls, fn.name, msgvar))
        keys.pop("op", None)
        model.splats.extend(splats)
        entry = messages.setdefault(op, {
            "direction": _direction(fn.module, reading=True),
            "writer": None, "dynamic": False, "reader": None})
        if entry["reader"] is None:
            entry["reader"] = {}
        for k, status in keys.items():
            if status == REQUIRED:
                entry["reader"][k] = REQUIRED
            else:
                entry["reader"].setdefault(k, OPTIONAL)
        model.anchors.setdefault(f"reader:{op}",
                                 (fn.module, n.lineno))


def _op_test(test, opvar: str) -> Optional[str]:
    """``op == "result"`` — possibly inside ``op == "chaos" and
    args.chaos``."""
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        for v in test.values:
            op = _op_test(v, opvar)
            if op is not None:
                return op
        return None
    if isinstance(test, ast.Compare) \
            and isinstance(test.left, ast.Name) \
            and test.left.id == opvar \
            and len(test.ops) == 1 \
            and isinstance(test.ops[0], ast.Eq):
        return _const_str(test.comparators[0])
    return None


# ---------------------------------------------------------------------- #
# manifest
# ---------------------------------------------------------------------- #
def dump_schema(schema: dict) -> str:
    """Canonical (sorted, stable) JSON for the manifest."""
    return json.dumps(schema, indent=2, sort_keys=True) + "\n"


def diff_schema(expected, actual, prefix: str = "") -> List[str]:
    """Key-level recursive diff, manifest vs extracted.  Each line
    names the exact path that drifted — the CI gate's output."""
    out: List[str] = []
    if isinstance(expected, dict) and isinstance(actual, dict):
        for k in sorted(set(expected) | set(actual), key=str):
            path = f"{prefix}.{k}" if prefix else str(k)
            if k not in actual:
                out.append(f"{path}: removed "
                           f"(manifest has {expected[k]!r})")
            elif k not in expected:
                out.append(f"{path}: added "
                           f"(extracted {actual[k]!r}, "
                           "not in manifest)")
            else:
                out.extend(diff_schema(expected[k], actual[k], path))
        return out
    if expected != actual:
        out.append(f"{prefix}: {expected!r} -> {actual!r}")
    return out


def protocol_markdown(schema: dict) -> str:
    """Render the schema as ``docs/wire_protocol.md`` content."""
    lines = [
        "# Wire protocol",
        "",
        "<!-- Generated from the extracted wire schema"
        " (`python -m multigrad_tpu.analysis.lint --targets wire"
        " --emit-protocol -` renders `analysis/protocol.json`)."
        " Regenerate rather than editing by hand. -->",
        "",
        f"Protocol manifest version: **{schema.get('version')}**.",
        "",
        "The router and its workers exchange newline-delimited JSON",
        "(`serve/wire.py`).  Two invariants make a mixed-version",
        "fleet safe, and both are machine-checked by the `wire` lint",
        "target (`analysis/wireschema.py`):",
        "",
        "1. **Key symmetry** — every key a reader *requires* is one",
        "   every writer always sends.  Optional keys are read with",
        "   `.get` and stay entirely off the message when absent, so",
        "   an undecorated legacy message is byte-identical to the",
        "   older protocol.",
        "2. **Known-keys-only readers** — no reader splats a wire",
        "   dict into a constructor; unknown fields from a newer",
        "   peer are ignored, never a crash.",
        "",
        "## Codec pairs",
        "",
        "`<base>_to_wire` / `<base>_from_wire` in `serve/wire.py`.",
        "Reader status `required` means the decode raises without",
        "the key; `optional` keys default when absent.",
        "",
    ]
    for base in sorted(schema.get("codecs", {})):
        entry = schema["codecs"][base]
        lines += [f"### `{base}`", "",
                  "| key | writer | reader |", "| --- | --- | --- |"]
        writer = entry.get("writer") or {}
        reader = entry.get("reader") or {}
        for key in sorted(set(writer) | set(reader)):
            lines.append(
                f"| `{key}` | {writer.get(key, '—')} "
                f"| {reader.get(key, '—')} |")
        lines.append("")
    lines += [
        "## Messages",
        "",
        "Every `{\"op\": ...}` frame on the router↔worker channel.",
        "`dynamic` writers splat a payload whose keys are not",
        "statically known (the chaos channel); symmetry checking",
        "skips them.",
        "",
    ]
    for op in sorted(schema.get("messages", {})):
        entry = schema["messages"][op]
        writer = entry.get("writer")
        reader = entry.get("reader")
        lines += [f"### `{op}` ({entry.get('direction')})", ""]
        if entry.get("dynamic"):
            lines.append("*Writer carries a dynamic payload.*")
            lines.append("")
        lines += ["| key | writer | reader |", "| --- | --- | --- |"]
        for key in sorted(set(writer or {}) | set(reader or {})):
            w = (writer or {}).get(key, "—")
            r = (reader or {}).get(key, "—")
            lines.append(f"| `{key}` | {w} | {r} |")
        lines.append("")
    lines += [
        "## Manifest-bump procedure",
        "",
        "The extracted schema is pinned in `multigrad_tpu/analysis/",
        "protocol.json`.  CI re-extracts and diffs on every run: a",
        "codec change that does not update the manifest fails the",
        "`wire` lint target with a key-level diff naming the drifted",
        "field.  To change the protocol:",
        "",
        "1. Make the codec change (writer AND reader, keeping new",
        "   keys optional on the reader side so old peers still",
        "   decode).",
        "2. Regenerate: `python -m multigrad_tpu.analysis.lint",
        "   --targets wire --emit-protocol",
        "   multigrad_tpu/analysis/protocol.json`.",
        "3. Commit the manifest diff alongside the code — the diff",
        "   IS the protocol review.",
        "",
        "Regenerate this document with",
        "`python - <<'PY'` + `protocol_markdown(...)` (see",
        "`docs/static_analysis.md`).",
        "",
    ]
    return "\n".join(lines)


# ---------------------------------------------------------------------- #
# checks
# ---------------------------------------------------------------------- #
def _anchor(model: WireModel, key: str) -> str:
    mod, lineno = model.anchors.get(key, ("", 0))
    if not mod:
        return ""
    return mod.replace(".", "/") + f".py:{lineno}"


def _check_asymmetry(model: WireModel) -> List[Finding]:
    out = []
    schema = model.schema
    for base, entry in sorted(schema.get("codecs", {}).items()):
        writer, reader = entry.get("writer"), entry.get("reader")
        if writer is None or reader is None:
            out.append(Finding(
                "wire-key-asymmetry", ERROR,
                f"codec {base!r} has a "
                f"{'writer' if reader is None else 'reader'} but no "
                f"{'reader' if reader is None else 'writer'} — every "
                "codec ships as a _to_wire/_from_wire pair",
                program=_PROGRAM,
                where=_anchor(model, f"codec:{base}")))
            continue
        for key, status in sorted(reader.items()):
            if status == REQUIRED and key not in writer:
                out.append(Finding(
                    "wire-key-asymmetry", ERROR,
                    f"codec {base!r}: reader requires key {key!r} "
                    "that the writer never sends — decode of every "
                    "message raises",
                    program=_PROGRAM,
                    where=_anchor(model, f"codec:{base}")))
        for key in sorted(set(writer) - set(reader)):
            out.append(Finding(
                "wire-key-asymmetry", WARNING,
                f"codec {base!r}: writer sends key {key!r} that the "
                "reader never reads — dead field or a misspelled "
                "reader key",
                program=_PROGRAM,
                where=_anchor(model, f"codec:{base}")))
    for op, entry in sorted(schema.get("messages", {}).items()):
        writer, reader = entry.get("writer"), entry.get("reader")
        if writer is None or reader is None or entry.get("dynamic"):
            continue
        for key, status in sorted(reader.items()):
            if status == REQUIRED \
                    and writer.get(key) != REQUIRED:
                missing = "optional in" if key in writer \
                    else "missing from"
                out.append(Finding(
                    "wire-key-asymmetry", ERROR,
                    f"message {op!r}: reader requires key {key!r} "
                    f"that is {missing} the writer — a legacy or "
                    "shed message crashes the dispatch loop",
                    program=_PROGRAM,
                    where=_anchor(model, f"reader:{op}")
                    or _anchor(model, f"message:{op}")))
    return out


def _check_splat(model: WireModel) -> List[Finding]:
    out = []
    seen = set()
    for s in model.splats:
        anchor = (s.module, s.lineno)
        if anchor in seen:
            continue
        seen.add(anchor)
        out.append(Finding(
            "wire-reader-splat", ERROR,
            f"wire dict {s.param!r} is **-splatted into a call — "
            "readers are known-keys-only; a newer peer's extra "
            "field must be ignored, not forwarded as an unexpected "
            "keyword",
            program=_PROGRAM,
            where=s.module.replace(".", "/")
            + f".py:{s.lineno} ({s.func})"))
    return out


def _check_drift(model: WireModel,
                 manifest_path: Optional[str]) -> List[Finding]:
    path = manifest_path or DEFAULT_MANIFEST_PATH
    if not os.path.exists(path):
        return [Finding(
            "wire-manifest-drift", ERROR,
            f"wire-protocol manifest {path} does not exist — "
            "generate it with --emit-protocol and commit it; the "
            "manifest is the mixed-version-fleet compatibility gate",
            program=_PROGRAM, path=path)]
    with open(path, encoding="utf-8") as f:
        manifest = json.load(f)
    diffs = diff_schema(manifest, model.schema)
    return [Finding(
        "wire-manifest-drift", ERROR,
        f"extracted wire schema drifted from the manifest: {d} — "
        "a deliberate protocol change must bump the manifest "
        "(--emit-protocol) in the same commit",
        program=_PROGRAM, where=d.split(":", 1)[0], path=path)
        for d in diffs]


def analyze_wire(root: Optional[str] = None, checks=None,
                 manifest_path: Optional[str] = None,
                 model: Optional[WireModel] = None) -> List[Finding]:
    """Run the wire checks; a clean, undrifted tree is ``[]``.

    ``checks`` subsets :data:`WIRE_CHECK_IDS`.  ``manifest_path``
    overrides the checked-in ``analysis/protocol.json`` (the drift
    gate's expectation).
    """
    if model is None:
        model = extract_schema(root)
    selected = set(WIRE_CHECK_IDS) if checks is None \
        else {c for c in checks if c in WIRE_CHECK_IDS}
    findings: List[Finding] = []
    if "wire-key-asymmetry" in selected:
        findings.extend(_check_asymmetry(model))
    if "wire-reader-splat" in selected:
        findings.extend(_check_splat(model))
    if "wire-manifest-drift" in selected:
        findings.extend(_check_drift(model, manifest_path))
    return findings
