"""AST inventory of the package's concurrency surface.

The serve/fleet layer is ~4k LoC of hand-threaded code (dispatcher
threads, heartbeat monitors, condition-variable queues, RPC writers)
whose invariants — lock acquisition order, condition-wait predicates,
what may run while a lock is held — were previously enforced by
review eyeballs.  This module makes them machine-readable: a pure
``ast`` walk over the package (zero imports of the scanned code, so
it runs in CI without a device or even jax) that inventories

* every lock/rlock/condition/event/semaphore **definition** —
  ``threading.*`` constructors and the :mod:`multigrad_tpu.utils
  .lockdep` factories alike — under a **canonical name**
  (``"serve.queue.FitQueue._lock"``) shared with the runtime shadow;
* every **thread spawn site** (``threading.Thread``/``Timer``) and
  its ``name=`` hygiene;
* the **lock-acquisition-order graph**: acquiring B inside a ``with
  A:`` (or between ``A.acquire()``/``A.release()``) adds the edge
  ``A → B``, following one level of intra-module calls, plus the
  ``may_precede=`` edges declared at :func:`~multigrad_tpu.utils
  .lockdep.make_lock` call sites for orderings the AST cannot derive
  (dynamic sink/callback dispatch);
* per-site facts the checks in :mod:`.concurrency` consume:
  condition ``wait()`` sites and their enclosing-``while`` status,
  ``notify`` sites and the locks held there, blocking/callback calls
  under locks, attribute writes with the held-lock set and the
  thread root(s) that can reach them.

Thread roots are propagated over the intra-module call graph to a
fixpoint: a function is attributed to every spawn target that
reaches it (and to ``<main>`` when reachable from non-thread code),
so "written from two different threads" is decidable per write site.

Conditions created over a sibling lock (``threading.Condition(
self._lock)``) resolve to the *underlying* mutex, so ``with
self._not_empty:`` correctly counts as holding ``._lock``.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = ["LockDef", "SpawnSite", "EdgeSite", "OpSite", "WaitSite",
           "NotifySite", "WriteSite", "AllowEntry",
           "ConcurrencyModel", "scan_package", "find_cycles",
           "to_dot", "MAIN_ROOT"]

MAIN_ROOT = "<main>"

#: ``threading`` constructors we inventory, by kind.
THREADING_KINDS = {
    "Lock": "lock", "RLock": "rlock", "Condition": "condition",
    "Event": "event", "Semaphore": "semaphore",
    "BoundedSemaphore": "semaphore",
}
#: lockdep factory names, by kind.
FACTORY_KINDS = {
    "make_lock": "lock", "make_rlock": "rlock",
    "make_condition": "condition",
}
#: Held-lock tracking applies to these kinds only (events and
#: semaphores are signalling primitives, not mutual exclusion).
HELD_KINDS = ("lock", "rlock", "condition")

#: Method/function names whose *call* blocks the calling thread
#: (sockets, subprocesses, device dispatch, sleeps).  ``Condition
#: .wait`` is deliberately absent — it releases the lock.
BLOCKING_ATTRS = {
    "sendall", "recv", "recv_into", "accept", "connect",
    "communicate", "sleep", "block_until_ready", "readline",
    "create_connection", "getaddrinfo", "urlopen", "select",
}
#: Receiver-name fragments that make a ``.wait()``/``.join()`` call
#: count as blocking (process handles, thread handles) — conditions
#: are excluded by kind, events by their inventory entry.
BLOCKING_WAIT_RECV = ("proc", "thread", "process")
#: Attribute names that identify a user-callback invocation.
CALLBACK_NAMES = {"callback", "action"}

_ALLOW_RE = re.compile(r"#\s*lock-ok:\s*([a-z0-9-]+)\s*(.*)$")


@dataclass(frozen=True)
class LockDef:
    name: str                    # canonical, e.g. serve.queue.FitQueue._lock
    kind: str                    # lock / rlock / condition / event / semaphore
    module: str
    lineno: int
    shares: Optional[str] = None         # condition -> underlying lock name
    declared_name: Optional[str] = None  # factory literal, if any
    may_precede: Tuple[str, ...] = ()    # declared edges ("*" allowed)


@dataclass(frozen=True)
class SpawnSite:
    module: str
    func: str
    lineno: int
    kind: str                    # thread / timer
    target: Optional[str] = None
    has_name: bool = False
    cls: Optional[str] = None    # class of the spawning function


@dataclass(frozen=True)
class EdgeSite:
    src: str
    dst: str
    module: str
    func: str
    lineno: int
    via: Optional[str] = None    # callee name for one-level edges
    declared: bool = False


@dataclass(frozen=True)
class OpSite:
    """A blocking or callback call made while holding locks."""
    op: str                      # "blocking" / "callback"
    desc: str
    module: str
    func: str
    lineno: int
    held: Tuple[str, ...]
    via: Optional[str] = None


@dataclass(frozen=True)
class WaitSite:
    cond: str
    module: str
    func: str
    lineno: int
    in_while: bool


@dataclass(frozen=True)
class NotifySite:
    cond: str
    owner: str
    module: str
    func: str
    lineno: int
    held: Tuple[str, ...]
    cls: Optional[str] = None    # class of the notifying function


@dataclass(frozen=True)
class WriteSite:
    module: str
    attr: str
    func: str
    lineno: int
    held: Tuple[str, ...]
    in_init: bool
    receiver: str = "self"
    # class of the written object for `self.attr = ...` writes
    # (None for writes through other receivers, whose type is
    # unknown statically), and the thread-root lookup key of the
    # function containing the write.
    owner_cls: Optional[str] = None
    func_key: str = ""


@dataclass
class AllowEntry:
    module: str
    lineno: int
    check: str
    reason: str
    used: bool = False


@dataclass
class _FuncInfo:
    key: str                               # mod.[Class.]name
    module: str
    simple: str
    cls: Optional[str] = None
    acquired: set = field(default_factory=set)
    # (caller_cls_ctx, callee_name, is_self_call, held, lineno) —
    # resolved to _FuncInfo keys after the whole module is scanned
    calls: list = field(default_factory=list)
    blocking: list = field(default_factory=list)   # OpSite
    notifies: list = field(default_factory=list)


@dataclass
class ConcurrencyModel:
    locks: Dict[str, LockDef] = field(default_factory=dict)
    spawns: List[SpawnSite] = field(default_factory=list)
    edges: List[EdgeSite] = field(default_factory=list)
    ops: List[OpSite] = field(default_factory=list)
    waits: List[WaitSite] = field(default_factory=list)
    notifies: List[NotifySite] = field(default_factory=list)
    writes: List[WriteSite] = field(default_factory=list)
    allows: List[AllowEntry] = field(default_factory=list)
    func_roots: Dict[str, frozenset] = field(default_factory=dict)
    # every RESOLVED intra-module call site:
    # (module, callee_cls, callee_name, held, lineno) — the
    # notify-outside-lock check's caller-context evidence
    calls: List[tuple] = field(default_factory=list)

    def edge_pairs(self) -> set:
        """Every (src, dst) pair of the graph — derived AND declared
        (wildcards excluded; see :meth:`wildcard_sources`)."""
        return {(e.src, e.dst) for e in self.edges if e.dst != "*"}

    def wildcard_sources(self) -> set:
        """Locks declared ``may_precede="*"``."""
        return {e.src for e in self.edges if e.dst == "*"}


# ------------------------------------------------------------------ #
# per-module scanning
# ------------------------------------------------------------------ #
def _dotted(node) -> str:
    """Best-effort dotted rendering of an expression (for messages
    and receiver heuristics)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{_dotted(node.value)}.{node.attr}"
    if isinstance(node, ast.Call):
        return f"{_dotted(node.func)}()"
    return node.__class__.__name__.lower()


def _lock_ctor_kind(call: ast.Call) -> Optional[Tuple[str, bool]]:
    """``(kind, is_factory)`` when ``call`` constructs a lock-like
    object (``threading.X(...)``, bare ``X(...)`` from a
    ``from threading import X``, or a lockdep factory), else None."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        if (isinstance(fn.value, ast.Name)
                and fn.value.id == "threading"
                and fn.attr in THREADING_KINDS):
            return THREADING_KINDS[fn.attr], False
        if fn.attr in FACTORY_KINDS:      # lockdep.make_lock(...)
            return FACTORY_KINDS[fn.attr], True
    if isinstance(fn, ast.Name):
        if fn.id in FACTORY_KINDS:
            return FACTORY_KINDS[fn.id], True
        if fn.id in THREADING_KINDS:
            return THREADING_KINDS[fn.id], False
    return None


def _unwrap_factory(call: ast.Call):
    """``(kind, is_factory, call)`` for a lock constructor, looking
    through ``field(default_factory=...)`` and zero-arg lambdas (the
    dataclass-field idiom)."""
    res = _lock_ctor_kind(call)
    if res is not None:
        return res[0], res[1], call
    fn = call.func
    if isinstance(fn, ast.Name) and fn.id == "field":
        for kw in call.keywords:
            if kw.arg != "default_factory":
                continue
            v = kw.value
            if isinstance(v, ast.Lambda) \
                    and isinstance(v.body, ast.Call):
                inner = _lock_ctor_kind(v.body)
                if inner is not None:
                    return inner[0], inner[1], v.body
            if isinstance(v, (ast.Name, ast.Attribute)):
                name = v.attr if isinstance(v, ast.Attribute) \
                    else v.id
                if name in THREADING_KINDS:
                    return THREADING_KINDS[name], False, call
    return None


def _str_const(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class _ModuleScanner:
    def __init__(self, module: str, tree: ast.Module, source: str,
                 model: ConcurrencyModel):
        self.module = module
        self.tree = tree
        self.model = model
        # (scope_key, symbol) -> LockDef; scope_key "" = module,
        # class name for self-attrs, function key for locals.
        self.symbols: Dict[Tuple[str, str], LockDef] = {}
        # (cls_or_None, simple_name) -> _FuncInfo.  Class-qualified
        # so two classes' same-named methods never merge (a merged
        # `close` would attribute one class's acquisitions to the
        # other's call sites — phantom lock-order edges).
        self.funcs: Dict[Tuple[Optional[str], str], _FuncInfo] = {}
        self._parse_allows(source)

    def fkey(self, cls: Optional[str], name: str) -> str:
        return ".".join(x for x in (self.module, cls, name) if x)

    def resolve_callee(self, cls_ctx: Optional[str], name: str,
                       is_self: bool) -> Optional[_FuncInfo]:
        """A call's target _FuncInfo: `self.m()` resolves within the
        calling class only; a bare `f()` prefers a same-class nested
        function, then a module-level one."""
        if is_self:
            return self.funcs.get((cls_ctx, name))
        return (self.funcs.get((cls_ctx, name))
                or self.funcs.get((None, name)))

    def _parse_allows(self, source: str):
        for i, line in enumerate(source.splitlines(), start=1):
            m = _ALLOW_RE.search(line)
            if m:
                self.model.allows.append(AllowEntry(
                    self.module, i, m.group(1),
                    m.group(2).strip()))

    # -- pass 1: lock definitions -------------------------------------- #
    def collect_defs(self):
        self._collect_scope(self.tree.body, scope="", owner="")
        for cls in [n for n in ast.walk(self.tree)
                    if isinstance(n, ast.ClassDef)]:
            # class-body fields (dataclass default_factory idiom)
            self._collect_scope(cls.body, scope=cls.name,
                                owner=cls.name, class_body=True)
            for fn in [n for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]:
                self._collect_fn_defs(fn, cls.name)
        for fn in [n for n in self.tree.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]:
            self._collect_fn_defs(fn, None)

    def _collect_fn_defs(self, fn, cls: Optional[str]):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                for tgt in node.targets:
                    self._maybe_def(tgt, node.value, fn, cls)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) \
                    and node is not fn:
                pass      # nested fns re-walked via module walk

    def _collect_scope(self, body, scope: str, owner: str,
                       class_body: bool = False):
        for node in body:
            value = None
            targets = []
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                value, targets = node.value, node.targets
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.value, ast.Call):
                value, targets = node.value, [node.target]
            if value is None:
                continue
            info = _unwrap_factory(value)
            if info is None:
                continue
            kind, is_factory, call = info
            for tgt in targets:
                if isinstance(tgt, ast.Name):
                    canonical = (f"{self.module}.{owner}.{tgt.id}"
                                 if class_body and owner
                                 else f"{self.module}.{tgt.id}")
                    self._register(canonical, kind, is_factory,
                                   call, node.lineno,
                                   scope_key=(owner if class_body
                                              else ""),
                                   symbol=tgt.id)

    def _maybe_def(self, tgt, call: ast.Call, fn, cls: Optional[str]):
        info = _unwrap_factory(call)
        if info is None:
            return
        kind, is_factory, call = info
        if isinstance(tgt, ast.Attribute) \
                and isinstance(tgt.value, ast.Name) \
                and tgt.value.id == "self" and cls is not None:
            canonical = f"{self.module}.{cls}.{tgt.attr}"
            self._register(canonical, kind, is_factory, call,
                           tgt.lineno, scope_key=cls,
                           symbol=tgt.attr)
        elif isinstance(tgt, ast.Name):
            canonical = f"{self.module}.{fn.name}.{tgt.id}"
            self._register(canonical, kind, is_factory, call,
                           tgt.lineno, scope_key=fn.name,
                           symbol=tgt.id)

    def _register(self, canonical: str, kind: str, is_factory: bool,
                  call: ast.Call, lineno: int, scope_key: str,
                  symbol: str):
        declared = None
        may_precede: Tuple[str, ...] = ()
        shares = None
        if is_factory:
            if call.args:
                declared = _str_const(call.args[0])
            for kw in call.keywords:
                if kw.arg == "name":
                    declared = _str_const(kw.value) or declared
                elif kw.arg == "may_precede":
                    v = kw.value
                    s = _str_const(v)
                    if s is not None:
                        may_precede = (s,)
                    elif isinstance(v, (ast.Tuple, ast.List)):
                        may_precede = tuple(
                            x for x in (_str_const(e)
                                        for e in v.elts)
                            if x is not None)
        if kind == "condition":
            lock_arg = None
            if is_factory:
                for kw in call.keywords:
                    if kw.arg == "lock":
                        lock_arg = kw.value
                if lock_arg is None and len(call.args) > 1:
                    lock_arg = call.args[1]
            elif call.args:
                lock_arg = call.args[0]
            if isinstance(lock_arg, ast.Attribute) \
                    and isinstance(lock_arg.value, ast.Name) \
                    and lock_arg.value.id == "self":
                shares = f"{self.module}.{scope_key}.{lock_arg.attr}"
        ld = LockDef(name=canonical, kind=kind, module=self.module,
                     lineno=lineno, shares=shares,
                     declared_name=declared,
                     may_precede=may_precede)
        self.model.locks[canonical] = ld
        self.symbols[(scope_key, symbol)] = ld
        for dst in may_precede:
            self.model.edges.append(EdgeSite(
                src=canonical, dst=dst, module=self.module,
                func="<declared>", lineno=lineno, declared=True))

    # -- pass 2: function bodies --------------------------------------- #
    def analyze_functions(self):
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                self._analyze_fn(node, cls=None, prefix="")
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        self._analyze_fn(sub, cls=node.name,
                                         prefix="")

    def _analyze_fn(self, fn, cls: Optional[str], prefix: str):
        simple = fn.name
        info = self.funcs.setdefault(
            (cls, simple),
            _FuncInfo(key=self.fkey(cls, simple),
                      module=self.module, simple=simple, cls=cls))
        scopes = tuple(x for x in (fn.name, prefix) if x)
        _FuncWalker(self, fn, cls, info, scopes).run()
        for node in fn.body:
            self._walk_nested(node, fn, cls)

    def _walk_nested(self, node, outer, cls):
        """Nested function defs (worker.main's closures) become
        first-class functions under their simple name."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._analyze_fn(node, cls=cls, prefix=outer.name)
            return
        for child in ast.iter_child_nodes(node):
            self._walk_nested(child, outer, cls)

    # -- lock-expression resolution ------------------------------------ #
    def resolve_lock(self, node, cls: Optional[str],
                     scopes: Tuple[str, ...]) -> Optional[LockDef]:
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" and cls is not None:
            return self.symbols.get((cls, node.attr))
        if isinstance(node, ast.Name):
            for scope in (*scopes, ""):
                ld = self.symbols.get((scope, node.id))
                if ld is not None:
                    return ld
        return None

    def underlying(self, ld: LockDef) -> str:
        if ld.kind == "condition" and ld.shares \
                and ld.shares in self.model.locks:
            return ld.shares
        return ld.name


class _FuncWalker:
    """Statement-ordered walk of one function body with a held-lock
    stack; records edges, wait/notify/blocking/callback/write sites
    and intra-module call sites."""

    def __init__(self, scanner: _ModuleScanner, fn,
                 cls: Optional[str], info: _FuncInfo,
                 scopes: Tuple[str, ...] = ()):
        self.s = scanner
        self.fn = fn
        self.cls = cls
        self.info = info
        self.scopes = scopes or (fn.name,)
        self.held: List[str] = []
        self.while_depth = 0
        self.in_init = fn.name in ("__init__", "__post_init__")

    def run(self):
        for stmt in self.fn.body:
            self._stmt(stmt)

    # -- statements ---------------------------------------------------- #
    def _stmt(self, node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return                      # separate scope
        if isinstance(node, ast.With):
            pushed = []
            for item in node.items:
                self._expr(item.context_expr)
                ld = self.s.resolve_lock(item.context_expr,
                                         self.cls, self.scopes)
                if ld is not None and ld.kind in HELD_KINDS:
                    name = self.s.underlying(ld)
                    self._acquire(name, node.lineno)
                    pushed.append(name)
            for stmt in node.body:
                self._stmt(stmt)
            for name in reversed(pushed):
                self._release(name)
            return
        if isinstance(node, ast.While):
            self._expr(node.test)
            self.while_depth += 1
            for stmt in node.body:
                self._stmt(stmt)
            self.while_depth -= 1
            for stmt in node.orelse:
                self._stmt(stmt)
            return
        if isinstance(node, ast.For):
            self._expr(node.iter)
            for stmt in node.body:
                self._stmt(stmt)
            for stmt in node.orelse:
                self._stmt(stmt)
            return
        if isinstance(node, (ast.If,)):
            self._expr(node.test)
            for stmt in node.body:
                self._stmt(stmt)
            for stmt in node.orelse:
                self._stmt(stmt)
            return
        if isinstance(node, (ast.Try,)):
            for stmt in node.body:
                self._stmt(stmt)
            for h in node.handlers:
                for stmt in h.body:
                    self._stmt(stmt)
            for stmt in node.orelse + node.finalbody:
                self._stmt(stmt)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign,
                             ast.AnnAssign)):
            self._assign(node)
            return
        # Everything else: visit expressions in order.
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._stmt(child)
            else:
                self._expr(child)

    def _assign(self, node):
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AugAssign):
            targets, value = [node.target], node.value
        else:
            targets = [node.target]
            value = node.value
        if value is not None:
            self._expr(value)
        is_lock_def = (isinstance(value, ast.Call)
                       and _unwrap_factory(value) is not None)
        for tgt in targets:
            if is_lock_def:
                continue
            if isinstance(tgt, ast.Attribute):
                recv = _dotted(tgt.value)
                self.s.model.writes.append(WriteSite(
                    module=self.s.module, attr=tgt.attr,
                    func=self.fn.name, lineno=tgt.lineno,
                    held=tuple(self.held),
                    in_init=self.in_init, receiver=recv,
                    owner_cls=(self.cls if recv == "self"
                               else None),
                    func_key=self.s.fkey(self.cls,
                                         self.fn.name)))
            elif isinstance(tgt, (ast.Subscript,)):
                self._expr(tgt.value)

    # -- expressions --------------------------------------------------- #
    def _expr(self, node):
        if node is None or isinstance(node, (ast.FunctionDef,
                                             ast.AsyncFunctionDef,
                                             ast.Lambda)):
            return
        if isinstance(node, ast.Call):
            self._call(node)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)

    def _call(self, call: ast.Call):
        fn = call.func
        mod = self.s.module
        # threading.Thread / Timer spawns
        spawn_kind = None
        if isinstance(fn, ast.Attribute) \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id == "threading" \
                and fn.attr in ("Thread", "Timer"):
            spawn_kind = "thread" if fn.attr == "Thread" else "timer"
        elif isinstance(fn, ast.Name) and fn.id in ("Thread",
                                                    "Timer"):
            spawn_kind = "thread" if fn.id == "Thread" else "timer"
        if spawn_kind:
            target = None
            has_name = False
            for kw in call.keywords:
                if kw.arg == "name":
                    has_name = True
                elif kw.arg == "target":
                    if isinstance(kw.value, ast.Name):
                        target = kw.value.id
                    elif isinstance(kw.value, ast.Attribute):
                        target = kw.value.attr
            if spawn_kind == "timer" and len(call.args) > 1:
                v = call.args[1]
                if isinstance(v, ast.Name):
                    target = v.id
                elif isinstance(v, ast.Attribute):
                    target = v.attr
            self.s.model.spawns.append(SpawnSite(
                module=mod, func=self.fn.name,
                lineno=call.lineno, kind=spawn_kind,
                target=target, has_name=has_name, cls=self.cls))
            return

        if isinstance(fn, ast.Attribute):
            attr = fn.attr
            self._expr(fn.value)
            recv_ld = self.s.resolve_lock(fn.value, self.cls,
                                          self.scopes)
            # acquire/release on a known lock object
            if recv_ld is not None and recv_ld.kind in HELD_KINDS:
                if attr == "acquire":
                    self._acquire(self.s.underlying(recv_ld),
                                  call.lineno)
                    return
                if attr == "release":
                    self._release(self.s.underlying(recv_ld))
                    return
                if attr == "wait" and recv_ld.kind == "condition":
                    self.s.model.waits.append(WaitSite(
                        cond=recv_ld.name, module=mod,
                        func=self.fn.name, lineno=call.lineno,
                        in_while=self.while_depth > 0))
                    return
                if attr in ("notify", "notify_all") \
                        and recv_ld.kind == "condition":
                    self.s.model.notifies.append(NotifySite(
                        cond=recv_ld.name,
                        owner=self.s.underlying(recv_ld),
                        module=mod, func=self.fn.name,
                        lineno=call.lineno,
                        held=tuple(self.held), cls=self.cls))
                    self.info.notifies.append(call.lineno)
                    return
            # semaphore acquire / event-or-proc wait are blocking
            recv_txt = _dotted(fn.value).lower()
            blocking = None
            if recv_ld is not None and recv_ld.kind == "semaphore" \
                    and attr == "acquire":
                blocking = f"{_dotted(fn)}() [semaphore]"
            elif attr in BLOCKING_ATTRS:
                blocking = f"{_dotted(fn)}()"
            elif attr in ("wait", "join") and (
                    (recv_ld is not None
                     and recv_ld.kind == "event")
                    or any(t in recv_txt
                           for t in BLOCKING_WAIT_RECV)):
                blocking = f"{_dotted(fn)}()"
            if blocking is not None:
                site = OpSite(op="blocking", desc=blocking,
                              module=mod, func=self.fn.name,
                              lineno=call.lineno,
                              held=tuple(self.held))
                self.info.blocking.append(site)
                if self.held:
                    self.s.model.ops.append(site)
            # user callbacks
            cb = (attr.startswith("on_") or attr in CALLBACK_NAMES
                  or (attr == "write" and "sink" in recv_txt))
            if cb and self.held:
                self.s.model.ops.append(OpSite(
                    op="callback", desc=f"{_dotted(fn)}()",
                    module=mod, func=self.fn.name,
                    lineno=call.lineno, held=tuple(self.held)))
            # intra-module method call on self
            if isinstance(fn.value, ast.Name) \
                    and fn.value.id == "self":
                self.info.calls.append(
                    (self.cls, attr, True, tuple(self.held),
                     call.lineno))
        elif isinstance(fn, ast.Name):
            # bare callback parameters / intra-module functions
            if fn.id.startswith("on_") or fn.id in CALLBACK_NAMES:
                if self.held:
                    self.s.model.ops.append(OpSite(
                        op="callback", desc=f"{fn.id}()",
                        module=mod, func=self.fn.name,
                        lineno=call.lineno,
                        held=tuple(self.held)))
            self.info.calls.append(
                (self.cls, fn.id, False, tuple(self.held),
                 call.lineno))
        for arg in call.args:
            self._expr(arg)
        for kw in call.keywords:
            self._expr(kw.value)

    # -- held bookkeeping ---------------------------------------------- #
    def _acquire(self, name: str, lineno: int):
        for h in self.held:
            if h != name:
                self.s.model.edges.append(EdgeSite(
                    src=h, dst=name, module=self.s.module,
                    func=self.fn.name, lineno=lineno))
        self.held.append(name)
        self.info.acquired.add(name)

    def _release(self, name: str):
        if name in self.held:
            # pop the most recent matching entry
            for i in range(len(self.held) - 1, -1, -1):
                if self.held[i] == name:
                    del self.held[i]
                    return


# ------------------------------------------------------------------ #
# package scan + derived analyses
# ------------------------------------------------------------------ #
def _iter_py_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d != "__pycache__"]
        for f in sorted(filenames):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def _module_name(root: str, path: str) -> str:
    rel = os.path.relpath(path, root)
    mod = rel[:-3].replace(os.sep, ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def scan_package(root: Optional[str] = None) -> ConcurrencyModel:
    """Scan every ``.py`` under ``root`` (default: the installed
    ``multigrad_tpu`` package directory) into a
    :class:`ConcurrencyModel`."""
    if root is None:
        root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
    model = ConcurrencyModel()
    scanners = []
    for path in _iter_py_files(root):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            continue
        sc = _ModuleScanner(_module_name(root, path), tree, source,
                            model)
        sc.collect_defs()
        scanners.append(sc)
    for sc in scanners:
        sc.analyze_functions()
    for sc in scanners:
        _expand_calls(sc)
        _propagate_roots(sc, model)
    return model


def _expand_calls(sc: _ModuleScanner):
    """One level of intra-module call following: a call made while
    holding locks contributes the callee's own acquisitions as
    lock-order edges and the callee's blocking ops as
    blocking-under-lock sites, attributed to the call site.  Every
    resolved call also lands in ``model.calls`` (the notify check's
    caller-context evidence)."""
    for info in sc.funcs.values():
        for cls_ctx, name, is_self, held, lineno in info.calls:
            callee = sc.resolve_callee(cls_ctx, name, is_self)
            if callee is None:
                continue
            sc.model.calls.append((sc.module, callee.cls,
                                   callee.simple, held, lineno))
            if not held:
                continue
            for acquired in sorted(callee.acquired):
                for h in held:
                    if h != acquired:
                        sc.model.edges.append(EdgeSite(
                            src=h, dst=acquired,
                            module=sc.module, func=info.simple,
                            lineno=lineno, via=name))
            for op in callee.blocking:
                sc.model.ops.append(OpSite(
                    op="blocking",
                    desc=f"{op.desc} (via {name})",
                    module=sc.module, func=info.simple,
                    lineno=lineno, held=held, via=name))


def _propagate_roots(sc: _ModuleScanner, model: ConcurrencyModel):
    """Fixpoint thread-root attribution over the intra-module call
    graph: spawn targets seed their own root; functions nobody calls
    seed ``<main>``; roots flow caller -> callee until stable."""
    roots: Dict[tuple, set] = {k: set() for k in sc.funcs}
    called: Dict[tuple, set] = {k: set() for k in sc.funcs}
    resolved_calls = []
    for key, info in sc.funcs.items():
        for cls_ctx, name, is_self, _held, _lineno in info.calls:
            callee = sc.resolve_callee(cls_ctx, name, is_self)
            if callee is None:
                continue
            ckey = (callee.cls, callee.simple)
            called[ckey].add(key)
            resolved_calls.append((key, ckey))
    # A spawn's target resolves like a bare-name call from the
    # spawning context (self._method targets carry the class).
    spawn_targets = set()
    for s in model.spawns:
        if s.module != sc.module or not s.target:
            continue
        callee = sc.resolve_callee(s.cls, s.target, False)
        if callee is not None:
            spawn_targets.add((callee.cls, callee.simple))
    for key in sc.funcs:
        if key in spawn_targets:
            roots[key].add(sc.fkey(*key))
        if not called[key] and key not in spawn_targets:
            roots[key].add(MAIN_ROOT)
    changed = True
    while changed:
        changed = False
        for caller_key, callee_key in resolved_calls:
            before = len(roots[callee_key])
            roots[callee_key] |= roots[caller_key]
            if len(roots[callee_key]) != before:
                changed = True
    for key, r in roots.items():
        model.func_roots[sc.fkey(*key)] = frozenset(
            r or {MAIN_ROOT})


def find_cycles(model: ConcurrencyModel) -> List[list]:
    """Cycles in the lock-order graph (derived + declared, wildcard
    declarations excluded), as lists of lock names."""
    graph: Dict[str, set] = {}
    for a, b in model.edge_pairs():
        graph.setdefault(a, set()).add(b)
    cycles = []
    seen_cycles = set()
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in
             set(graph) | {b for bs in graph.values() for b in bs}}

    def dfs(node, path):
        color[node] = GRAY
        path.append(node)
        for nxt in sorted(graph.get(node, ())):
            if color.get(nxt, WHITE) == GRAY:
                i = path.index(nxt)
                cyc = tuple(path[i:])
                canon = tuple(sorted(cyc))
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    cycles.append(list(cyc) + [nxt])
            elif color.get(nxt, WHITE) == WHITE:
                dfs(nxt, path)
        path.pop()
        color[node] = BLACK

    for node in sorted(color):
        if color[node] == WHITE:
            dfs(node, [])
    return cycles


def to_dot(model: ConcurrencyModel) -> str:
    """The lock-order graph in Graphviz DOT (derived edges solid,
    declared dashed, conditions/events annotated; the CI artifact)."""
    shapes = {"lock": "box", "rlock": "box3d",
              "condition": "ellipse", "event": "diamond",
              "semaphore": "hexagon"}
    lines = ["digraph lock_order {",
             '  rankdir=LR; node [fontsize=10, shape=box];']
    for name in sorted(model.locks):
        ld = model.locks[name]
        if ld.kind == "condition" and ld.shares:
            continue          # rendered as its underlying mutex
        label = f"{name}\\n({ld.kind})"
        lines.append(
            f'  "{name}" [label="{label}", '
            f'shape={shapes.get(ld.kind, "box")}];')
    seen = set()
    for e in model.edges:
        if e.dst == "*":
            lines.append(
                f'  "{e.src}" [style=filled, '
                f'fillcolor="#fff2cc"];  '
                f'// may_precede="*" (fan-out declared)')
            continue
        key = (e.src, e.dst, e.declared)
        if key in seen:
            continue
        seen.add(key)
        style = "dashed" if e.declared else "solid"
        label = "declared" if e.declared \
            else f"{e.module}.{e.func}:{e.lineno}"
        lines.append(f'  "{e.src}" -> "{e.dst}" '
                     f'[style={style}, label="{label}", '
                     f'fontsize=8];')
    lines.append("}")
    return "\n".join(lines) + "\n"
