"""Concurrency static analysis: the thread-safety check registry.

Consumes the AST inventory of :mod:`.lockgraph` and reports through
the same :class:`~multigrad_tpu.analysis.findings.Finding` machinery
as the SPMD checks — one registry, one severity model, one CI gate
(``python -m multigrad_tpu.analysis.lint --targets threads``).

=====================  ==============================================
``lock-order-cycle``   the lock-acquisition-order graph (``with``
                       nesting + one level of intra-module calls +
                       ``may_precede`` declarations) contains a
                       cycle — the classic AB/BA deadlock, caught
                       before any thread runs
``cond-wait-no-while`` a ``Condition.wait()`` not guarded by a
                       ``while``-predicate loop: spurious wakeups
                       and lost-wakeup races (the PR-10
                       ``_purge_cancelled`` producer-deadlock class)
``notify-outside-lock`` ``notify``/``notify_all`` without holding
                       the condition's owning mutex (undefined
                       behavior per the threading docs; the waiter
                       can miss the wakeup)
``blocking-under-lock`` socket send/recv, subprocess waits,
                       ``time.sleep``, ``block_until_ready``, event/
                       process waits, semaphore acquires... while a
                       lock is held — the convoy/deadlock fuel every
                       serve-era review round caught by eye
``callback-under-lock`` a user callback (``on_*``, sink ``write``,
                       ``action``/``callback``) invoked while
                       holding a lock — re-entrancy (the PR-9
                       ``MetricsLogger`` sink shape) and arbitrary
                       lock-order edges injected by user code
``unlocked-shared-write`` an attribute written from ≥ 2 thread roots
                       with no common lock across its write sites
``thread-unnamed``     a ``threading.Thread`` spawn without a
                       descriptive ``name=`` (lockdep reports, trace
                       waterfalls and stuck-session dumps would say
                       ``Thread-7``)
``lockdep-name``       a lockdep factory call whose literal name
                       disagrees with the AST-derived canonical name
                       (the runtime shadow and this pass would stop
                       cross-checking the same graph)
``allowlist``          a ``# lock-ok:`` entry with no justification,
                       an unknown check id, or one that suppresses
                       nothing (stale)
``runtime-coverage``   (cross-check only) a lockdep runtime edge
                       absent from the static graph — a static
                       coverage hole — or a violation recorded at
                       runtime
=====================  ==============================================

**Allowlisting**: a finding that is deliberate is suppressed by a
trailing (or preceding-line) comment at its anchor line::

    self._sock.sendall(data)  # lock-ok: <check-id> <why it is safe>

The linter *verifies* the annotation: the check id must be real, the
justification non-empty, and the entry must actually suppress a
finding — zero unexplained findings, zero stale explanations.
"""
from __future__ import annotations

import collections
from typing import List, Optional

from .findings import ERROR, WARNING, Finding
from .lockgraph import (MAIN_ROOT, ConcurrencyModel, find_cycles,
                        scan_package, to_dot)

__all__ = ["THREAD_CHECK_IDS", "analyze_concurrency",
           "lock_order_dot", "crosscheck_runtime", "scan_package"]

THREAD_CHECK_IDS = (
    "lock-order-cycle", "cond-wait-no-while", "notify-outside-lock",
    "blocking-under-lock", "callback-under-lock",
    "unlocked-shared-write", "thread-unnamed", "lockdep-name",
    "allowlist", "runtime-coverage",
)

_PROGRAM = "threads"


def _where(module: str, lineno: int, func: str = "") -> str:
    mod_path = module.replace(".", "/") + ".py"
    fn = f" ({func})" if func else ""
    return f"{mod_path}:{lineno}{fn}"


class _Allowlist:
    def __init__(self, model: ConcurrencyModel):
        self.entries = model.allows
        self._index = {}
        for e in self.entries:
            self._index[(e.module, e.lineno, e.check)] = e
            # an annotation on the line ABOVE the anchor also counts
            self._index.setdefault(
                (e.module, e.lineno + 1, e.check), e)

    def suppress(self, check: str, module: str, lineno: int) -> bool:
        e = self._index.get((module, lineno, check))
        if e is not None and e.reason:
            e.used = True
            return True
        return False

    def verify(self) -> List[Finding]:
        out = []
        for e in self.entries:
            if e.check not in THREAD_CHECK_IDS:
                out.append(Finding(
                    "allowlist", ERROR,
                    f"lock-ok annotation names unknown check "
                    f"{e.check!r}", program=_PROGRAM,
                    where=_where(e.module, e.lineno)))
            elif not e.reason:
                out.append(Finding(
                    "allowlist", ERROR,
                    f"lock-ok annotation for {e.check!r} has no "
                    "justification — every allowlisted finding "
                    "must say WHY it is safe",
                    program=_PROGRAM,
                    where=_where(e.module, e.lineno)))
            elif not e.used:
                out.append(Finding(
                    "allowlist", WARNING,
                    f"stale lock-ok annotation: no {e.check!r} "
                    "finding at this line anymore — delete it or "
                    "move it to the real anchor",
                    program=_PROGRAM,
                    where=_where(e.module, e.lineno)))
        return out


def _check_cycles(model, allow) -> List[Finding]:
    out = []
    for cycle in find_cycles(model):
        steps = list(zip(cycle, cycle[1:]))
        sites = [e for e in model.edges
                 if not e.declared and (e.src, e.dst) in steps]
        anchor = sites[0] if sites else None
        mod = anchor.module if anchor else cycle[0].rsplit(
            ".", 2)[0]
        lineno = anchor.lineno if anchor else 0
        if allow.suppress("lock-order-cycle", mod, lineno):
            continue
        out.append(Finding(
            "lock-order-cycle", ERROR,
            "lock-acquisition-order cycle: "
            + " -> ".join(cycle)
            + " — two threads taking these locks in opposite "
              "orders deadlock",
            program=_PROGRAM,
            where=_where(mod, lineno,
                         anchor.func if anchor else ""),
            path="/".join(cycle)))
    return out


def _check_waits(model, allow) -> List[Finding]:
    out = []
    for w in model.waits:
        if w.in_while:
            continue
        if allow.suppress("cond-wait-no-while", w.module, w.lineno):
            continue
        out.append(Finding(
            "cond-wait-no-while", ERROR,
            f"Condition.wait() on {w.cond} is not guarded by a "
            "while-predicate loop — spurious wakeups and lost "
            "wakeups proceed on a false predicate",
            program=_PROGRAM,
            where=_where(w.module, w.lineno, w.func),
            path=w.cond))
    return out


def _check_notifies(model, allow) -> List[Finding]:
    """A notify site must hold the condition's owning mutex — either
    locally, or (for helper methods) in every intra-module call
    context that reaches it."""
    out = []
    for n in model.notifies:
        if n.owner in n.held:
            continue
        # one level up: every caller of this helper must hold it
        callers_hold = _callers_hold(model, n, n.owner)
        if callers_hold:
            continue
        if allow.suppress("notify-outside-lock", n.module, n.lineno):
            continue
        out.append(Finding(
            "notify-outside-lock", ERROR,
            f"{n.cond}.notify outside its owning lock "
            f"{n.owner} — waiters can miss the wakeup "
            "(undefined behavior per threading docs)",
            program=_PROGRAM,
            where=_where(n.module, n.lineno, n.func),
            path=n.cond))
    return out


def _callers_hold(model: ConcurrencyModel, notify, owner) -> bool:
    """True when every recorded intra-module call of the notify
    site's function holds ``owner`` at the call site (the
    ``_purge_cancelled`` pattern: a lock-holding consumer calls the
    helper).  No recorded caller = cannot prove = False."""
    sites = [c for c in model.calls
             if c[0] == notify.module and c[1] == notify.cls
             and c[2] == notify.func]
    return bool(sites) and all(owner in held
                               for (_m, _c, _f, held, _ln) in sites)


def _check_ops(model, allow) -> List[Finding]:
    out = []
    for op in model.ops:
        check = ("blocking-under-lock" if op.op == "blocking"
                 else "callback-under-lock")
        if allow.suppress(check, op.module, op.lineno):
            continue
        noun = ("blocking call" if op.op == "blocking"
                else "user callback")
        out.append(Finding(
            check, WARNING,
            f"{noun} {op.desc} while holding "
            f"{', '.join(op.held)} — "
            + ("every other thread needing the lock convoys "
               "behind (or deadlocks on) this operation"
               if op.op == "blocking" else
               "user code runs inside the critical section: "
               "re-entrancy deadlocks and arbitrary lock-order "
               "edges (the PR-9 sink-re-entrancy class)"),
            program=_PROGRAM,
            where=_where(op.module, op.lineno, op.func),
            path="+".join(op.held)))
    return out


def _check_shared_writes(model, allow) -> List[Finding]:
    out = []
    # Grouping: writes through non-self receivers (`handle.state`)
    # cannot be typed statically, so they merge with EVERY write of
    # the same attr in the module — the aliasing that catches
    # `close()` writing what `_worker_lost` guards.  When an attr
    # has ONLY self-writes, each class is its own shared variable:
    # two classes with a private, own-lock-guarded `.state` must not
    # be judged as one.
    by_attr = collections.defaultdict(list)
    for w in model.writes:
        if w.in_init or w.attr.startswith("__"):
            continue
        by_attr[(w.module, w.attr)].append(w)
    groups = {}
    for (module, attr), sites in by_attr.items():
        if any(w.owner_cls is None for w in sites):
            groups[(module, attr, None)] = sites
        else:
            for w in sites:
                groups.setdefault(
                    (module, attr, w.owner_cls), []).append(w)
    for (module, attr, _owner), sites in sorted(groups.items()):
        roots = set()
        for w in sites:
            roots |= model.func_roots.get(
                w.func_key, frozenset({MAIN_ROOT}))
        if len(roots) < 2:
            continue
        common = None
        for w in sites:
            held = set(w.held)
            common = held if common is None else (common & held)
        if common:
            continue
        anchor = next((w for w in sites if not w.held), sites[0])
        if allow.suppress("unlocked-shared-write", anchor.module,
                          anchor.lineno):
            continue
        where_all = ", ".join(
            f"{w.func}:{w.lineno}" for w in sites[:6])
        out.append(Finding(
            "unlocked-shared-write", WARNING,
            f"attribute .{attr} is written from "
            f"{len(roots)} thread roots "
            f"({', '.join(sorted(roots))}) with no common lock "
            f"across its write sites [{where_all}]",
            program=_PROGRAM,
            where=_where(anchor.module, anchor.lineno,
                         anchor.func),
            path=attr))
    return out


def _check_spawns(model, allow) -> List[Finding]:
    out = []
    for s in model.spawns:
        if s.kind != "thread" or s.has_name:
            continue
        if allow.suppress("thread-unnamed", s.module, s.lineno):
            continue
        out.append(Finding(
            "thread-unnamed", WARNING,
            "threading.Thread spawned without name= — lockdep "
            "reports, trace waterfalls and stuck-session dumps "
            "will say Thread-7 instead of what it does"
            + (f" (target {s.target})" if s.target else ""),
            program=_PROGRAM,
            where=_where(s.module, s.lineno, s.func)))
    return out


def _check_names(model, allow) -> List[Finding]:
    out = []
    for name, ld in sorted(model.locks.items()):
        if ld.declared_name is None or ld.declared_name == name:
            continue
        if allow.suppress("lockdep-name", ld.module, ld.lineno):
            continue
        out.append(Finding(
            "lockdep-name", ERROR,
            f"lockdep factory name {ld.declared_name!r} disagrees "
            f"with the AST-derived canonical name {name!r} — the "
            "runtime shadow and the static graph would stop "
            "cross-checking the same lock",
            program=_PROGRAM,
            where=_where(ld.module, ld.lineno)))
    return out


_CHECK_FNS = {
    "lock-order-cycle": _check_cycles,
    "cond-wait-no-while": _check_waits,
    "notify-outside-lock": _check_notifies,
    "blocking-under-lock": _check_ops,
    "callback-under-lock": _check_ops,
    "unlocked-shared-write": _check_shared_writes,
    "thread-unnamed": _check_spawns,
    "lockdep-name": _check_names,
}


def analyze_concurrency(root: Optional[str] = None,
                        checks=None,
                        model: Optional[ConcurrencyModel] = None
                        ) -> List[Finding]:
    """Run the concurrency checks over the package (or any source
    tree rooted at ``root``) and return the surviving findings —
    allowlisted sites are suppressed, and the allowlist itself is
    verified (unknown check, empty justification, stale entry)."""
    if model is None:
        model = scan_package(root)
    allow = _Allowlist(model)
    selected = list(checks) if checks is not None \
        else [c for c in THREAD_CHECK_IDS
              if c not in ("allowlist", "runtime-coverage")]
    findings: List[Finding] = []
    ran = set()
    for check in selected:
        fn = _CHECK_FNS.get(check)
        if fn is None or fn in ran:
            continue
        ran.add(fn)
        for f in fn(model, allow):
            if f.check in selected or f.check == check:
                findings.append(f)
    if checks is None or "allowlist" in checks:
        findings.extend(allow.verify())
    return findings


def lock_order_dot(root: Optional[str] = None,
                   model: Optional[ConcurrencyModel] = None) -> str:
    """The lock-order graph in Graphviz DOT (the CI artifact)."""
    if model is None:
        model = scan_package(root)
    return to_dot(model)


def crosscheck_runtime(runtime, root: Optional[str] = None,
                       model: Optional[ConcurrencyModel] = None
                       ) -> List[Finding]:
    """The static side of the both-ways lockdep cross-check.

    ``runtime`` is a path (one lockdep dump file, or a directory of
    ``lockdep-*.json`` dumps from a fleet run).  Every runtime
    acquisition edge must appear in the static graph — derived or
    declared — or it is a **static coverage hole** (the analyzer
    missed an ordering real execution produced); every violation the
    runtime shadow recorded (order cycle, self-deadlock, long hold)
    is surfaced as a finding naming both stacks.
    """
    from .. import _lockdep as lockdep

    if model is None:
        model = scan_package(root)
    edges, violations, loaded = lockdep.load_edge_dumps(runtime)
    findings = []
    if not loaded:
        # A gate that silently passes when the evidence is missing
        # is no gate: a crashed (or mis-pathed) MGT_LOCKDEP run must
        # fail the cross-check, not launder it.
        return [Finding(
            "runtime-coverage", ERROR,
            f"no lockdep dumps found at {runtime!r} — the runtime "
            "side of the cross-check produced no evidence (did the "
            "MGT_LOCKDEP=1 run crash, or does MGT_LOCKDEP_DUMP "
            "point somewhere else?)", program=_PROGRAM)]
    for hole in lockdep.crosscheck(model.edge_pairs(),
                                   model.wildcard_sources(),
                                   runtime_edges=edges):
        src, dst = hole["edge"]
        findings.append(Finding(
            "runtime-coverage", ERROR,
            f"runtime acquisition edge {src} -> {dst} is absent "
            "from the static lock graph — a static coverage hole; "
            "add the ordering (or a may_precede declaration at the "
            "lock's factory) so the analyzer sees what execution "
            "does",
            program=_PROGRAM, path=f"{src}->{dst}"))
    for v in violations:
        detail = {k: v[k] for k in ("lock", "edge", "cycle",
                                    "held_s", "thread")
                  if k in v}
        msg = (f"lockdep runtime violation {v.get('kind')}: "
               f"{detail}")
        stacks = [v[k] for k in ("stack", "other_stack")
                  if v.get(k)]
        if stacks:
            msg += "\n" + "\n--- other stack ---\n".join(
                s.rstrip() for s in stacks)
        findings.append(Finding(
            "runtime-coverage", ERROR, msg, program=_PROGRAM,
            path=str(v.get("kind"))))
    return findings
