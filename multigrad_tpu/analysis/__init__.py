"""Static shard-safety analysis of SPMD programs.

The paper's central claim — loss-and-grad communication of
O(|sumstats| + |params|) bytes, independent of catalog size — and the
replication invariants the pre-vma ``check_rep=False`` compat path
stops JAX from checking are *runtime-measured* by
:mod:`multigrad_tpu.telemetry` but were never *proved*.  This package
proves them statically: models' SPMD programs are traced abstractly
(``jax.make_jaxpr`` over ``ShapeDtypeStruct``\\ s — zero FLOPs, no
accelerator needed) and a registry of checks walks the jaxprs:

=================  ====================================================
``comm-scaling``   every collective's payload is identical when the
                   catalog axes grow — the static proof of the
                   O(|y|+|params|) bound, naming the offending
                   collective on failure
``k-scaling``      batched (K, ndim) programs' collective payloads
                   grow at most linearly when K grows — the
                   sharded-K ensemble bound (no hidden cross-member
                   coupling)
``replication``    every shard_map output declared replicated is
                   dominated by a psum/all_gather (the SPMD analog of
                   a race detector; replaces the replication checking
                   ``check_rep=False`` disables on pre-vma jax)
``callback-in-scan``  host callbacks inside scan bodies that are not
                   ``lax.cond``-gated (the telemetry-tap shape)
``dtype-promotion``  inexact values wider than the working precision
                   (weak-type f64 leaks)
``captured-const``  large arrays baked into jitted programs instead of
                   passed as arguments
=================  ====================================================

The jaxpr checks prove what the *programs* do; the concurrency layer
(:mod:`.concurrency` + :mod:`.lockgraph`, the ``threads`` lint
target) proves what the *threads around them* do: lock-order cycles,
unguarded condition waits, blocking calls and user callbacks under
locks, cross-thread writes with no common lock — cross-checked at
runtime by the :mod:`multigrad_tpu.utils.lockdep` shadow.

Two further static passes ride the same lint machinery:
:mod:`.settlement` (the ``settlement`` target) proves every future
the serving stack mints is discharged on every path — settled with
the right ordering (trace roots and counters before the resolve,
never under the owning lock, first-wins terminal setters) and backed
by a broad-exception backstop on every settling thread root; and
:mod:`.wireschema` (the ``wire`` target) extracts the fleet wire
protocol from the codec/message/reader ASTs, proves writer/reader
key symmetry and known-keys-only decoding, and gates schema drift
against the committed ``analysis/protocol.json`` manifest.

Entry points: :func:`analyze` / :func:`assert_clean` (tests),
``OnePointModel.check_shard_safety`` (one call per model),
:func:`analyze_concurrency` (threads), :func:`analyze_settlement`,
:func:`analyze_wire` / :func:`extract_schema`, and the CI gate
``python -m multigrad_tpu.analysis.lint``.
"""
from .findings import ERROR, WARNING, Finding, format_findings  # noqa
from .checks import (CHECK_IDS, DEFAULT_CONST_THRESHOLD,  # noqa
                     PROGRAM_CHECKS, check_callbacks_in_scan,
                     check_captured_consts, check_comm_invariance,
                     check_dtype_promotion, check_k_scaling,
                     check_replication)
from .jaxprs import (CollectiveSite, collect_collectives,  # noqa
                     trace_program, walk_eqns)
from .analyzer import (analyze, analyze_fit, analyze_group,  # noqa
                       analyze_model, analyze_program,
                       analyze_streaming, assert_clean)
from .concurrency import (THREAD_CHECK_IDS,  # noqa
                          analyze_concurrency, crosscheck_runtime,
                          lock_order_dot)
from .lockgraph import ConcurrencyModel, scan_package, to_dot  # noqa
from .settlement import (SETTLE_CHECK_IDS,  # noqa
                         analyze_settlement, scan_settlement)
from .wireschema import (PROTOCOL_VERSION, WIRE_CHECK_IDS,  # noqa
                         analyze_wire, diff_schema, dump_schema,
                         extract_schema, protocol_markdown)

__all__ = [
    "Finding", "ERROR", "WARNING", "format_findings",
    "analyze", "analyze_model", "analyze_streaming", "analyze_group",
    "analyze_fit", "analyze_program", "assert_clean",
    "check_comm_invariance", "check_k_scaling", "check_replication",
    "check_callbacks_in_scan", "check_dtype_promotion",
    "check_captured_consts", "CHECK_IDS", "PROGRAM_CHECKS",
    "DEFAULT_CONST_THRESHOLD",
    "CollectiveSite", "collect_collectives", "trace_program",
    "walk_eqns",
    "analyze_concurrency", "crosscheck_runtime", "lock_order_dot",
    "THREAD_CHECK_IDS", "ConcurrencyModel", "scan_package", "to_dot",
    "analyze_settlement", "scan_settlement", "SETTLE_CHECK_IDS",
    "analyze_wire", "extract_schema", "dump_schema", "diff_schema",
    "protocol_markdown", "WIRE_CHECK_IDS", "PROTOCOL_VERSION",
]
