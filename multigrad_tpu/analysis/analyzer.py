"""Orchestration: trace a model's SPMD programs and run the checks.

Entry points, from lowest to highest level:

* :func:`analyze_program` — one callable, one abstract trace, the four
  program-level checks.  Works on ANY jax-traceable function (a raw
  ``shard_map``, a custom in-graph algorithm built with
  ``OnePointModel.wrap_spmd``, ...).
* :func:`analyze_model` — an :class:`~multigrad_tpu.core.model
  .OnePointModel`: builds fresh programs for the requested kinds,
  runs the program-level checks, and — the headline — re-traces each
  program with the comm-sharded aux axes scaled up to *prove* the
  O(|sumstats|+|params|) communication bound statically
  (:func:`~multigrad_tpu.analysis.checks.check_comm_invariance`).
* :func:`analyze_streaming` — a :class:`~multigrad_tpu.data.streaming
  .StreamingOnePointModel`: same treatment for the chunked programs
  (here the catalog axis is the *chunk row count*, so scaling needs no
  second data set at all).
* :func:`analyze_group` — an :class:`~multigrad_tpu.core.group
  .OnePointGroup`: the fused joint program when the group fuses, the
  member programs otherwise (MPMD).
* :func:`analyze_fit` — the whole-fit Adam scan program (optimizer
  update included), where the callback-in-scan check has a real loop
  to scrutinize.
* :func:`analyze` — type dispatch over all of the above.
* :func:`assert_clean` — the pytest-facing wrapper: raises
  ``AssertionError`` with the formatted findings report.

Everything here is zero-FLOP: programs are traced with
``jax.make_jaxpr`` over ``ShapeDtypeStruct``\\ s, so analysis runs on
a login node with no accelerator attached.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from .checks import (DEFAULT_CONST_THRESHOLD, PROGRAM_CHECKS,
                     check_comm_invariance, check_k_scaling)
from .findings import Finding, format_findings
from .jaxprs import abstractify, trace_program

__all__ = ["analyze", "analyze_program", "analyze_model",
           "analyze_streaming", "analyze_group", "analyze_fit",
           "assert_clean", "DEFAULT_KINDS"]

# The programs analyzed by default: the paper's headline fused program
# plus the Jacobian path the inference subsystem builds on.
DEFAULT_KINDS = ("loss_and_grad", "sumstats_jac_rev")


def _run_program_checks(closed, program: str, checks, expected_dtype,
                        const_threshold) -> List[Finding]:
    extra = {
        "dtype-promotion": {"expected_dtype": expected_dtype},
        "captured-const": {"threshold_bytes": const_threshold},
    }
    findings: List[Finding] = []
    for check_id, fn in PROGRAM_CHECKS.items():
        if checks is not None and check_id not in checks:
            continue
        findings.extend(fn(closed, program, **extra.get(check_id, {})))
    return findings


def analyze_program(fn, *args, program: str = "program",
                    checks: Optional[Sequence[str]] = None,
                    expected_dtype=None,
                    const_threshold: int = DEFAULT_CONST_THRESHOLD
                    ) -> List[Finding]:
    """Trace ``fn(*args)`` abstractly and run the program-level checks.

    ``args`` may be concrete arrays or ``ShapeDtypeStruct``\\ s; they
    are abstracted leaf-by-leaf, so no data is materialized and
    nothing executes.  ``checks`` restricts to a subset of check ids
    (default: all program-level checks).
    """
    args = jax.tree_util.tree_map(abstractify, args)
    closed = trace_program(fn, *args)
    return _run_program_checks(closed, program, checks,
                               expected_dtype, const_threshold)


# --------------------------------------------------------------------- #
# Catalog-axis scaling (the comm-scaling re-trace)
# --------------------------------------------------------------------- #
def _comm_axes(leaf, comm) -> set:
    """Mesh-axis names of `comm` that shard this aux leaf."""
    sh = getattr(leaf, "sharding", None)
    if not isinstance(sh, NamedSharding):
        return set()
    named = set()
    for entry in jax.tree_util.tree_leaves(tuple(sh.spec)):
        named.add(entry)
    return named & set(comm.axes)


def _abstract_aux(leaves) -> list:
    return [abstractify(leaf) for leaf in leaves]


def _scaled_aux(leaves, comm, scale: int) -> tuple:
    """Aux structs with every comm-sharded dimension scaled.

    The model core's sharding contract (``core/model.py`` module doc)
    makes "the catalog axes" a *derivable* property: exactly the aux
    dimensions sharded over the model's comm.  Scaling those — and
    only those — grows the catalog without touching targets, bin
    edges, or any other replicated leaf.  Returns ``(structs,
    n_scaled)``; ``n_scaled == 0`` means nothing is comm-sharded and
    the comm-scaling check has no axis to vary.
    """
    out, n_scaled = [], 0
    for leaf in leaves:
        shape = tuple(np.shape(leaf)) if hasattr(leaf, "shape") else None
        if shape is None or not _comm_axes(leaf, comm):
            out.append(abstractify(leaf))
            continue
        spec = tuple(leaf.sharding.spec)
        spec = spec + (None,) * (len(shape) - len(spec))
        new_shape = tuple(
            d * scale if spec[i] is not None else d
            for i, d in enumerate(shape))
        n_scaled += 1
        out.append(jax.ShapeDtypeStruct(new_shape, leaf.dtype))
    return out, n_scaled


def _key_struct(randkey):
    if randkey is None:
        return jax.ShapeDtypeStruct((), jnp.result_type(float))
    from ..optim.adam import init_randkey
    return init_randkey(randkey)


def _params_struct(params):
    params = jnp.asarray(params, dtype=jnp.result_type(float)) \
        if not hasattr(params, "dtype") else params
    return abstractify(params)


def analyze_model(model, params, kinds: Sequence[str] = DEFAULT_KINDS,
                  randkey=None, checks: Optional[Sequence[str]] = None,
                  scale: int = 2, expected_dtype=None,
                  const_threshold: int = DEFAULT_CONST_THRESHOLD,
                  k_scale: Optional[int] = None) -> List[Finding]:
    """Statically verify an ``OnePointModel``'s SPMD programs.

    For each program kind: run the program-level checks on an abstract
    trace, then — for distributed models with comm-sharded aux —
    re-trace with the catalog axes scaled ``scale``× and require every
    collective's per-execution payload unchanged (the static proof of
    the O(|sumstats|+|params|) bound, with the offending collective's
    source location on failure).

    Parameters
    ----------
    model : OnePointModel
    params : array-like | ShapeDtypeStruct
        A parameter vector (only its shape/dtype matter).
    kinds : sequence of str
        Program kinds (see ``OnePointModel._build_local_fn``).
    randkey : optional
        Trace the randkey-taking program variants.
    checks : sequence of str, optional
        Restrict to these check ids (default: all).
    scale : int
        Catalog-axis growth factor for the comm-scaling re-trace.
    k_scale : int, optional
        For batched ``(K, ndim)`` programs: ALSO re-trace with the K
        batch axis grown ``k_scale``× and require every collective
        payload to scale at most linearly
        (:func:`~multigrad_tpu.analysis.checks.check_k_scaling`) —
        the sharded-K ensemble bound: doubling K doubles the
        per-member-batched payload and leaves the per-member
        O(|y|+|params|) data-axis bound untouched.  Requires 2-D
        ``params``; on K-sharded program kinds both K and
        ``k_scale·K`` must divide the mesh's replica count.
    """
    label = type(model).__name__
    with_key = randkey is not None
    key = _key_struct(randkey)
    p_struct = _params_struct(params)
    leaves = model.aux_leaves()
    base_structs = _abstract_aux(leaves)

    findings: List[Finding] = []
    run_comm = checks is None or "comm-scaling" in checks
    run_k = k_scale is not None \
        and (checks is None or "k-scaling" in checks)
    if run_k and len(p_struct.shape) != 2:
        raise ValueError(
            f"k_scale needs a (K, ndim) params struct, got shape "
            f"{p_struct.shape}")
    scaled_structs, n_scaled = (None, 0)
    if run_comm and model.comm is not None:
        scaled_structs, n_scaled = _scaled_aux(leaves, model.comm,
                                               scale)

    for kind in kinds:
        program = model._build_program(kind, with_key)
        prog_label = f"{label}:{kind}"
        closed = trace_program(program, p_struct, base_structs, key)
        findings.extend(_run_program_checks(
            closed, prog_label, checks, expected_dtype,
            const_threshold))
        if n_scaled:
            closed_scaled = trace_program(program, p_struct,
                                          scaled_structs, key)
            findings.extend(check_comm_invariance(
                closed, closed_scaled, program=prog_label,
                scale=scale))
        if run_k:
            k_struct = jax.ShapeDtypeStruct(
                (p_struct.shape[0] * int(k_scale),
                 p_struct.shape[1]), p_struct.dtype)
            closed_k = trace_program(program, k_struct,
                                     base_structs, key)
            findings.extend(check_k_scaling(
                closed, closed_k, program=prog_label,
                scale=int(k_scale)))
    return findings


def analyze_streaming(sm, params, randkey=None,
                      checks: Optional[Sequence[str]] = None,
                      scale: int = 2, expected_dtype=None,
                      const_threshold: int = DEFAULT_CONST_THRESHOLD,
                      include_scan_path: bool = True) -> List[Finding]:
    """Statically verify a ``StreamingOnePointModel``'s chunk programs.

    The streamed algebra's catalog axis is the *chunk row count* — an
    argument shape, not stored data — so the comm-scaling proof here
    needs no second catalog: the same chunk programs are traced with
    ``rows_per_chunk`` and ``scale * rows_per_chunk`` rows and every
    collective payload must be identical (per-chunk traffic
    independent of chunk size ⇒ per-step traffic depends only on the
    chunk COUNT, the invariant ``measure_comm`` reports at runtime).

    Covers ``chunk_sumstats`` + ``chunk_vjp`` (the two-pass stream)
    and, with ``include_scan_path``, the single-dispatch
    ``chunk_scan`` program.
    """
    label = f"Streaming[{type(sm.model).__name__}]"
    with_key = randkey is not None
    key = _key_struct(randkey)
    p_struct = _params_struct(params)
    aux_structs = _abstract_aux(sm.model.aux_leaves())
    plan = sm.plan()
    run_comm = (checks is None or "comm-scaling" in checks) \
        and sm.comm is not None

    def chunk_structs(rows, lead=()):
        structs = []
        for name in sm._names:
            row = sm.streams[name].read(0, 1)
            structs.append(jax.ShapeDtypeStruct(
                lead + (rows,) + row.shape[1:], row.dtype))
        return structs

    findings: List[Finding] = []
    rows = plan.rows_per_chunk
    # The scan path is verified under the SAME remat policy the model
    # executes with (the policy changes the traced jaxpr — a saveable
    # policy keeps residuals a full-remat trace recomputes), so the
    # comm-scaling proof covers the configured program, not a default.
    remat_policy = getattr(sm, "remat_policy", "dots")

    def run(kind, build_args, prog_label):
        program = sm.model._build_stream_program(
            kind, with_key, sm._names, remat_policy=remat_policy)
        closed = trace_program(program, *build_args(rows))
        findings.extend(_run_program_checks(
            closed, prog_label, checks, expected_dtype,
            const_threshold))
        if run_comm:
            closed_scaled = trace_program(program,
                                          *build_args(rows * scale))
            findings.extend(check_comm_invariance(
                closed, closed_scaled, program=prog_label,
                scale=scale))

    run("chunk_sumstats",
        lambda r: (p_struct, chunk_structs(r), aux_structs, key),
        f"{label}:chunk_sumstats")

    # chunk_vjp consumes the cotangent dL/dy, whose shape comes from
    # the sumstats program's output — eval_shape it, zero FLOPs.
    p1 = sm.model._build_stream_program("chunk_sumstats", with_key,
                                        sm._names)
    total = jax.eval_shape(p1, p_struct, chunk_structs(rows),
                           aux_structs, key)
    ct = total[0] if sm.model.sumstats_func_has_aux else total
    ct = jax.tree_util.tree_map(abstractify, ct)
    run("chunk_vjp",
        lambda r: (p_struct, chunk_structs(r), aux_structs, ct, key),
        f"{label}:chunk_vjp")

    if include_scan_path:
        # Two stacked chunks suffice: the scan body is identical per
        # chunk, so any size-dependence shows up already at n=2.
        n_chunks = 2
        run("chunk_scan",
            lambda r: (p_struct, chunk_structs(r, (n_chunks,)),
                       aux_structs, key),
            f"{label}:chunk_scan")
    return findings


def analyze_group(group, params, randkey=None,
                  checks: Optional[Sequence[str]] = None,
                  scale: int = 2, expected_dtype=None,
                  const_threshold: int = DEFAULT_CONST_THRESHOLD,
                  comm_allow_linear: Sequence[str] = ()
                  ) -> List[Finding]:
    """Statically verify an ``OnePointGroup``.

    Fused groups are checked as ONE joint program (exactly what
    executes); the comm-scaling re-trace scales every member's
    comm-sharded aux axes together.  Non-fused (MPMD) groups execute
    one program per member, so each member is analyzed independently.

    ``comm_allow_linear`` forwards to :func:`~multigrad_tpu.analysis
    .checks.check_comm_invariance`: collective ops held to an
    at-most-linear catalog bound instead of invariance — for groups
    with a declared ring-exchange member (the joint SMF+wprp
    likelihood's pair counter).
    """
    label = f"Group[{','.join(type(m).__name__ for m in group.models)}]"
    if not group.fused:
        findings: List[Finding] = []
        for m in group.models:
            findings.extend(analyze_model(
                m, params, kinds=("loss_and_grad",), randkey=randkey,
                checks=checks, scale=scale,
                expected_dtype=expected_dtype,
                const_threshold=const_threshold))
        return findings

    with_key = randkey is not None
    key = _key_struct(randkey)
    p_struct = _params_struct(params)
    program = group._get_fused_program(with_key)
    base = tuple(_abstract_aux(m.aux_leaves()) for m in group.models)
    closed = trace_program(program, p_struct, base, key)
    findings = _run_program_checks(
        closed, f"{label}:fused_loss_and_grad", checks, expected_dtype,
        const_threshold)

    run_comm = checks is None or "comm-scaling" in checks
    scaled, n_scaled = [], 0
    for m in group.models:
        if m.comm is None:
            scaled.append(_abstract_aux(m.aux_leaves()))
            continue
        s, n = _scaled_aux(m.aux_leaves(), m.comm, scale)
        scaled.append(s)
        n_scaled += n
    if run_comm and n_scaled:
        closed_scaled = trace_program(program, p_struct, tuple(scaled),
                                      key)
        findings.extend(check_comm_invariance(
            closed, closed_scaled,
            program=f"{label}:fused_loss_and_grad", scale=scale,
            allow_linear=comm_allow_linear))
    return findings


def analyze_fit(model, params, nsteps: int = 3,
                learning_rate: float = 0.01, randkey=None,
                const_randkey: bool = False, tap=None,
                checks: Optional[Sequence[str]] = None,
                expected_dtype=None,
                const_threshold: int = DEFAULT_CONST_THRESHOLD
                ) -> List[Finding]:
    """Statically verify a model's whole-fit Adam scan program.

    Traces the same segment program family ``run_adam`` executes
    (:func:`multigrad_tpu.optim.adam.adam_fit_program` — optimizer
    update, bounds bijection and optional telemetry tap included), so
    the callback-in-scan check sees the REAL training loop: an
    ungated host callback anywhere in the model's loss path lands
    inside this scan and is flagged; the shipped cond-gated taps pass.
    """
    import optax

    from ..optim.adam import adam_fit_program

    label = f"{type(model).__name__}:adam_scan[{nsteps}]"
    with_key = randkey is not None
    p = jnp.zeros(np.shape(params), jnp.result_type(float)) \
        if isinstance(params, jax.ShapeDtypeStruct) else \
        jnp.asarray(params, dtype=jnp.result_type(float))
    ndim = p.shape[-1]

    program = model._build_program("loss_and_grad", with_key)

    def wrapper(u, key, dynamic):
        return program(u, dynamic, key)

    fit = adam_fit_program(wrapper, nsteps,
                           learning_rate=learning_rate,
                           with_key=with_key,
                           const_randkey=const_randkey, tap=tap)
    opt_state = optax.adam(learning_rate).init(p)
    low = jnp.full((ndim,), -jnp.inf)
    high = jnp.full((ndim,), jnp.inf)
    key0 = _key_struct(randkey) if with_key else jax.random.key(0)
    aux_structs = _abstract_aux(model.aux_leaves())
    args = (abstractify(p), opt_state, key0, low, high,
            (aux_structs,))
    if tap is not None:
        args = args + (jnp.asarray(0, jnp.int32),)
    closed = trace_program(fit, *args)
    return _run_program_checks(closed, label, checks, expected_dtype,
                               const_threshold)


def analyze(obj, params, **kwargs) -> List[Finding]:
    """Type-dispatching front door over the ``analyze_*`` family.

    Accepts an ``OnePointModel`` (subclasses included), a
    ``StreamingOnePointModel``, or an ``OnePointGroup``; forwards
    ``kwargs`` to the matching analyzer.
    """
    from ..core.group import OnePointGroup
    from ..core.model import OnePointModel
    from ..data.streaming import StreamingOnePointModel

    if isinstance(obj, StreamingOnePointModel):
        return analyze_streaming(obj, params, **kwargs)
    if isinstance(obj, OnePointGroup):
        return analyze_group(obj, params, **kwargs)
    if isinstance(obj, OnePointModel):
        return analyze_model(obj, params, **kwargs)
    raise TypeError(
        "analyze() wants an OnePointModel, StreamingOnePointModel or "
        f"OnePointGroup, got {type(obj).__name__}")


def assert_clean(obj, params, **kwargs) -> None:
    """Assert that the shard-safety analyzer finds nothing.

    The test-suite hook: add one line per model family ::

        from multigrad_tpu.analysis import assert_clean
        assert_clean(model, params)

    and any regression that breaks the communication bound, drops a
    psum, leaks f64, captures a catalog, or plants a callback in the
    fit loop fails the suite with the full findings report.
    """
    findings = analyze(obj, params, **kwargs)
    if findings:
        raise AssertionError(
            "shard-safety analysis found problems:\n"
            + format_findings(findings))
