"""Replication dataflow: the SPMD analog of a race detector.

Inside a ``shard_map`` body every value either *varies* across the
devices of some mesh axes (it was computed from that device's shard)
or is *replicated* (identical everywhere).  An output declared
replicated (``out_specs=PartitionSpec()``) that actually varies is a
wrong-answer bug: each device returns a different number and JAX
silently hands the caller device 0's copy — exactly the class of bug
``check_rep=True`` used to catch before the pre-vma compat path
(:mod:`multigrad_tpu.parallel._shard_map_compat`) had to disable it,
and that vma-era jax re-detects with its varying-manual-axes types.

This module re-implements that verification *statically*, on any jax
version, by forward dataflow over the body jaxpr:

* a body input varies over the mesh axes its ``in_names`` shard it
  along (``{}`` — replicated — varies over nothing);
* ``psum``/``pmax``/``pmin`` REMOVE the reduced axes from the
  variance set (their output is identical on every participant);
  ``all_gather`` likewise (every device materializes the full axis);
* ``axis_index`` ADDS its axis (each device sees its own index);
* everything else propagates the union of its inputs' variance;
* control flow recurses: ``scan``/``while`` iterate their carry to a
  fixpoint, ``cond`` unions its branches plus the predicate (a
  device-varying predicate makes every branch output device-varying),
  and ``while`` unions its loop predicate into the whole carry (a
  device-varying trip count makes every carry diverge, replicated
  body math or not).

The check then compares each body output's inferred variance against
the axes its ``out_names`` declare: variance not accounted for by the
output sharding is a replication leak.

The analysis is *sound for the primitives it models* and conservative
elsewhere (unknown higher-order primitives propagate the input union
through their sub-jaxpr when the arity matches, else the plain
union), so a "clean" verdict can be trusted up to primitives that
launder variance through unmodeled semantics — none of which exist in
this package's programs.
"""
from __future__ import annotations

from typing import FrozenSet, List

from .jaxprs import subjaxprs

__all__ = ["body_output_variance", "shard_map_leaks"]

# Collectives whose OUTPUT is identical on every device of the reduced
# axes (full-axis reduction or full-axis materialization).
_REDUCING = frozenset({"psum", "pmax", "pmin", "all_gather"})

_EMPTY: FrozenSet[str] = frozenset()


def _axes_param(eqn) -> tuple:
    axes = eqn.params.get("axes", eqn.params.get("axis_name"))
    if axes is None:
        return ()
    return (axes,) if isinstance(axes, (str, int)) else tuple(axes)


def body_output_variance(jaxpr, in_variance) -> List[FrozenSet[str]]:
    """Variance sets of ``jaxpr``'s outputs given its inputs'.

    ``jaxpr`` is an OPEN jaxpr (e.g. a shard_map body); ``in_variance``
    is one frozenset of mesh-axis names per invar.  Constants are
    replicated by definition (they are baked into the program
    identically on every device).
    """
    env = {}

    def read(v):
        if hasattr(v, "val"):          # Literal
            return _EMPTY
        return env.get(v, _EMPTY)

    def write(v, s):
        env[v] = s

    for v, s in zip(jaxpr.invars, in_variance):
        write(v, frozenset(s))
    for v in jaxpr.constvars:
        write(v, _EMPTY)

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        ins = [read(v) for v in eqn.invars]
        union = frozenset().union(*ins) if ins else _EMPTY

        if name in _REDUCING and eqn.params.get(
                "axis_index_groups") is None:
            out = [union - set(_axes_param(eqn))] * len(eqn.outvars)
        elif name == "axis_index":
            out = [union | set(_axes_param(eqn))] * len(eqn.outvars)
        elif name == "scan":
            body = eqn.params["jaxpr"].jaxpr
            nc = eqn.params["num_consts"]
            ncar = eqn.params["num_carry"]
            carry = ins[nc:nc + ncar]
            # Fixpoint over the carry: a value that varies in step i
            # varies in every later step.  Monotone over finite sets,
            # so len(carry)+1 sweeps suffice.
            for _ in range(len(carry) + 1):
                outs = body_output_variance(
                    body, ins[:nc] + carry + ins[nc + ncar:])
                new_carry = [c | o for c, o in zip(carry, outs[:ncar])]
                if new_carry == carry:
                    break
                carry = new_carry
            out = body_output_variance(
                body, ins[:nc] + carry + ins[nc + ncar:])
        elif name == "while":
            body = eqn.params["body_jaxpr"].jaxpr
            cond = eqn.params["cond_jaxpr"].jaxpr
            cn = eqn.params["cond_nconsts"]
            bn = eqn.params["body_nconsts"]
            carry = ins[cn + bn:]
            for _ in range(len(carry) + 1):
                # A device-varying PREDICATE varies the trip count:
                # devices exit on different iterations, so every
                # carry diverges even if the body math is replicated.
                # Union the predicate's variance into the whole carry
                # (the cond consts ins[:cn] feed only the predicate).
                pred = body_output_variance(
                    cond, ins[:cn] + carry)[0]
                outs = body_output_variance(body,
                                            ins[cn:cn + bn] + carry)
                new_carry = [c | o | pred
                             for c, o in zip(carry, outs)]
                if new_carry == carry:
                    break
                carry = new_carry
            out = carry
        elif name == "cond":
            pred, rest = ins[0], ins[1:]
            branch_outs = [
                body_output_variance(br.jaxpr, rest)
                for br in eqn.params["branches"]]
            out = [frozenset().union(pred, *[b[i] for b in branch_outs])
                   for i in range(len(eqn.outvars))]
        else:
            subs = subjaxprs(eqn)
            out = None
            if len(subs) == 1:
                inner = subs[0][0]
                body = inner.jaxpr if hasattr(inner, "jaxpr") else inner
                if len(body.invars) == len(ins):
                    # Generic call-like primitive (pjit, remat,
                    # custom_jvp/vjp, ...): run the analysis through
                    # its body so an inner psum is credited.
                    outs = body_output_variance(body, ins)
                    if len(outs) == len(eqn.outvars):
                        out = outs
            if out is None:
                out = [union] * len(eqn.outvars)

        for v, s in zip(eqn.outvars, out):
            write(v, s)

    return [read(v) for v in jaxpr.outvars]


def _spec_names(params, names_key, specs_key):
    """shard_map arg shardings as axis-name collections per position.

    jax <= 0.5 stores ``in_names``/``out_names`` (dicts of
    ``{array_dim: (axis, ...)}``); newer jax stores
    ``in_specs``/``out_specs`` (PartitionSpecs).  Normalize both to a
    sequence of iterables-of-axis-names.
    """
    if names_key in params:
        return [tuple(ax for axes in names.values() for ax in axes)
                for names in params[names_key]]
    out = []
    for spec in params[specs_key]:
        axes = []
        for entry in tuple(spec):
            if entry is None:
                continue
            axes.extend((entry,) if isinstance(entry, str)
                        else tuple(entry))
        out.append(tuple(axes))
    return out


def shard_map_leaks(eqn) -> List[tuple]:
    """Replication leaks of ONE shard_map equation.

    Returns ``(out_index, leaked_axes)`` tuples: the positions whose
    declared out-sharding does not account for the inferred variance —
    outputs the caller will consume as replicated (or as sharded over
    fewer axes than they actually vary over) while each device holds a
    different value.
    """
    body = eqn.params["jaxpr"]
    body = body.jaxpr if hasattr(body, "jaxpr") else body
    in_names = _spec_names(eqn.params, "in_names", "in_specs")
    out_names = _spec_names(eqn.params, "out_names", "out_specs")
    in_var = [frozenset(axes) for axes in in_names]
    outs = body_output_variance(body, in_var)
    leaks = []
    for i, (axes, var) in enumerate(zip(out_names, outs)):
        leaked = var - frozenset(axes)
        if leaked:
            leaks.append((i, tuple(sorted(leaked))))
    return leaks
