"""Shard-safety lint CLI: static SPMD verification as a CI gate.

Runs the full analyzer (:mod:`multigrad_tpu.analysis`) over the
shipped model families and exits nonzero on findings — the
communication bound, replication invariants, dtype hygiene, callback
gating and constant capture are all verified per push with ZERO device
execution (every program is traced abstractly).

Usage::

    # 8 virtual CPU devices so the distributed paths are exercised
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        JAX_PLATFORMS=cpu python -m multigrad_tpu.analysis.lint

    python -m multigrad_tpu.analysis.lint --targets smf,streaming
    python -m multigrad_tpu.analysis.lint --json   # machine-readable

    # the AST passes (no models, no devices needed)
    python -m multigrad_tpu.analysis.lint --targets threads
    python -m multigrad_tpu.analysis.lint --targets settlement,wire
    python -m multigrad_tpu.analysis.lint --targets wire \\
        --emit-protocol multigrad_tpu/analysis/protocol.json

stdlib-argparse only; exit status 0 = clean, 1 = findings, 2 = usage.
The device count comes from the environment (set ``XLA_FLAGS`` BEFORE
launching: ``python -m`` imports the package — and therefore jax —
before this module's code runs, so it cannot force the flag itself);
with a single device the analysis still runs, on 1-shard meshes.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List

import jax.numpy as jnp
import numpy as np

from .analyzer import analyze
from .checks import CHECK_IDS, DEFAULT_CONST_THRESHOLD
from .findings import ERROR


def _build_targets(names, num_halos: int):
    """Instantiate the shipped model families to verify.

    Yields ``(name, obj, params[, analyze_kwargs])`` tuples;
    construction is lazy so ``--targets`` skips the cost of families
    not asked for.
    """
    from ..core.group import OnePointGroup
    from ..data.streaming import StreamingOnePointModel
    from ..models.galhalo_hist import (GalhaloHistModel, TRUTH,
                                       make_galhalo_hist_data)
    from ..models.smf import SMFChi2Model, SMFModel, make_smf_data
    from ..parallel.mesh import global_comm, split_subcomms

    comm = global_comm()
    params2 = jnp.zeros(2)

    if "smf" in names:
        yield "smf", SMFModel(
            aux_data=make_smf_data(num_halos, comm=comm), comm=comm), \
            params2
    if "smf_chi2" in names:
        yield "smf_chi2", SMFChi2Model(
            aux_data=make_smf_data(num_halos, comm=comm), comm=comm), \
            params2
    if "smf_fused" in names:
        # The fused scatter-into-bins hot path (bin_mode="fused"):
        # searchsorted + gather + segment_sum must satisfy the same
        # comm bound as the dense kernel (all are shard-local ops).
        from ..ops.binned import fused_bin_window
        window = fused_bin_window(np.linspace(9, 10, 11), 0.6)
        yield "smf_fused", SMFModel(
            aux_data=make_smf_data(num_halos, comm=comm,
                                   bin_mode="fused",
                                   bin_window=window),
            comm=comm), params2
    if "galhalo_hist" in names:
        yield "galhalo_hist", GalhaloHistModel(
            aux_data=make_galhalo_hist_data(num_halos, comm=comm),
            comm=comm), jnp.asarray(TRUTH, jnp.result_type(float))
    if "galhalo_hist_fused" in names:
        from ..ops.binned import fused_bin_window
        edges = np.linspace(7.0, 11.75, 41)
        yield "galhalo_hist_fused", GalhaloHistModel(
            aux_data=make_galhalo_hist_data(
                num_halos, comm=comm, bin_edges=edges,
                bin_mode="fused",
                bin_window=fused_bin_window(edges, 0.3)),
            comm=comm), jnp.asarray(TRUTH, jnp.result_type(float))
    if "ensemble_sharded" in names:
        # The sharded-K ensemble path: a (K, ndim) batch partitioned
        # over the replica axis of a 2-level (replica, data) mesh.
        # Two static proofs: catalog comm-scaling (the per-member
        # O(|y|+|params|) data-axis bound is untouched by catalog
        # growth) and k-scaling (doubling K scales every collective
        # payload at most linearly — no hidden cross-member
        # coupling).  Needs >= 2 devices to split a replica axis off.
        from ..parallel.mesh import ensemble_comm
        if comm.size < 2:
            print("lint: skipping ensemble_sharded (needs >= 2 "
                  "devices; set "
                  "--xla_force_host_platform_device_count)",
                  file=sys.stderr)
        else:
            ecomm = ensemble_comm(2)
            yield ("ensemble_sharded", SMFModel(
                aux_data=make_smf_data(num_halos, comm=ecomm),
                comm=ecomm),
                jnp.zeros((8, 2)),
                dict(kinds=("batched_loss_and_grad_sharded",),
                     k_scale=2))
    if "serve_bucket" in names:
        # The fit-fleet scheduler's bucketed dispatch: K tenants'
        # fits through ONE (K, ndim) batched program.  The comm-
        # scaling re-trace proves the per-request bound statically —
        # catalog growth must leave every collective payload of the
        # batched program unchanged (the batched psums carry
        # (K, |y|) / (K, |params|), a function of bucket size and
        # sumstats width only, never of catalog rows).
        yield ("serve_bucket", SMFModel(
            aux_data=make_smf_data(num_halos, comm=comm), comm=comm),
            jnp.zeros((16, 2)),
            dict(kinds=("batched_loss_and_grad",)))
    if "streaming" in names:
        aux = make_smf_data(num_halos, comm=None)
        log_mh = np.asarray(aux.pop("log_halo_masses"))
        template = SMFModel(aux_data=aux, comm=comm)
        yield "streaming", StreamingOnePointModel(
            model=template, streams={"log_halo_masses": log_mh},
            chunk_rows=max(comm.size, num_halos // 4)), params2
    if "group" in names:
        # Fused path: two members on ONE mesh -> one joint program.
        yield "group", OnePointGroup(models=(
            SMFModel(aux_data=make_smf_data(num_halos, comm=comm),
                     comm=comm),
            SMFChi2Model(aux_data=make_smf_data(num_halos, comm=comm),
                         comm=comm))), params2
    if "group_mpmd" in names:
        # MPMD path: members on DISJOINT sub-meshes -> per-member
        # programs.  Needs >= 2 devices to split.
        if comm.size < 2:
            print("lint: skipping group_mpmd (needs >= 2 devices; "
                  "set --xla_force_host_platform_device_count)",
                  file=sys.stderr)
        else:
            subcomms, _, _ = split_subcomms(num_groups=2, comm=comm)
            yield "group_mpmd", OnePointGroup(models=(
                SMFModel(aux_data=make_smf_data(num_halos,
                                                comm=subcomms[0]),
                         comm=subcomms[0]),
                SMFChi2Model(aux_data=make_smf_data(num_halos,
                                                    comm=subcomms[1]),
                             comm=subcomms[1]))), params2
    if "joint_smf_wprp" in names:
        # The north-star JOINT likelihood (the posterior-pipeline
        # payoff workload): SMF χ² + wprp fused on one mesh through
        # param views.  The comm-scaling re-trace proves the joint
        # bound statically — catalog growth must leave every
        # collective payload of the fused program unchanged, i.e. the
        # group costs O(|y_smf| + |y_wprp| + |params|) on the wire no
        # matter how many halos either member holds.
        from ..models.joint import make_joint_smf_wprp
        yield ("joint_smf_wprp",
               make_joint_smf_wprp(num_halos=min(num_halos, 512),
                                   comm=comm),
               jnp.zeros(3),
               # The wprp member's ring rotation is a DECLARED
               # neighbor exchange (O(rows-per-shard) by
               # construction); every reduction in the fused program
               # still meets the exact invariance bound.
               dict(comm_allow_linear=("ppermute",)))


#: The model families `_build_targets` instantiates (traced
#: abstractly on the mesh).
MODEL_TARGETS = ("smf", "smf_chi2", "smf_fused", "galhalo_hist",
                 "galhalo_hist_fused", "ensemble_sharded",
                 "serve_bucket", "streaming", "group", "group_mpmd",
                 "joint_smf_wprp")
#: All lint targets: the model families plus the static passes (AST
#: scans of the package itself, not models): the concurrency pass,
#: the settlement-obligation pass and the wire-schema pass.
ALL_TARGETS = MODEL_TARGETS + ("threads", "settlement", "wire")


def _run_threads_target(args, checks=None) -> list:
    """The concurrency static pass: not a model — an AST scan of the
    package itself (lock-order graph, condition-wait predicates,
    blocking/callbacks under locks, shared writes, thread naming,
    allowlist verification), plus the optional lockdep runtime
    cross-check and DOT export.  ``checks`` subsets the thread
    checks (the thread-side split of ``--checks``)."""
    from .concurrency import (analyze_concurrency, crosscheck_runtime,
                              lock_order_dot, scan_package)
    model = scan_package()
    findings = list(analyze_concurrency(model=model, checks=checks))
    if args.runtime_edges:
        findings.extend(crosscheck_runtime(args.runtime_edges,
                                           model=model))
    if args.dot:
        with open(args.dot, "w") as f:
            f.write(lock_order_dot(model=model))
        print(f"[threads] lock-order graph -> {args.dot}",
              file=sys.stderr)
    return findings


def _run_settlement_target(checks=None) -> list:
    """The settlement static pass: prove every future-shaped
    obligation in the serve layer is discharged on every path, with
    the ordering conventions (root-before-resolve, settle outside
    the lock, first-wins) machine-checked.  ``checks`` subsets
    ``SETTLE_CHECK_IDS``."""
    from .settlement import analyze_settlement
    return list(analyze_settlement(checks=checks))


def _run_wire_target(args, checks=None) -> list:
    """The wire-schema static pass: extract the codec/message schema
    from the serve ASTs, check writer/reader key symmetry and
    known-keys-only readers, and diff against the checked-in
    ``analysis/protocol.json`` manifest (the mixed-version-fleet
    drift gate).  ``--emit-protocol`` writes the extracted schema
    (``-`` for stdout) and skips the drift diff for that run."""
    from .wireschema import analyze_wire, dump_schema, extract_schema
    model = extract_schema()
    if args.emit_protocol:
        payload = dump_schema(model.schema)
        if args.emit_protocol == "-":
            sys.stdout.write(payload)
        else:
            with open(args.emit_protocol, "w", encoding="utf-8") as f:
                f.write(payload)
            print(f"[wire] protocol manifest -> {args.emit_protocol}",
                  file=sys.stderr)
        if checks is None:
            checks = [c for c in ("wire-key-asymmetry",
                                  "wire-reader-splat")]
        else:
            checks = [c for c in checks if c != "wire-manifest-drift"]
    return list(analyze_wire(model=model, checks=checks,
                             manifest_path=args.manifest))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m multigrad_tpu.analysis.lint",
        description="Static SPMD shard-safety verification of the "
                    "shipped models (zero device execution).")
    parser.add_argument(
        "--targets", default=",".join(ALL_TARGETS),
        help=f"comma list from {{{','.join(ALL_TARGETS)}}} "
             "(default: all)")
    parser.add_argument(
        "--checks", default=None,
        help=f"comma list from {{{','.join(CHECK_IDS)}}} "
             "(default: all)")
    parser.add_argument(
        "--num-halos", type=int, default=800,
        help="catalog size for the instantiated models (trace-time "
             "only; default 800)")
    parser.add_argument(
        "--scale", type=int, default=2,
        help="catalog growth factor for the comm-scaling re-trace "
             "(default 2)")
    parser.add_argument(
        "--const-threshold", type=int,
        default=DEFAULT_CONST_THRESHOLD,
        help="captured-constant size threshold in bytes "
             "(default 1 MiB)")
    parser.add_argument(
        "--randkey", type=int, default=None,
        help="also trace the randkey-taking program variants")
    parser.add_argument(
        "--dot", default=None, metavar="PATH",
        help="write the lock-order graph as Graphviz DOT (threads "
             "target; the CI artifact)")
    parser.add_argument(
        "--runtime-edges", default=None, metavar="PATH",
        help="lockdep dump file (or directory of lockdep-*.json "
             "dumps from a MGT_LOCKDEP=1 run) to cross-check "
             "against the static lock graph: a runtime edge absent "
             "from the graph — or any recorded runtime violation — "
             "is a finding (threads target)")
    parser.add_argument(
        "--manifest", default=None, metavar="PATH",
        help="wire-protocol manifest to diff against (wire target; "
             "default: the checked-in analysis/protocol.json)")
    parser.add_argument(
        "--emit-protocol", default=None, metavar="PATH",
        help="write the extracted wire schema as a protocol manifest "
             "('-' for stdout) and skip the drift diff for this run "
             "(wire target; the manifest-bump workflow)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings on stdout")
    args = parser.parse_args(argv)

    targets = [t.strip() for t in args.targets.split(",") if t.strip()]
    unknown = set(targets) - set(ALL_TARGETS)
    if unknown:
        parser.error(f"unknown targets {sorted(unknown)}")
    # --checks spans EVERY registry: jaxpr check ids apply to the
    # model targets, thread/settle/wire check ids to their static
    # passes.  A selection naming only one side runs nothing on the
    # others (the user scoped the run), and an id in no registry
    # errors.
    from .concurrency import THREAD_CHECK_IDS
    from .settlement import SETTLE_CHECK_IDS
    from .wireschema import WIRE_CHECK_IDS
    checks = thread_checks = settle_checks = wire_checks = None
    if args.checks is not None:
        selected = [c.strip() for c in args.checks.split(",")
                    if c.strip()]
        bad = set(selected) - set(CHECK_IDS) - set(THREAD_CHECK_IDS) \
            - set(SETTLE_CHECK_IDS) - set(WIRE_CHECK_IDS)
        if bad:
            parser.error(f"unknown checks {sorted(bad)}")
        checks = [c for c in selected if c in CHECK_IDS]
        thread_checks = [c for c in selected
                         if c in THREAD_CHECK_IDS]
        settle_checks = [c for c in selected
                         if c in SETTLE_CHECK_IDS]
        wire_checks = [c for c in selected if c in WIRE_CHECK_IDS]

    all_findings: List = []

    def _static_pass(name, selected_checks, run):
        findings = []
        if selected_checks is None or selected_checks:
            findings = run(selected_checks)
            all_findings.extend(findings)
            if not args.json:
                status = "clean" if not findings \
                    else f"{len(findings)} finding(s)"
                print(f"[{name}] {status}")
                for f in findings:
                    print(f"    {f}")
        return findings

    if "threads" in targets:
        targets = [t for t in targets if t != "threads"]
        _static_pass("threads", thread_checks,
                     lambda c: _run_threads_target(args, checks=c))
    if "settlement" in targets:
        targets = [t for t in targets if t != "settlement"]
        _static_pass("settlement", settle_checks,
                     lambda c: _run_settlement_target(checks=c))
    if "wire" in targets:
        targets = [t for t in targets if t != "wire"]
        _static_pass("wire", wire_checks,
                     lambda c: _run_wire_target(args, checks=c))
    if checks is not None and not checks:
        targets = []          # static-pass-checks-only run
    for name, obj, params, *extra in _build_targets(targets,
                                                    args.num_halos):
        findings = analyze(obj, params, checks=checks,
                           scale=args.scale, randkey=args.randkey,
                           const_threshold=args.const_threshold,
                           **(extra[0] if extra else {}))
        all_findings.extend(findings)
        if not args.json:
            status = "clean" if not findings \
                else f"{len(findings)} finding(s)"
            print(f"[{name}] {status}")
            for f in findings:
                print(f"    {f}")

    if args.json:
        print(json.dumps({
            "findings": [f.to_dict() for f in all_findings],
            "clean": not all_findings,
        }, indent=2))
    elif all_findings:
        # Findings were already printed per target; close with the
        # count line only.
        n_err = sum(1 for f in all_findings if f.severity == ERROR)
        print(f"-- {len(all_findings)} finding(s), {n_err} error(s)")
    else:
        print("clean: no findings")
    return 1 if all_findings else 0


if __name__ == "__main__":
    sys.exit(main())
