"""Settlement lint: prove every future settles, in the right order.

The serve layer's correctness rests on a handful of *settlement
obligations*: a :class:`~multigrad_tpu.serve.queue.FitFuture`,
:class:`~multigrad_tpu.serve.jobs.JobFuture` or
:class:`~multigrad_tpu.serve.fleet.FleetRequest` claim, once created,
MUST reach a discharge call (``_set_result`` / ``_set_exception`` /
``_stage_settled`` / shed / cancel / requeue) on *every* path out of
the owning scope — including exception edges and thread-body exits —
and the discharge must follow the conventions every review round from
PR 10 through PR 18 kept restoring by hand:

* **Backstops** — a thread whose body (or call graph) settles futures
  must wrap itself in a broad ``except`` backstop: a dispatcher,
  reader, monitor or stage worker dying silently strands every
  obligation it held (the PR-16 unrecorded-stage-death bug class).
* **Root-before-resolve** — trace roots and dispatch counters are
  recorded BEFORE the future resolves: a caller waking on
  ``result()`` must see a fully-accounted request (the PR-13 bug
  class, re-fixed three times).
* **Settle-outside-lock** — resolving a future runs caller callbacks
  and wakes waiters; doing so under the owning lock is a lock-order
  hazard and a latency cliff.
* **First-wins** — future classes guard ``_set_result`` /
  ``_set_exception`` so a late duplicate (a requeued request
  completing twice) cannot clobber the delivered result; and no code
  path settles the same future twice unconditionally.

Like :mod:`.lockgraph` / :mod:`.concurrency` (whose thread-root
propagation this pass reuses to follow obligations handed across
threads), everything here is a pure-``ast`` pass — the scanned code
is parsed, never imported.

Deliberate exceptions are allowlisted IN the code::

    fut._set_exception(err)   # settle-ok: <check-id> <why it is safe>

and the allowlist itself is verified: unknown check ids and empty
justifications are errors, stale entries are warnings.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .findings import ERROR, WARNING, Finding
from .lockgraph import MAIN_ROOT, ConcurrencyModel, scan_package

__all__ = ["SETTLE_CHECK_IDS", "SettlementModel", "scan_settlement",
           "analyze_settlement"]

#: Registry of settlement check ids (the ``--checks`` vocabulary of
#: the ``settlement`` lint target).
SETTLE_CHECK_IDS = (
    "settle-orphan",
    "settle-no-backstop",
    "settle-root-after-resolve",
    "settle-under-lock",
    "settle-double",
    "settle-first-wins",
    "settle-allowlist",
)

_PROGRAM = "settlement"

#: Discharge calls: resolving an obligation (``_stage_settled`` is
#: the per-stage incremental settle of a :class:`JobFuture`).
RESOLVE_ATTRS = frozenset({"_set_result", "_set_exception",
                           "_stage_settled"})
#: Terminal resolves only — the pair the first-wins / double-settle
#: invariants are about.
TERMINAL_ATTRS = frozenset({"_set_result", "_set_exception"})
#: Accounting that must land BEFORE a resolve (root-before-resolve):
#: trace roots, dispatch counters, latency/SLO observations.  NOT in
#: this set: ``telemetry.log`` summaries and gauge refreshes, which
#: legitimately trail the resolve (they are streams, not the state a
#: woken caller reads).
ACCOUNTING_ATTRS = frozenset({"_trace_root", "_count", "_count_locked",
                              "_fits_counter", "_count_job",
                              "_count_stage", "record_shed",
                              "_observe_latency", "observe"})

_ALLOW_RE = re.compile(r"#\s*settle-ok:\s*([a-z0-9-]+)\s*(.*)$")


# ---------------------------------------------------------------------- #
# model
# ---------------------------------------------------------------------- #
@dataclass
class ResolveSite:
    """One discharge call (``<base>.<attr>(...)``)."""

    module: str
    func: str                 # simple name, for messages
    fkey: str                 # lockgraph-style "module[.Class].name"
    lineno: int
    base: str                 # dotted receiver ("req.future", "fut")
    attr: str
    held: Tuple[str, ...]     # lock-ish `with` contexts held here


@dataclass
class CreateSite:
    """An obligation minted: ``name = SomethingFuture(...)``."""

    module: str
    func: str
    fkey: str
    lineno: int
    var: str
    factory: str
    used: bool = False        # referenced after creation (handed off)


@dataclass
class OrderViol:
    """Accounting recorded after the future already resolved."""

    module: str
    func: str
    lineno: int               # the late accounting call
    acct: str
    resolve_lineno: int
    resolve_base: str


@dataclass
class DoubleSettle:
    """Two unconditional terminal resolves of one base on one path."""

    module: str
    func: str
    lineno: int
    base: str
    first_lineno: int


@dataclass
class FutureMethod:
    """A future class's ``_set_result`` / ``_set_exception``."""

    module: str
    cls: str
    name: str
    lineno: int
    guarded: bool             # has a first-wins early-exit


@dataclass
class FuncFacts:
    """Per-function settlement facts (keyed like lockgraph)."""

    fkey: str
    module: str
    simple: str
    lineno: int
    broad_handler: bool = False   # any except Exception/BaseException
    resolves: int = 0


@dataclass
class AllowEntry:
    module: str
    lineno: int
    check: str
    reason: str
    used: bool = False


@dataclass
class SettlementModel:
    """Everything :func:`analyze_settlement`'s checks consume."""

    resolves: List[ResolveSite] = field(default_factory=list)
    creations: List[CreateSite] = field(default_factory=list)
    order_viols: List[OrderViol] = field(default_factory=list)
    doubles: List[DoubleSettle] = field(default_factory=list)
    future_methods: List[FutureMethod] = field(default_factory=list)
    funcs: Dict[str, FuncFacts] = field(default_factory=dict)
    allows: List[AllowEntry] = field(default_factory=list)
    #: The PR-15 concurrency model: spawn sites + thread-root
    #: fixpoint (``func_roots``) — how obligations handed across
    #: threads are followed.
    lock_model: Optional[ConcurrencyModel] = None


def _dotted(node) -> str:
    """Best-effort dotted rendering of an expression."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{_dotted(node.value)}.{node.attr}"
    if isinstance(node, ast.Call):
        return f"{_dotted(node.func)}()"
    if isinstance(node, ast.Subscript):
        return f"{_dotted(node.value)}[...]"
    return node.__class__.__name__.lower()


def _lockish(expr) -> Optional[str]:
    """Dotted name when a ``with`` context looks like a lock."""
    base = expr
    if isinstance(base, ast.Call):      # with self._lock: vs lock()
        base = base.func
    name = _dotted(base)
    last = name.rsplit(".", 1)[-1].lower()
    if "lock" in last or "cond" in last or "mutex" in last:
        return name
    return None


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [_dotted(e) for e in t.elts]
    else:
        names = [_dotted(t)]
    return any(n.rsplit(".", 1)[-1] in ("Exception", "BaseException")
               for n in names)


def _walk_no_fn(node):
    """ast.walk that does not descend into nested function/class
    definitions (their bodies are scanned as functions of their
    own)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


# ---------------------------------------------------------------------- #
# scanner
# ---------------------------------------------------------------------- #
class _ModScanner:
    def __init__(self, module: str, tree: ast.Module, source: str,
                 model: SettlementModel):
        self.module = module
        self.tree = tree
        self.model = model
        for i, line in enumerate(source.splitlines(), start=1):
            m = _ALLOW_RE.search(line)
            if m:
                model.allows.append(AllowEntry(
                    module, i, m.group(1), m.group(2).strip()))

    def fkey(self, cls: Optional[str], name: str) -> str:
        return ".".join(x for x in (self.module, cls, name) if x)

    def scan(self):
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                self._scan_fn(node, cls=None)
            elif isinstance(node, ast.ClassDef):
                self._scan_class(node)

    def _scan_class(self, cls: ast.ClassDef):
        methods = {n.name: n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        # A "future class": defines BOTH terminal settle methods —
        # each must carry a first-wins early-exit guard.
        if TERMINAL_ATTRS <= set(methods):
            for name in sorted(TERMINAL_ATTRS):
                fn = methods[name]
                guarded = any(
                    isinstance(n, ast.If)
                    and any(isinstance(s, (ast.Return, ast.Raise))
                            for s in n.body)
                    for n in _walk_no_fn(fn))
                self.model.future_methods.append(FutureMethod(
                    self.module, cls.name, name, fn.lineno, guarded))
        for fn in methods.values():
            self._scan_fn(fn, cls=cls.name)

    def _scan_fn(self, fn, cls: Optional[str]):
        key = self.fkey(cls, fn.name)
        facts = FuncFacts(fkey=key, module=self.module,
                          simple=fn.name, lineno=fn.lineno)
        self.model.funcs[key] = facts
        _FnWalker(self, fn, cls, facts).run()
        # Nested defs (worker.main's closures) are functions of
        # their own — same keying as lockgraph, so the thread-root
        # fixpoint lines up.
        for node in fn.body:
            self._walk_nested(node, cls)

    def _walk_nested(self, node, cls):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._scan_fn(node, cls=cls)
            return
        for child in ast.iter_child_nodes(node):
            self._walk_nested(child, cls)


class _FnWalker:
    """Statement-ordered walk of ONE function body: resolve sites
    with their held locks, unconditional resolve→accounting ordering,
    unconditional double settles, obligation creations, and broad
    exception backstops."""

    def __init__(self, sc: _ModScanner, fn, cls: Optional[str],
                 facts: FuncFacts):
        self.sc = sc
        self.fn = fn
        self.cls = cls
        self.facts = facts
        self.creations: List[CreateSite] = []

    def run(self):
        self._suite(self.fn.body, held=())
        # Orphans: a minted future never referenced again in this
        # function was neither discharged nor handed off.
        names = [n.id for n in _walk_no_fn(self.fn)
                 if isinstance(n, ast.Name)]
        for c in self.creations:
            c.used = names.count(c.var) > 1
            self.sc.model.creations.append(c)

    # -- statements ----------------------------------------------------- #
    def _suite(self, stmts, held) -> List[ResolveSite]:
        """Walk one suite; returns the resolves that execute
        UNCONDITIONALLY in it (With bodies are transparent;
        If/For/While/Try bodies are not — their resolves are
        conditional from the suite's point of view)."""
        settled: List[ResolveSite] = []
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if settled:
                self._late_accounting(stmt, settled)
            if isinstance(stmt, ast.Try):
                for h in stmt.handlers:
                    if _is_broad_handler(h):
                        self.facts.broad_handler = True
                    self._suite(h.body, held)
                self._suite(stmt.body, held)
                self._suite(stmt.orelse, held)
                settled.extend(self._suite(stmt.finalbody, held))
            elif isinstance(stmt, (ast.If, ast.For, ast.While)):
                self._suite(stmt.body, held)
                self._suite(stmt.orelse, held)
            elif isinstance(stmt, ast.With):
                locks = tuple(x for x in
                              (_lockish(i.context_expr)
                               for i in stmt.items) if x)
                settled.extend(
                    self._suite(stmt.body, held + locks))
            else:
                settled.extend(self._plain(stmt, held, settled))
        return settled

    def _plain(self, stmt, held, settled) -> List[ResolveSite]:
        out: List[ResolveSite] = []
        for node in _walk_no_fn(stmt):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) \
                    and f.attr in RESOLVE_ATTRS:
                site = ResolveSite(
                    module=self.sc.module, func=self.fn.name,
                    fkey=self.facts.fkey, lineno=node.lineno,
                    base=_dotted(f.value), attr=f.attr, held=held)
                self.sc.model.resolves.append(site)
                self.facts.resolves += 1
                if f.attr in TERMINAL_ATTRS:
                    for prev in settled + out:
                        if prev.base == site.base \
                                and prev.attr in TERMINAL_ATTRS:
                            self.sc.model.doubles.append(DoubleSettle(
                                self.sc.module, self.fn.name,
                                node.lineno, site.base,
                                prev.lineno))
                            break
                out.append(site)
            elif isinstance(f, (ast.Name, ast.Attribute)):
                name = f.id if isinstance(f, ast.Name) else f.attr
                if name.endswith("Future") \
                        and isinstance(stmt, ast.Assign) \
                        and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name) \
                        and stmt.value is node:
                    self.creations.append(CreateSite(
                        module=self.sc.module, func=self.fn.name,
                        fkey=self.facts.fkey, lineno=node.lineno,
                        var=stmt.targets[0].id, factory=name))
        return out

    def _late_accounting(self, stmt, settled: List[ResolveSite]):
        for node in _walk_no_fn(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ACCOUNTING_ATTRS:
                first = settled[0]
                self.sc.model.order_viols.append(OrderViol(
                    module=self.sc.module, func=self.fn.name,
                    lineno=node.lineno, acct=node.func.attr,
                    resolve_lineno=first.lineno,
                    resolve_base=first.base))


def scan_settlement(root: Optional[str] = None) -> SettlementModel:
    """Scan a package tree (default: ``multigrad_tpu``'s own) into a
    :class:`SettlementModel`.  Also runs :func:`~multigrad_tpu
    .analysis.lockgraph.scan_package` over the same tree — the PR-15
    thread-root fixpoint is how resolves are attributed to the
    threads that run them."""
    import os
    if root is None:
        root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
    model = SettlementModel()
    model.lock_model = scan_package(root)
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d != "__pycache__"]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, root)
            module = rel[:-3].replace(os.sep, ".")
            if module.endswith(".__init__"):
                module = module[:-len(".__init__")]
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
            _ModScanner(module, tree, source, model).scan()
    return model


# ---------------------------------------------------------------------- #
# allowlist
# ---------------------------------------------------------------------- #
class _Allowlist:
    """In-code ``# settle-ok: <check> <why>`` suppressions, indexed
    by (module, lineno) AND (module, lineno+1) so an annotation on
    the line above its anchor counts too."""

    def __init__(self, entries: List[AllowEntry]):
        self.entries = entries
        self.index: Dict[Tuple[str, int, str], AllowEntry] = {}
        for e in entries:
            self.index[(e.module, e.lineno, e.check)] = e
            self.index.setdefault(
                (e.module, e.lineno + 1, e.check), e)

    def suppress(self, module: str, lineno: int, check: str) -> bool:
        e = self.index.get((module, lineno, check))
        if e is not None and e.reason:
            e.used = True
            return True
        return False

    def verify(self) -> List[Finding]:
        out = []
        for e in self.entries:
            where = _where(e.module, e.lineno)
            if e.check not in SETTLE_CHECK_IDS:
                out.append(Finding(
                    "settle-allowlist", ERROR,
                    f"settle-ok names unknown check {e.check!r} "
                    f"(known: {', '.join(SETTLE_CHECK_IDS)})",
                    program=_PROGRAM, where=where))
            elif not e.reason:
                out.append(Finding(
                    "settle-allowlist", ERROR,
                    f"settle-ok for {e.check!r} has no "
                    "justification — the allowlist contract is an "
                    "explained exception, not a mute button",
                    program=_PROGRAM, where=where))
            elif not e.used:
                out.append(Finding(
                    "settle-allowlist", WARNING,
                    f"stale settle-ok: no {e.check!r} finding is "
                    "anchored here anymore — remove the annotation",
                    program=_PROGRAM, where=where))
        return out


def _where(module: str, lineno: int, func: str = "") -> str:
    path = module.replace(".", "/") + ".py"
    return f"{path}:{lineno} ({func})" if func \
        else f"{path}:{lineno}"


# ---------------------------------------------------------------------- #
# checks
# ---------------------------------------------------------------------- #
def _check_orphan(model: SettlementModel,
                  allow: _Allowlist) -> List[Finding]:
    out = []
    for c in model.creations:
        if c.used:
            continue
        if allow.suppress(c.module, c.lineno, "settle-orphan"):
            continue
        out.append(Finding(
            "settle-orphan", ERROR,
            f"{c.factory}() creates an obligation in {c.var!r} that "
            "is never discharged or handed off — every path out of "
            "the owning scope must reach _set_result/_set_exception "
            "or pass the future on",
            program=_PROGRAM,
            where=_where(c.module, c.lineno, c.func)))
    return out


def _check_no_backstop(model: SettlementModel,
                       allow: _Allowlist) -> List[Finding]:
    """A thread root from whose call graph futures are settled must
    carry a broad exception backstop: the thread dying silently
    strands every obligation it held (the PR-16 stage-death shape).
    Thread attribution is the PR-15 root fixpoint — obligations
    handed across threads are followed, not just direct resolves."""
    lock_model = model.lock_model
    if lock_model is None:
        return []
    func_roots = lock_model.func_roots
    # Roots under which some scanned function discharges.
    settling_roots = set()
    for fkey, facts in model.funcs.items():
        if facts.resolves:
            settling_roots |= set(
                func_roots.get(fkey, frozenset()))
    settling_roots.discard(MAIN_ROOT)
    out = []
    for fkey in sorted(settling_roots):
        facts = model.funcs.get(fkey)
        if facts is None or facts.broad_handler:
            continue
        # Only flag actual thread roots (a function is its own root
        # exactly when something spawns it).
        if fkey not in func_roots.get(fkey, frozenset()):
            continue
        if allow.suppress(facts.module, facts.lineno,
                          "settle-no-backstop"):
            continue
        out.append(Finding(
            "settle-no-backstop", ERROR,
            f"thread body {facts.simple!r} settles futures (itself "
            "or via its callees) but has no broad except backstop — "
            "an escaping exception kills the thread and strands "
            "every obligation it held; wrap the body in "
            "try/except (Base)Exception that discharges or requeues",
            program=_PROGRAM,
            where=_where(facts.module, facts.lineno, facts.simple)))
    return out


def _check_root_after_resolve(model: SettlementModel,
                              allow: _Allowlist) -> List[Finding]:
    out = []
    for v in model.order_viols:
        if allow.suppress(v.module, v.lineno,
                          "settle-root-after-resolve"):
            continue
        out.append(Finding(
            "settle-root-after-resolve", ERROR,
            f"{v.acct}(...) runs after {v.resolve_base} already "
            f"resolved (line {v.resolve_lineno}) — trace roots and "
            "dispatch counters must land BEFORE the resolve, so a "
            "caller waking on result() sees a fully-accounted "
            "request",
            program=_PROGRAM,
            where=_where(v.module, v.lineno, v.func)))
    return out


def _check_under_lock(model: SettlementModel,
                      allow: _Allowlist) -> List[Finding]:
    out = []
    for s in model.resolves:
        if not s.held:
            continue
        if allow.suppress(s.module, s.lineno, "settle-under-lock"):
            continue
        out.append(Finding(
            "settle-under-lock", ERROR,
            f"{s.base}.{s.attr}(...) runs while holding "
            f"{', '.join(s.held)} — settling wakes waiters and runs "
            "caller callbacks; move the resolve outside the owning "
            "lock (collect under the lock, settle after)",
            program=_PROGRAM,
            where=_where(s.module, s.lineno, s.func)))
    return out


def _check_double(model: SettlementModel,
                  allow: _Allowlist) -> List[Finding]:
    out = []
    for d in model.doubles:
        if allow.suppress(d.module, d.lineno, "settle-double"):
            continue
        out.append(Finding(
            "settle-double", ERROR,
            f"{d.base} is settled twice unconditionally on the same "
            f"path (first at line {d.first_lineno}) — settlement is "
            "first-wins; the second resolve is dead at best and a "
            "clobbered result at worst",
            program=_PROGRAM,
            where=_where(d.module, d.lineno, d.func)))
    return out


def _check_first_wins(model: SettlementModel,
                      allow: _Allowlist) -> List[Finding]:
    out = []
    for m in model.future_methods:
        if m.guarded:
            continue
        if allow.suppress(m.module, m.lineno, "settle-first-wins"):
            continue
        out.append(Finding(
            "settle-first-wins", ERROR,
            f"{m.cls}.{m.name} has no first-wins guard — a late "
            "duplicate settle (a requeued request completing twice) "
            "clobbers the already-delivered outcome; early-return "
            "when the future is already settled",
            program=_PROGRAM,
            where=_where(m.module, m.lineno,
                         f"{m.cls}.{m.name}")))
    return out


_CHECK_FNS = {
    "settle-orphan": _check_orphan,
    "settle-no-backstop": _check_no_backstop,
    "settle-root-after-resolve": _check_root_after_resolve,
    "settle-under-lock": _check_under_lock,
    "settle-double": _check_double,
    "settle-first-wins": _check_first_wins,
}


def analyze_settlement(root: Optional[str] = None,
                       checks=None,
                       model: Optional[SettlementModel] = None
                       ) -> List[Finding]:
    """Run the settlement checks; a clean tree is the empty list.

    ``checks`` subsets :data:`SETTLE_CHECK_IDS`; by default every
    check runs and the allowlist is verified.  Pass a prebuilt
    ``model`` (from :func:`scan_settlement`) to amortize the scan.
    """
    if model is None:
        model = scan_settlement(root)
    allow = _Allowlist(model.allows)
    selected = list(_CHECK_FNS) if checks is None \
        else [c for c in checks if c in _CHECK_FNS]
    findings: List[Finding] = []
    for check in _CHECK_FNS:
        if check not in selected:
            continue
        findings.extend(_CHECK_FNS[check](model, allow))
    if checks is None or "settle-allowlist" in checks:
        findings.extend(allow.verify())
    return findings
