"""Jaxpr plumbing shared by every shard-safety check.

All checks operate on the SAME artifact: a :class:`jax.core.ClosedJaxpr`
obtained by tracing a model's compiled SPMD program abstractly
(:func:`trace_program` — ``jax.make_jaxpr`` over
``ShapeDtypeStruct``\\ s, the zero-FLOP trick
:mod:`multigrad_tpu.telemetry.comm` uses for traffic accounting).  This
module hides the jax-version-specific shape of that artifact:

* :func:`walk_eqns` yields every equation at every nesting depth
  (``pjit`` bodies, ``shard_map`` bodies, ``scan``/``while`` bodies,
  ``cond`` branches, custom-derivative sub-jaxprs, ...) together with
  its context path and its static execution multiplier (the product of
  enclosing ``scan`` trip counts) — the quantity that turns a
  per-call payload into a per-program-execution payload.
* :func:`collect_collectives` reduces a trace to its
  :class:`CollectiveSite` list — the communication footprint the
  comm-scaling check compares across catalog sizes.
* :func:`iter_consts` yields every closed-over constant baked into the
  program (outer jaxpr and every nested closed sub-jaxpr).

Byte accounting is shared with the runtime telemetry counter
(:func:`multigrad_tpu.telemetry.comm.leaf_nbytes`) so the static
analyzer and the trace-time :class:`~multigrad_tpu.telemetry.CommCounter`
can never disagree on what a payload weighs.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List, Tuple

import jax
import numpy as np

from ..telemetry.comm import leaf_nbytes

__all__ = ["CollectiveSite", "COLLECTIVE_PRIMS", "CALLBACK_PRIMS",
           "trace_program", "abstractify", "walk_eqns",
           "collect_collectives", "iter_consts", "eqn_source",
           "subjaxprs"]

# Primitives that move data across mesh axes (communication payload =
# sum of input aval bytes).  `pvary`/`pbroadcast` (vma-era type casts)
# move nothing and are deliberately absent.
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "all_gather", "all_to_all", "ppermute",
    "pgather", "reduce_scatter",
})

# Host-callback primitives (each one is a device->host round trip).
CALLBACK_PRIMS = frozenset({
    "debug_callback", "pure_callback", "io_callback",
})


def abstractify(x):
    """`x` as a ShapeDtypeStruct (passthrough for non-arrays/structs).

    Non-array leaves (python ints/floats used as static or weak-typed
    arguments) pass through unchanged — ``jax.make_jaxpr`` abstracts
    them itself.
    """
    if isinstance(x, jax.ShapeDtypeStruct) or x is None:
        return x
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
    return x


def trace_program(fn, *args) -> "jax.core.ClosedJaxpr":
    """Trace ``fn(*args)`` abstractly; zero FLOPs, no device execution.

    ``args`` may mix concrete arrays, ``ShapeDtypeStruct``\\ s (use
    :func:`abstractify` on real data), and arbitrary pytrees thereof.
    The returned ClosedJaxpr is the artifact every check walks.
    """
    return jax.make_jaxpr(fn)(*args)


def _as_jaxpr(obj):
    """The open ``Jaxpr`` behind a ClosedJaxpr/Jaxpr, else None."""
    if hasattr(obj, "eqns"):
        return obj
    inner = getattr(obj, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        return inner
    return None


def subjaxprs(eqn) -> List[Tuple[object, object]]:
    """All (sub_jaxpr, original_param_value) pairs of one equation.

    Covers every higher-order primitive generically: any eqn param
    that is (or contains, for tuple-valued params like ``cond``'s
    ``branches``) a Jaxpr/ClosedJaxpr is yielded.  New jax primitives
    with jaxpr-valued params are picked up automatically.
    """
    out = []
    for val in eqn.params.values():
        items = val if isinstance(val, (list, tuple)) else (val,)
        for item in items:
            if _as_jaxpr(item) is not None:
                out.append((item, val))
    return out


def eqn_source(eqn) -> str:
    """``file:line (function)`` of the frame that bound this equation.

    Best-effort: jax's own traceback summarization, which prefers
    user frames over library internals.  Empty when the eqn carries
    no source info (e.g. synthesized transpose eqns).
    """
    try:
        from jax._src import source_info_util
        src = source_info_util.summarize(eqn.source_info)
        return "" if src in ("<unknown>", None) else src
    except Exception:  # pragma: no cover - jax internals moved
        return ""


def walk_eqns(closed, _path=(), _mult=1) -> Iterator[tuple]:
    """Yield ``(eqn, path, mult)`` for every eqn at every depth.

    ``path`` is the tuple of enclosing higher-order primitive names
    (``("pjit", "shard_map", "scan")``); ``mult`` is the number of
    times the eqn executes per program call — the product of
    enclosing ``scan`` trip counts (``while`` bodies contribute ×1:
    their trip count is dynamic, but the path records the loop so
    callers can treat "inside a while" conservatively).
    """
    jaxpr = _as_jaxpr(closed)
    if jaxpr is None:
        return
    for eqn in jaxpr.eqns:
        yield eqn, _path, _mult
        name = eqn.primitive.name
        mult = _mult
        if name == "scan":
            length = eqn.params.get("length")
            if isinstance(length, (int, np.integer)):
                mult = _mult * int(length)
        for sub, _ in subjaxprs(eqn):
            yield from walk_eqns(sub, _path + (name,), mult)


@dataclass(frozen=True)
class CollectiveSite:
    """One collective primitive occurrence in a traced program."""

    op: str            # primitive name, e.g. "psum"
    nbytes: int        # payload bytes of ONE call (sum of input avals)
    mult: int          # static calls per program execution (scan trips)
    where: str         # source location, best effort
    path: str          # jaxpr nesting, e.g. "pjit/shard_map"
    axes: tuple = ()   # mesh axis names reduced/gathered over, when
    #                    recoverable from the eqn params (psum `axes`,
    #                    all_gather `axis_name`, ...) — what lets the
    #                    cost model split payload between the data
    #                    (fast) and replica (slow) axes of a 2-level
    #                    mesh.  Empty when the primitive carries no
    #                    axis names (or only positional axes).

    @property
    def executed_bytes(self) -> int:
        """Payload bytes per program execution (``nbytes * mult``)."""
        return self.nbytes * self.mult


def _eqn_payload(eqn) -> int:
    return sum(leaf_nbytes(v.aval) for v in eqn.invars
               if hasattr(v, "aval"))


def _eqn_axes(eqn) -> tuple:
    """Named mesh axes of one collective eqn, best effort."""
    params = eqn.params
    raw = params.get("axes", params.get("axis_name", ()))
    if raw is None:
        return ()
    if isinstance(raw, str):
        raw = (raw,)
    try:
        return tuple(a for a in raw if isinstance(a, str))
    except TypeError:
        return ()


def collect_collectives(closed) -> List[CollectiveSite]:
    """All collective sites of a traced program, in trace order.

    Trace order is deterministic for a fixed program structure, which
    is what lets the comm-scaling check pair sites positionally
    between two traces of the same program at different data sizes.
    """
    sites = []
    for eqn, path, mult in walk_eqns(closed):
        if eqn.primitive.name in COLLECTIVE_PRIMS:
            sites.append(CollectiveSite(
                op=eqn.primitive.name, nbytes=_eqn_payload(eqn),
                mult=mult, where=eqn_source(eqn),
                path="/".join(path), axes=_eqn_axes(eqn)))
    return sites


def iter_consts(closed, _path=()) -> Iterator[tuple]:
    """Yield ``(const, path)`` for every closed-over constant.

    Walks the outer ClosedJaxpr's consts and every nested closed
    sub-jaxpr's (``pjit`` bodies are where jit bakes captured arrays).
    """
    consts = getattr(closed, "consts", None) or ()
    for c in consts:
        yield c, "/".join(_path)
    jaxpr = _as_jaxpr(closed)
    if jaxpr is None:
        return
    for eqn in jaxpr.eqns:
        for sub, _ in subjaxprs(eqn):
            yield from iter_consts(sub, _path + (eqn.primitive.name,))
