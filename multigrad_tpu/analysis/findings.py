"""Structured results of the shard-safety checks.

Every check returns a list of :class:`Finding` — one per violated
invariant, never a bare string or an exception — so callers can
aggregate across programs and models, filter by severity, render a
human report (:func:`format_findings`) or machine-readable records
(:meth:`Finding.to_dict`), and gate CI on the result.  A clean program
is the empty list.
"""
from __future__ import annotations

from dataclasses import dataclass, field, asdict
from typing import List

__all__ = ["Finding", "ERROR", "WARNING", "format_findings"]

ERROR = "error"
WARNING = "warning"


@dataclass(frozen=True)
class Finding:
    """One violated invariant, located as precisely as the trace allows.

    Attributes
    ----------
    check : str
        Check id (e.g. ``"comm-scaling"``, ``"replication"``) — the
        registry key in :data:`multigrad_tpu.analysis.checks.CHECKS`.
    severity : str
        ``"error"`` (wrong answers or broken scaling claims) or
        ``"warning"`` (performance/hygiene hazards).
    message : str
        Human-readable statement of what is wrong and why it matters.
    program : str
        Label of the analyzed program (e.g. ``"SMFModel:loss_and_grad"``).
    where : str
        Source location of the offending equation (``file:line (fn)``),
        empty when the trace carries no user frame.
    path : str
        The equation's position in the jaxpr nesting
        (e.g. ``"pjit/shard_map/scan"``).
    """

    check: str
    severity: str
    message: str
    program: str = ""
    where: str = ""
    path: str = field(default="")

    def to_dict(self) -> dict:
        return asdict(self)

    def __str__(self) -> str:
        loc = f" [{self.where}]" if self.where else ""
        ctx = f" ({self.path})" if self.path else ""
        prog = f"{self.program}: " if self.program else ""
        return (f"{self.severity.upper()} {self.check}: "
                f"{prog}{self.message}{loc}{ctx}")


def format_findings(findings: List[Finding]) -> str:
    """Render findings as a numbered, severity-sorted report."""
    if not findings:
        return "clean: no findings"
    order = {ERROR: 0, WARNING: 1}
    ranked = sorted(findings,
                    key=lambda f: (order.get(f.severity, 2), f.check))
    lines = [f"{i + 1}. {f}" for i, f in enumerate(ranked)]
    n_err = sum(1 for f in findings if f.severity == ERROR)
    lines.append(f"-- {len(findings)} finding(s), {n_err} error(s)")
    return "\n".join(lines)
