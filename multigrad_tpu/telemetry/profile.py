"""Profiler capture + device-time attribution, scoped to a fit.

``bench.py`` can *time* a fit and :mod:`.comm` can *count* its
bytes; neither can say where the device time goes.  This module
wraps ``jax.profiler`` capture (via
:func:`multigrad_tpu.utils.profiling.trace`) around any block —
typically one warmed-up fit — and parses the perfetto trace into
per-op and per-program device-time buckets, folding in the tunnel
round-trip floor the way ``bench.py`` does (min over trivial
dispatch+fetch round trips, recorded as ``tunnel_rtt_ms`` so a
reader knows which kind of session produced the numbers)::

    from multigrad_tpu.telemetry import profiled_fit

    model.run_adam(guess, nsteps)                # warm-up/compile
    with profiled_fit(logger, nsteps=5000,
                      cost=model_cost(model, guess)) as prof:
        model.run_adam(guess + 0.01, nsteps=5000, progress=False)
    prof.record["per_step_us"]      # measured device time per step
    prof.record["roofline_frac"]    # vs the static cost model

The parsing core (:func:`summarize_device_trace`) is the machinery
``examples/roofline_trace.py`` grew for the roofline study, hoisted
here so every consumer (the example, this context manager, ad-hoc
triage) shares one filter set; the example now delegates to it.

A failed capture/parse (no device slices on an exotic backend, an
empty trace) is recorded on the result object (``prof.error``) and
in the emitted record instead of raised — profiling must never turn
a finished fit into an exception.
"""
from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import time
from collections import defaultdict
from typing import Optional

__all__ = ["profiled_fit", "FitProfile", "summarize_device_trace",
           "measure_rtt_floor"]


def measure_rtt_floor(reps: int = 10) -> float:
    """Dispatch+fetch round-trip floor, seconds (min over ``reps``).

    The same protocol as ``bench.py``'s ``measure_fetch_rtt``: min,
    not mean — the floor is the cost every measurement pays, and a
    mean polluted by one tunnel hiccup over-subtracts.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    f = jax.jit(lambda a: a + 1.0)
    np.asarray(f(jnp.float32(0.0)))           # compile outside
    best = float("inf")
    for i in range(reps):
        t0 = time.perf_counter()
        np.asarray(f(jnp.float32(i)))
        best = min(best, time.perf_counter() - t0)
    return best


def _is_container_slice(name: str) -> bool:
    """Container/bookkeeping slices that bracket (and would double
    count) the op slices they contain."""
    return (name.startswith("end: ") or "Execute" in name
            or name.split(".")[0] in ("while", "condition", "body",
                                      "call")
            or name.startswith("ThreadpoolListener")
            or name.startswith("TaskDispatcher"))


def summarize_device_trace(log_dir: str, top: int = 12) -> dict:
    """Parse a perfetto trace into device-time buckets.

    Returns ``{"total_us", "ops": [{"op", "us", "count", "frac"}...],
    "programs": {jit_name: {"us", "count"}}}``.  ``ops`` are the
    executed XLA op slices (fusions appear as single slices, so
    XLA's fusion decisions are visible by name), aggregated across
    the device tracks; ``programs`` buckets the ``jit_<name>``
    container slices — per-program attribution when several programs
    share a capture.

    On TPU the device is its own trace process; on CPU the op slices
    live on the XLA executor threads (``XLAPjRt`` pools on newer jax,
    ``tf_XLAEigen`` workers on older ones).  Raises
    ``FileNotFoundError`` when no perfetto file exists under
    ``log_dir`` and ``RuntimeError`` when the filters match nothing
    (empty capture / renamed backend tracks).
    """
    paths = glob.glob(os.path.join(
        log_dir, "**", "*.trace.json.gz"), recursive=True)
    if not paths:
        raise FileNotFoundError(
            f"no perfetto trace under {log_dir!r} — capture with "
            f"trace(..., perfetto=True) first")
    with gzip.open(sorted(paths)[-1], "rt") as f:
        payload = json.load(f)
    events = payload["traceEvents"] if isinstance(payload, dict) \
        else payload

    proc_names, thread_names = {}, {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            proc_names[e["pid"]] = e["args"].get("name", "")
        elif e.get("name") == "thread_name":
            thread_names[(e["pid"], e.get("tid"))] = \
                e["args"].get("name", "")

    def on_device(e):
        proc = proc_names.get(e.get("pid"), "")
        if "TPU" in proc or ("/device:" in proc and "CPU" not in proc):
            return True
        # CPU executor thread names vary by jax version AND by which
        # pool the thunk runtime picked this dispatch: "XLAPjRt"
        # pools on newer releases, "tf_XLAEigen" eigen workers on
        # older ones, "tf_XLATfrtCpuClient" client-executor threads
        # when ops run on the PJRT client pool (observed on 0.4.x —
        # captures alternate between Eigen and client threads run to
        # run).  The codegen pool is deliberately absent: its slices
        # are compile time, not execution.
        tname = thread_names.get((e.get("pid"), e.get("tid")), "")
        return ("XLAPjRt" in tname or "XLAEigen" in tname
                or "XLATfrtCpuClient" in tname)

    def bucket(keep_containers):
        agg = defaultdict(lambda: [0.0, 0])
        programs = defaultdict(lambda: [0.0, 0])
        total = 0.0
        for e in events:
            if e.get("ph") != "X" or not on_device(e):
                continue
            name = e.get("name", "?")
            dur = float(e.get("dur", 0.0))
            if name.startswith("jit_"):
                # Whole-program container slice: the per-program
                # bucket (excluded from the op totals it brackets).
                cur = programs[name.split(".")[0]]
                cur[0] += dur
                cur[1] += 1
                continue
            if not keep_containers and _is_container_slice(name):
                continue
            if keep_containers and (name.startswith("end: ")
                                    or "Execute" in name):
                continue
            agg[name][0] += dur
            agg[name][1] += 1
            total += dur
        return agg, programs, total

    # Strict pass first: named op slices only (fusions visible by
    # name).  The CPU backend sometimes runs the named fusions inline
    # off the executor threads and leaves only per-thunk "call.N" /
    # scan "while" brackets on them — the loose pass keeps those, so
    # a capture still attributes time (flagged via "filter").
    agg, programs, total = bucket(keep_containers=False)
    trace_filter = "ops"
    if total == 0.0:
        agg, programs, total = bucket(keep_containers=True)
        trace_filter = "loose"
    if total == 0.0:
        raise RuntimeError(
            "no device-track slices matched in the trace under "
            f"{log_dir!r}: either the capture recorded no device ops "
            "or the process/thread-name filters need updating for "
            "this backend")
    rows = sorted(((name, d, c) for name, (d, c) in agg.items()),
                  key=lambda r: -r[1])
    return {
        "total_us": round(total, 1),
        "filter": trace_filter,
        "ops": [{"op": name[:120], "us": round(d, 1), "count": c,
                 "frac": round(d / total, 4)}
                for name, d, c in rows[:top]],
        "programs": {name: {"us": round(d, 1), "count": c}
                     for name, (d, c) in sorted(
                         programs.items(), key=lambda kv: -kv[1][0])},
    }


class FitProfile:
    """Result object of :func:`profiled_fit` — populated at exit.

    Attributes: ``log_dir`` (the capture directory), ``record`` (the
    emitted ``profile`` telemetry record, also returned even without
    a logger), ``summary`` (the raw :func:`summarize_device_trace`
    output), ``error`` (capture/parse failure string, else None).
    """

    def __init__(self):
        self.log_dir: Optional[str] = None
        self.record: dict = {}
        self.summary: Optional[dict] = None
        self.error: Optional[str] = None


@contextlib.contextmanager
def profiled_fit(logger=None, name: str = "fit",
                 log_dir: Optional[str] = None,
                 nsteps: Optional[int] = None, cost=None,
                 rtt: bool = True, top: int = 12):
    """Capture a ``jax.profiler`` trace around a fit and attribute it.

    Parameters
    ----------
    logger : MetricsLogger, optional
        Destination of the ``profile`` record (None: the record is
        still built on the yielded :class:`FitProfile`).
    name : str
        Label carried in the record (``"fit"``, a bench config, ...).
    log_dir : str, optional
        Trace directory; default: a fresh private temp dir
        (:func:`multigrad_tpu.utils.profiling.trace`'s default).
    nsteps : int, optional
        Steps executed inside the block — enables ``per_step_us``.
    cost : ProgramCost, optional
        Static cost of one step (:func:`.costmodel.model_cost`);
        joins the measured per-step device time against the roofline
        prediction (``predicted_us`` / ``roofline_frac`` / ``bound``
        land in the record).  Requires ``nsteps``.
    rtt : bool
        Measure the dispatch round-trip floor before the capture and
        record it as ``tunnel_rtt_ms`` (bench.py's floor protocol) —
        the context every tunneled-TPU number needs.
    top : int
        Ops kept in the per-op table.

    Yields a :class:`FitProfile`; read ``.record`` after the block.
    Profile the *warmed-up* program: compilation inside the capture
    swamps the device-time buckets with host work.
    """
    from ..utils.profiling import trace

    prof = FitProfile()
    rtt_s = None
    if rtt:
        try:
            rtt_s = measure_rtt_floor()
        except Exception as e:              # backend not up yet
            prof.error = f"rtt probe failed: {e}"
    t0 = time.perf_counter()
    with trace(log_dir, perfetto=True) as d:
        prof.log_dir = d
        yield prof
    wall_s = time.perf_counter() - t0

    record = {"name": name, "wall_s": round(wall_s, 4)}
    if rtt_s is not None:
        record["tunnel_rtt_ms"] = round(rtt_s * 1e3, 3)
    if nsteps:
        record["nsteps"] = int(nsteps)
    try:
        summary = summarize_device_trace(d, top=top)
    except (FileNotFoundError, RuntimeError, ValueError, OSError) as e:
        prof.error = str(e)
        record["error"] = str(e)
    else:
        prof.summary = summary
        record["total_device_us"] = summary["total_us"]
        record["filter"] = summary["filter"]
        record["device_frac_of_wall"] = round(
            summary["total_us"] / (wall_s * 1e6), 4) if wall_s else None
        record["top_ops"] = summary["ops"]
        if summary["programs"]:
            record["programs"] = summary["programs"]
        if nsteps:
            per_step_us = summary["total_us"] / nsteps
            record["per_step_us"] = round(per_step_us, 2)
            if cost is not None:
                from .costmodel import roofline_record
                join = roofline_record(cost, per_step_us * 1e-6)
                record.update({
                    "predicted_us": round(join["predicted_s"] * 1e6, 2),
                    "roofline_frac": (round(join["roofline_frac"], 4)
                                      if join["roofline_frac"]
                                      is not None else None),
                    "bound": join["bound"],
                    "flops_per_step": join["flops"],
                    "transcendentals": join["transcendentals"],
                })
    prof.record = record
    if logger is not None:
        logger.log("profile", **record)
